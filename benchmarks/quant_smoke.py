"""graftquant smoke: int8 KV + quantized transfer end-to-end on CPU.

The contract, asserted in one short run (same body runs in tier-1 —
``tests/test_graftquant.py::test_quant_smoke_end_to_end``):

1. **Transcript equality**: the int8-KV engine's greedy streams
   (dense AND paged) are byte-identical to the model-dtype engine's
   at this geometry — measured, never assumed (int8 KV is not
   token-exact by construction; the full pinned matrix incl. spec
   decode and the socket fleet lives in ``tests/test_graftquant.py``).
2. **The residency claim**: ``per_slot_kv_bytes`` is THE shape x
   dtype product the quantized pool allocates (planner == allocator
   byte-for-byte at a live ledger), and at head_dim=64 — gpt_small's
   geometry — the per-slot KV ratio clears **1.8x** for bf16 caches
   and ~3.8x for f32, so a fixed budget holds >= 1.8x the requests.
3. **The quality audit**: the max-abs teacher-forced logit delta
   between the two cache representations is NONZERO (the pin is a
   real measurement, not a no-op) and inside the committed 5e-3.
4. **Quantized transfer**: a detached prefill leaves the wire seam
   already int8 + f32 scales at < 0.6x the model-dtype payload, and
   splices into a second quantized engine transcript-equal.

Run: ``make quant`` (or ``python benchmarks/quant_smoke.py``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_smoke():
    import jax.numpy as jnp
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        plan_capacity)
    from pytorch_multiprocessing_distributed_tpu.inference import (
        teacher_forced_logits)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        hbm as hbm_ledger)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, SlotPool, init_params)
    from pytorch_multiprocessing_distributed_tpu.serving.scheduler import (
        Request)

    model = models.GPT(vocab_size=61, max_seq_len=64, hidden_size=128,
                       num_layers=2, num_heads=2, mlp_dim=64,
                       attn_impl="xla")  # head_dim=64, gpt_small's
    params = init_params(model, 1)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 61, (n,)).tolist() for n in (3, 12, 7)]
    s_max = 32

    # ---- 1: transcript equality, dense and paged int8
    ref_eng = ServingEngine(model, params, max_slots=2, s_max=s_max,
                            min_bucket=8)
    ref = ref_eng.serve([(p, 6) for p in prompts])
    for tag, kw in (("dense", {}),
                    ("paged", {"kv_layout": "paged", "page_size": 8,
                               "num_pages": 9})):
        eng = ServingEngine(model, params, max_slots=2, s_max=s_max,
                            min_bucket=8, kv_dtype="int8", **kw)
        got = eng.serve([(p, 6) for p in prompts])
        for a, b, p in zip(got, ref, prompts):
            assert a.tokens == b.tokens, (
                f"int8 {tag} stream diverged (prompt len {len(p)}): "
                f"{a.tokens} vs {b.tokens}")
    print("quant smoke: int8 dense + paged transcripts byte-equal vs "
          "model-dtype engine OK")

    # ---- 2: the residency claim, byte-exact at a live ledger
    kv_model = SlotPool.per_slot_kv_bytes(model, s_max)
    kv_int8 = SlotPool.per_slot_kv_bytes(model, s_max, "int8")
    with hbm_ledger.scoped_ledger() as ledger:
        pool = SlotPool(model, 4, s_max, kv_dtype="int8")
        kv_entry = ledger.entries()["serving.kv_pool"]
    assert kv_entry[1] == 4 * kv_int8, (
        "quantized SlotPool bytes diverge from per_slot_kv_bytes")
    del pool
    # bf16 twin of the same geometry: the TPU headline ratio (byte
    # math only — per_slot_kv_bytes reads geometry, no allocation)
    bf16 = models.GPT(vocab_size=61, max_seq_len=64, hidden_size=128,
                      num_layers=2, num_heads=2, mlp_dim=64,
                      attn_impl="xla", dtype=jnp.bfloat16)
    r_bf16 = (SlotPool.per_slot_kv_bytes(bf16, s_max)
              / SlotPool.per_slot_kv_bytes(bf16, s_max, "int8"))
    r_f32 = kv_model / kv_int8
    assert r_bf16 >= 1.8, f"bf16 head_dim=64 ratio {r_bf16:.3f} < 1.8"
    assert r_f32 >= 3.5, f"f32 head_dim=64 ratio {r_f32:.3f} < 3.5"
    budget = 1 << 24
    dense_plan = plan_capacity(model, s_max, budget)
    quant_plan = plan_capacity(model, s_max, budget, kv_dtype="int8")
    assert quant_plan["max_slots"] >= 1.8 * dense_plan["max_slots"]
    print(f"quant smoke: KV/slot {kv_model} -> {kv_int8} B "
          f"(f32 {r_f32:.2f}x, bf16 {r_bf16:.2f}x), planner "
          f"{dense_plan['max_slots']} -> {quant_plan['max_slots']} "
          f"slots at a fixed budget OK")

    # ---- 3: quality audit — nonzero, bounded logit delta
    full = jnp.asarray(list(prompts[1]) + list(ref[1].tokens))[None, :]
    lg_ref = teacher_forced_logits(model, params, full,
                                   len(prompts[1]))
    lg_q = teacher_forced_logits(model, params, full, len(prompts[1]),
                                 kv_dtype="int8")
    delta = float(jnp.max(jnp.abs(lg_q - lg_ref)))
    assert 0.0 < delta < 5e-3, (
        f"teacher-forced logit delta {delta:.2e} outside (0, 5e-3)")
    print(f"quant smoke: max |logit delta| = {delta:.2e} "
          f"(nonzero, < 5e-3) OK")

    # ---- 4: quantized transfer — halved payload, transcript-equal
    sender = ServingEngine(model, params, max_slots=3, s_max=s_max,
                           min_bucket=8, kv_dtype="int8")
    recv = ServingEngine(model, params, max_slots=3, s_max=s_max,
                         min_bucket=8, kv_dtype="int8")
    reqs = [Request(p, 6, None) for p in prompts]
    for r in reqs:
        tok0, kb, vb, ks, vs = sender.prefill_detached_wire(r)
        assert kb.dtype == np.int8 and ks.dtype == np.float32
        full_bytes = kb.size * np.dtype(model.dtype).itemsize
        assert kb.nbytes + ks.nbytes < 0.6 * full_bytes, (
            "quantized transfer payload is not < 0.6x model-dtype")
        recv.admit_prefilled(r, tok0, kb, vb, k_scale=ks, v_scale=vs)
    list(recv.run())
    for r, b in zip(reqs, ref):
        assert list(r.tokens) == list(b.tokens), (
            "spliced quantized stream diverged")
    print("quant smoke: quantized PageTransfer < 0.6x payload, "
          "spliced streams transcript-equal OK")


if __name__ == "__main__":
    run_smoke()
    print("quant smoke OK")
