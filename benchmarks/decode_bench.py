"""Input-pipeline microbenchmark: serial vs thread-pool JPEG decode.

The reference hides decode cost behind ``num_workers=4`` loader processes
(``data.py:44-52``); :class:`FolderImageNet` uses a thread pool (Pillow
releases the GIL inside decode). This prints images/sec for
``num_workers`` in {0, 2, 4, 8} over a generated JPEG tree so the
speedup is measurable anywhere (VERDICT r1 item #4: >=3x serial).

Usage: python benchmarks/decode_bench.py [--n 256] [--size 224]
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_tree(root: str, n: int, size: int) -> None:
    from PIL import Image

    rng = np.random.default_rng(0)
    d = os.path.join(root, "train", "n00000000")
    os.makedirs(d, exist_ok=True)
    for i in range(n):
        arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        Image.fromarray(arr).save(os.path.join(d, f"img_{i}.jpeg"),
                                  quality=90)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", default=256, type=int, help="images in the tree")
    p.add_argument("--size", default=224, type=int, help="source image size")
    p.add_argument("--crop", default=224, type=int, help="output crop size")
    args = p.parse_args()

    from pytorch_multiprocessing_distributed_tpu.data.imagenet import (
        FolderImageNet)

    ncpu = os.cpu_count() or 1
    print(f"host cpus: {ncpu}" + (
        " — NOTE: thread-pool decode cannot beat serial on a 1-core host;"
        " run on a real TPU VM (96+ cores) for the meaningful number"
        if ncpu == 1 else ""
    ))
    with tempfile.TemporaryDirectory() as root:
        make_tree(root, args.n, args.size)
        idx = np.arange(args.n)
        results = {}
        for workers in (0, 2, 4, 8):
            ds = FolderImageNet(root, "train", image_size=args.crop,
                                num_workers=workers)
            ds.get(idx[:8], np.random.default_rng(0), True)  # warm pool
            t0 = time.perf_counter()
            ds.get(idx, np.random.default_rng(1), True)
            dt = time.perf_counter() - t0
            results[workers] = args.n / dt
            print(f"num_workers={workers}: {args.n / dt:8.1f} images/sec")
        print(f"speedup vs serial: "
              f"{results[max(results)] / results[0]:.2f}x "
              f"(best pool) / {results[4] / results[0]:.2f}x (4 workers)")


if __name__ == "__main__":
    main()
