"""Re-record every bench config's TPU baseline in one command.

Runs ``bench.py`` once per config (canonical settings), sequentially,
and stops early if the TPU backend is unavailable — the per-config
JSON lines stream to stdout and ``benchmarks/baseline_record.json``
updates via bench.py's own record logic (first valid canonical run per
metric writes it; a slope-estimator run replaces a legacy whole-window
record).

Grant windows on this environment are short and can close mid-run
(measured round 4: the pool dropped between two configs of one
invocation), so the run order is NEED-first: configs whose on-disk
record is missing or still carries the legacy whole-window estimator
run before configs that already have a valid slope record.
``--missing`` restricts the run to exactly those needy configs — the
shortest path to a complete record set when a grant appears.

Use after a measurement-methodology change or on new hardware:

    python benchmarks/record_baselines.py [--configs a b c] [--missing]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "baseline_record.json")


def legacy_metrics():
    """-> (legacy, rec): metric names PRESENT in the record but written
    under the pre-slope estimator, plus the record itself. Absent
    metrics are not in either — callers must also check ``m not in
    rec`` (see ``needs`` below)."""
    try:
        with open(RECORD) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        rec = {}
    return {
        m for m, v in rec.items()
        if not (isinstance(v, dict)
                and v.get("estimator") == "two_window_slope")
    }, rec


def main() -> int:
    sys.path.insert(0, REPO)
    from bench import CONFIGS, metric_for  # noqa: E402

    p = argparse.ArgumentParser()
    p.add_argument("--configs", nargs="+", default=sorted(CONFIGS),
                   choices=sorted(CONFIGS))
    p.add_argument("--missing", action="store_true",
                   help="only configs whose baseline record is absent or "
                        "legacy (pre-slope-estimator)")
    p.add_argument("--settle", type=float, default=20.0,
                   help="seconds between configs (the single-tenant chip "
                        "needs the previous client's teardown to finish "
                        "before the next probe)")
    args = p.parse_args()

    legacy, rec = legacy_metrics()

    def needs(config):
        m = metric_for(config)[0]
        return m not in rec or m in legacy

    configs = [c for c in args.configs if not args.missing or needs(c)]
    # need-first: a closing grant window should cost the LEAST needed
    # config, not the most
    configs.sort(key=lambda c: (not needs(c), c))
    if not configs:
        print("all requested configs already have slope-estimator "
              "records; nothing to do", file=sys.stderr)
        return 0
    print(f"run order: {configs}", file=sys.stderr, flush=True)

    rc = 0
    prev_platform = None
    for k, config in enumerate(configs):
        # teardown-settle exists for the single-tenant chip. Skip it
        # only when the previous run is KNOWN to have fallen back to
        # CPU; a TPU run, or an error line whose platform is unknown
        # (the crash may have happened after TPU init), still settles.
        if k and prev_platform != "cpu":
            time.sleep(args.settle)
        prev_platform = None
        print(f"=== {config}", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"),
                 "--config", config],
                cwd=REPO, capture_output=True, text=True, timeout=1800,
            )
        except subprocess.TimeoutExpired as e:
            tail = (e.stderr or "")[-2000:] if isinstance(e.stderr, str) else ""
            print(f"!! {config}: bench.py hung past 1800s — stopping "
                  f"(sick backend?)", file=sys.stderr)
            if tail:
                print(tail, file=sys.stderr)
            return 2
        lines = proc.stdout.strip().splitlines() if proc.stdout else []
        line = lines[-1] if lines else ""
        print(line, flush=True)
        try:
            result = json.loads(line)
        except json.JSONDecodeError:
            print(f"!! {config}: no JSON line (rc={proc.returncode})",
                  file=sys.stderr)
            print(proc.stderr[-2000:], file=sys.stderr)
            return 1
        # platform BEFORE the error check: a sanity-gate failure line
        # still carries extra.platform="tpu" (the run held the chip)
        extra = result.get("extra", {})
        prev_platform = extra.get("platform")
        # an ERROR line is a per-config failure: record it, keep going
        if "error" in result:
            print(f"!! {config}: {result['error']}", file=sys.stderr)
            rc = 3
            continue
        if extra.get("platform") != "tpu":
            print(
                f"!! {config} fell back to {extra.get('platform')} "
                f"({extra.get('backend_note')}) — stopping: baselines "
                "must be TPU numbers",
                file=sys.stderr,
            )
            return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
