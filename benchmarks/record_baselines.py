"""Re-record every bench config's TPU baseline in one command.

Runs ``bench.py`` once per config (canonical settings), sequentially,
and stops early if the TPU backend is unavailable — the per-config
JSON lines stream to stdout and ``benchmarks/baseline_record.json``
updates via bench.py's own record logic (first valid canonical run per
metric writes it; a slope-estimator run replaces a legacy whole-window
record).

Use after a measurement-methodology change or on new hardware:

    python benchmarks/record_baselines.py [--configs a b c]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    sys.path.insert(0, REPO)
    from bench import CONFIGS  # noqa: E402

    p = argparse.ArgumentParser()
    p.add_argument("--configs", nargs="+", default=sorted(CONFIGS),
                   choices=sorted(CONFIGS))
    args = p.parse_args()

    rc = 0
    for config in args.configs:
        print(f"=== {config}", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"),
                 "--config", config],
                cwd=REPO, capture_output=True, text=True, timeout=1800,
            )
        except subprocess.TimeoutExpired as e:
            tail = (e.stderr or "")[-2000:] if isinstance(e.stderr, str) else ""
            print(f"!! {config}: bench.py hung past 1800s — stopping "
                  f"(sick backend?)", file=sys.stderr)
            if tail:
                print(tail, file=sys.stderr)
            return 2
        lines = proc.stdout.strip().splitlines() if proc.stdout else []
        line = lines[-1] if lines else ""
        print(line, flush=True)
        try:
            result = json.loads(line)
        except json.JSONDecodeError:
            print(f"!! {config}: no JSON line (rc={proc.returncode})",
                  file=sys.stderr)
            print(proc.stderr[-2000:], file=sys.stderr)
            return 1
        # an ERROR line (fenced {metric, value, error} with no extra)
        # is a per-config failure: record it and keep going
        if "error" in result:
            print(f"!! {config}: {result['error']}", file=sys.stderr)
            rc = 3
            continue
        extra = result.get("extra", {})
        if extra.get("platform") != "tpu":
            print(
                f"!! {config} fell back to {extra.get('platform')} "
                f"({extra.get('backend_note')}) — stopping: baselines "
                "must be TPU numbers",
                file=sys.stderr,
            )
            return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
