"""Per-op TPU time breakdown for a bench config via jax.profiler.

Answers "where does the step time actually go" (the question behind the
resnet50 MFU gap: 0.29 vs 0.46+ for resnet18/152 on the same chip,
``benchmarks/baseline_record.json``). Traces a few steady-state steps
of the EXACT program ``bench.py`` times, then parses the raw
``*.xplane.pb`` with the tensorflow-bundled proto (no tensorboard UI in
this environment) and aggregates device-plane event durations by op
name and by HLO category.

Run (on chip):  python benchmarks/profile_step.py --config resnet50_imagenet
Artifacts:      benchmarks/profile_<config>.json  (top ops + categories)
"""

import argparse
import collections
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import benchmarks._common as _common  # noqa: E402  (platform guard)


def parse_xplanes(trace_dir):
    """-> [(plane_name, line_name, event_name, hlo_category,
    total_ps, count), ...] aggregated per (plane, line, op)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir}")
    rows = []
    for path in paths:
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}

            def stat_value(s, plane=plane):
                # category strings arrive inline (str_value) or as a
                # reference into the plane's stat_metadata string table
                if s.str_value:
                    return s.str_value
                if s.ref_value:
                    return plane.stat_metadata[s.ref_value].name
                return None

            cat = {
                m_id: next(
                    (
                        stat_value(s)
                        for s in m.stats
                        if plane.stat_metadata[s.metadata_id].name
                        == "hlo_category"
                    ),
                    None,
                )
                for m_id, m in plane.event_metadata.items()
            }
            for line in plane.lines:
                agg = collections.defaultdict(lambda: [0, 0])
                for ev in line.events:
                    a = agg[ev.metadata_id]
                    a[0] += ev.duration_ps
                    a[1] += 1
                for m_id, (ps, n) in agg.items():
                    rows.append(
                        (
                            plane.name,
                            line.name,
                            meta.get(m_id, str(m_id)),
                            cat.get(m_id),
                            ps,
                            n,
                        )
                    )
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet50_imagenet")
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--batch_size", type=int, default=0)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--top", type=int, default=30)
    p.add_argument("--trace_dir", default="")
    args = p.parse_args()
    _common.apply_platform_env()

    import tempfile

    import jax

    import bench

    devices, note = bench.init_devices()
    if devices[0].platform != "tpu":
        print(json.dumps({"error": f"no TPU ({note}); profiling needs "
                                   "the real chip"}))
        return 1

    from pytorch_multiprocessing_distributed_tpu.utils.profiler import sync

    # the EXACT program bench.py times — one shared builder, no drift
    step, state, batch_args, _, batch = bench.build_workload(
        args.config, args.dtype, args.batch_size, devices,
        remat=args.remat,
    )
    step, costs = bench.compile_step(step, state, *batch_args)
    for _ in range(3):  # steady state before the trace
        state, m = step(state, *batch_args)
    sync(m)

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="pmdt_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(args.steps):
            state, m = step(state, *batch_args)
        sync(m)

    rows = parse_xplanes(trace_dir)
    # Device planes only; the busiest line is the op timeline.
    dev_rows = [r for r in rows if "TPU" in r[0] or "tpu" in r[0].lower()]
    if not dev_rows:
        dev_rows = rows
    by_line = collections.defaultdict(int)
    for _, line, _, _, ps, _ in dev_rows:
        by_line[line] += ps
    op_line = max(by_line, key=by_line.get)
    ops = [r for r in dev_rows if r[1] == op_line]
    total_ps = sum(r[4] for r in ops)
    ops.sort(key=lambda r: -r[4])
    cats = collections.defaultdict(int)
    for r in ops:
        cats[r[3] or "uncategorized"] += r[4]

    def fmt(r):
        _, _, name, c, ps, n = r
        return {
            "op": name[:120],
            "category": c,
            "ms_total": round(ps / 1e9, 3),
            "ms_per_step": round(ps / 1e9 / args.steps, 3),
            "pct": round(100 * ps / total_ps, 2),
            "count": n,
        }

    out = {
        "config": args.config,
        "global_batch": batch,
        "dtype": args.dtype,
        "remat": args.remat,
        "steps_traced": args.steps,
        "device_plane_line": op_line,
        "device_ms_per_step": round(total_ps / 1e9 / args.steps, 3),
        "flops_per_step": (costs or {}).get("flops"),
        "categories_pct": {
            k: round(100 * v / total_ps, 2)
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1])
        },
        "top_ops": [fmt(r) for r in ops[: args.top]],
        "trace_dir": trace_dir,
    }
    rec = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       f"profile_{args.config}.json")
    with open(rec, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: out[k] for k in
                      ("config", "device_ms_per_step", "categories_pct")}))
    print(f"# full breakdown -> {rec}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
