"""Autoregressive decode throughput (tokens/sec) for the GPT family.

Times :func:`..inference.generate` — KV-cached, one jitted program,
``lax.scan`` decode loop — at a few (prompt, new-tokens) points.
Decode is bandwidth-bound (the cache re-read per token), the natural
complement to ``bench.py``'s compute-bound ``gpt_lm`` training number.

Run: ``python benchmarks/generate_bench.py [--model gpt_small]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import benchmarks._common as _common  # noqa: E402
from benchmarks._common import timeit  # noqa: E402


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt_small")
    p.add_argument("--batch", default=8, type=int)
    p.add_argument("--prompt", default=128, type=int)
    p.add_argument("--new_tokens", default="128,512", type=str)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--tp", default=1, type=int,
                   help="model-axis size for tensor-parallel decode "
                        "(heads + KV cache + vocab head sharded; 1 = "
                        "single-shard)")
    args = p.parse_args()

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.inference import (
        generate, shard_params_for_tp_decode)
    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model = models.get_model(
        args.model, dtype=dtype,
        attn_impl="xla" if platform != "tpu" else "flash")
    if platform != "tpu":
        args.batch, args.prompt = min(args.batch, 2), min(args.prompt, 16)
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        0, model.vocab_size, (args.batch, args.prompt)))
    params = model.init(jax.random.PRNGKey(0), prompt[:1])["params"]
    mesh = None
    if args.tp > 1:
        n_dev = len(jax.devices())
        if n_dev % args.tp:
            raise SystemExit(
                f"--tp {args.tp} does not divide {n_dev} devices "
                "(for a CPU run: XLA_FLAGS=--xla_force_host_platform_"
                "device_count=8 JAX_PLATFORMS=cpu)")
        mesh = make_mesh(n_dev // args.tp, args.tp)
        params = shard_params_for_tp_decode(params, mesh)
    print(f"# platform={platform} model={args.model} dtype={args.dtype} "
          f"b={args.batch} prompt={args.prompt} tp={args.tp}")

    for n in [int(x) for x in args.new_tokens.split(",")]:
        if platform != "tpu":
            n = min(n, 16)
        dt = timeit(
            lambda prompt, n=n: generate(
                model, params, prompt, max_new_tokens=n, mesh=mesh),
            (prompt,),
        )
        tps = args.batch * n / dt
        print(f"new={n:5d}  {dt * 1e3:9.2f} ms/call  "
              f"{tps:10.1f} tokens/sec  "
              f"({1e3 * dt / n:7.3f} ms/token/batch)", flush=True)


if __name__ == "__main__":
    main()
