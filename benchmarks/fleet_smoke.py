"""graftfleet smoke: a synthetic 2-rank run must produce the whole
cross-host observability surface, and every artifact must PARSE.

The ``make fleet`` target (and the tier-1 test that drives this module
in-process) runs two synthetic "ranks" over one in-process control
store (``MemStore`` — the same client surface the real C++ ``TCPStore``
serves), with rank 1 artificially slowed, then asserts end-to-end:

1. **merged per-rank timeline** — the :class:`FleetCollector` scrapes
   every rank's ``/events.json`` and emits ONE Chrome-trace object
   with a lane (pid) per rank, clock-aligned through the published
   monotonic-offset handshake; it must carry both ranks' lanes and
   valid spans;
2. **straggler report** — every rank stamps its arrival at each
   collective boundary; the report must NAME the injected-slow rank
   and carry its lag percentiles (and the per-boundary skew);
3. **goodput fraction on a live scrape** — each rank's
   ``/snapshot.json`` (stdlib ``http.server``, one real HTTP GET)
   must expose ``goodput_frac`` classified from its own spans, and
   the merged gauges must label it by rank with cross-rank
   percentiles.

Exit code 0 and one ``graftfleet smoke OK`` line = the fleet
observability stack is wired. Schema drift fails loudly here, before
a real incident needs the artifacts.

Run: ``python benchmarks/fleet_smoke.py`` (CPU-safe, jax-free:
threads as ranks, milliseconds of synthetic work).
"""

import argparse
import itertools
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROUNDS = 6
SLOW_RANK = 1
SLOW_S = 0.03
FAST_S = 0.002


def _span(scope, seq, name, cat, dur, host, rank, **attrs):
    """Record one retroactive span into a NON-armed per-rank scope
    (two ranks share this process, so the module-global arm — one
    rank per process in production — is driven directly here)."""
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        scope as graftscope)

    attrs = dict(attrs, host=host, rank=rank)
    scope.record(graftscope.Event(
        name, cat, "X", time.perf_counter() - dur, dur, 0,
        next(seq), attrs))


def _rank_workload(store, rank, scope, seq):
    """One rank's synthetic run: per round, a train window (the slow
    rank's is longer — IT is the straggler), a data-wait span, and an
    arrival stamp at the collective boundary."""
    from pytorch_multiprocessing_distributed_tpu.runtime import fleet

    monitor = fleet.FleetMonitor(store, f"host{rank}", rank, 2,
                                 run_uid="smoke")
    delay = SLOW_S if rank == SLOW_RANK else FAST_S
    for _ in range(ROUNDS):
        time.sleep(delay)
        _span(scope, seq, "train.window", "train", delay,
              f"host{rank}", rank)
        _span(scope, seq, "train.data", "train", delay * 0.1,
              f"host{rank}", rank)
        monitor.note_arrival("dist.gate")
    return monitor


def run() -> dict:
    """The smoke body; returns the parsed artifacts for the caller
    (the tier-1 test asserts on them in-process)."""
    from pytorch_multiprocessing_distributed_tpu.runtime import fleet
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        scope as graftscope)
    from pytorch_multiprocessing_distributed_tpu.runtime.store import (
        MemStore)

    store = MemStore()
    seq = itertools.count()
    scopes = {r: graftscope.Scope(keep=True) for r in (0, 1)}
    monitors = {}

    # the two "ranks" run concurrently (the real multi-process shape);
    # the slow one falls behind at every boundary
    def worker(rank):
        monitors[rank] = _rank_workload(store, rank, scopes[rank], seq)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads), "a rank hung"

    # each rank serves its snapshot + events live; goodput classified
    # from its OWN spans rides /snapshot.json
    servers = {}
    try:
        for rank in (0, 1):
            ledger = fleet.GoodputLedger()
            scope_r = scopes[rank]

            def snapshot_fn(ledger=ledger, scope_r=scope_r,
                            rank=rank):
                ledger.ingest(scope_r.events())
                snap = {"rank": rank,
                        "rounds_completed": ROUNDS}
                snap.update(ledger.gauges())
                return snap

            def events_fn(since=0, scope_r=scope_r):
                events, _ = scope_r.events_since(since)
                return [e.to_dict() for e in events]

            servers[rank] = graftscope.start_stats_server(
                snapshot_fn, port=0, events_fn=events_fn)
            address = (f"127.0.0.1:"
                       f"{servers[rank].server_address[1]}")
            monitors[rank].publish_endpoint(address)

        # one live scrape straight off a rank's HTTP endpoint (not
        # through the collector): the goodput gauge is THERE
        port0 = servers[0].server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port0}/snapshot.json") as resp:
            live_snap = json.loads(resp.read())

        collector = fleet.FleetCollector(store, run_uid="smoke")
        scraped = collector.scrape()
        gauges = collector.merged_gauges(
            {r: s["snapshot"] for r, s in scraped.items()})
        timeline = collector.merged_timeline(
            {r: s["events"] for r, s in scraped.items()},
            hosts={r: s["host"] for r, s in scraped.items()})
        report = collector.straggler_report()
    finally:
        for server in servers.values():
            server.shutdown()

    # ---- assert: merged timeline — one lane per rank, spans valid
    parsed = json.loads(json.dumps(timeline))  # schema must serialize
    lanes = {ev["pid"] for ev in parsed["traceEvents"]}
    assert lanes == {0, 1}, f"expected a lane per rank, got {lanes}"
    names = {ev["args"]["name"] for ev in parsed["traceEvents"]
             if ev["ph"] == "M"}
    assert names == {"rank 0 (host0)", "rank 1 (host1)"}, names
    spans = [ev for ev in parsed["traceEvents"] if ev["ph"] == "X"]
    assert len(spans) == 2 * 2 * ROUNDS, len(spans)
    assert all(ev["ts"] >= 0.0 and ev["dur"] >= 0.0 for ev in spans)

    # ---- assert: the straggler report NAMES the slow rank
    assert report["collectives"] == ROUNDS, report
    assert report["straggler_rank"] == SLOW_RANK, report
    assert report["by_rank"][SLOW_RANK]["lag_p50_s"] > 0.0
    assert report["straggler_lag_p95_s"] > 0.0
    assert report["by_name"]["dist.gate"]["slowest_rank"] == SLOW_RANK
    for q in ("skew_p50_s", "skew_p95_s", "skew_p99_s"):
        assert report[q] >= 0.0, (q, report)

    # ---- assert: goodput fraction on the live scrape + merged gauges
    assert 0.0 < live_snap["goodput_frac"] <= 1.0, live_snap
    assert live_snap["goodput_productive_s"] > 0.0
    merged_goodput = gauges["goodput_frac"]
    assert set(merged_goodput["by_rank"]) == {0, 1}
    assert 0.0 <= merged_goodput["p50"] <= 1.0
    assert gauges["rank"]["by_rank"] == {0: 0.0, 1: 1.0}

    return {"timeline": parsed, "report": report,
            "gauges": gauges, "live_snapshot": live_snap}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.parse_args(argv)
    out = run()
    report = out["report"]
    print(f"# straggler rank {report['straggler_rank']} "
          f"(lag p95 {report['straggler_lag_p95_s'] * 1e3:.1f} ms over "
          f"{report['collectives']} collectives), "
          f"goodput_frac={out['live_snapshot']['goodput_frac']:.3f}")
    print("graftfleet smoke OK")


if __name__ == "__main__":
    main()
