"""graftwire smoke: a router driving 2 REAL replica-server
subprocesses over localhost sockets must stream byte-identically to
the in-process fleet, meter the PageTransfer bytes it ships, and
survive a ``SIGKILL``\\ ed replica process — end to end.

The ``make wire`` target (and the slow tier-1 test that drives this
module in-process, ``test_wire_smoke_end_to_end``) spawns replica
servers as SUBPROCESSES (``python benchmarks/wire_smoke.py
--serve_replica ...`` — each builds the same tiny paged engine from
the same seed and prints its bound address), then asserts from a
router in THIS process:

1. **disaggregation over the wire** — a prefill + decode subprocess
   pair serves token-exact vs the in-process fleet baseline, every
   prompt's KV block crossing the wire as raw framed numpy
   (``router.transfer_bytes`` metered, and the process-wide
   ``wire_bytes_sent`` meter carried at least that payload), then
   drains cleanly: both children exit 0 on their own;
2. **SIGKILL → redelivery** — a both/both pair with WALs serves the
   same request set; mid-run the busiest replica's PROCESS is killed
   -9 (no drain, no goodbye frame) WITH a pipelined frame in flight
   (a ``step`` submitted, not yet completed — graftlink's hard
   case). The orphaned completion handle fails NAMED (``WireDead``),
   never hangs and never leaks; the router reaps the victim, reads
   its WAL from the router-known path (``hello`` published it; same
   host = shared filesystem), redelivers the unfinished requests to
   the peer under ORIGINAL uids — every stream still byte-exact, and
   the fleet ``tokens_generated`` merge dedups the replayed prefix
   to the unique token count.

Exit code 0 and one ``graftwire smoke OK`` line = the wire transport
stack is deployable. Run: ``python benchmarks/wire_smoke.py``
(CPU-runnable; tiny model, ~2 min — subprocesses pay the jax import).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_NEW = 6


def _tiny_model():
    from pytorch_multiprocessing_distributed_tpu import models

    return models.GPT(vocab_size=61, max_seq_len=64, hidden_size=32,
                      num_layers=2, num_heads=2, mlp_dim=64,
                      attn_impl="xla")


def _engine(journal=None):
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, init_params)

    model = _tiny_model()
    # seed 1 everywhere: parent baseline and every child build
    # bit-identical params, so byte-identity is a transport claim
    params = init_params(model, 1)
    return model, ServingEngine(
        model, params, max_slots=2, s_max=32, min_bucket=8,
        kv_layout="paged", page_size=8, retry_backoff_s=0.0,
        journal=journal)


def _prompts():
    import numpy as np

    rng = np.random.default_rng(0)
    return [rng.integers(0, 61, (int(rng.integers(4, 20)),)).tolist()
            for _ in range(6)]


# --------------------------------------------------------------- child

def serve_replica(args) -> int:
    """The subprocess body: one paged engine behind a ReplicaServer,
    address handed to the parent through ``--addr_file``, alive until
    the remote router drains it (or the parent kills -9)."""
    from pytorch_multiprocessing_distributed_tpu.runtime import heal
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ReplicaServer)

    journal = (heal.RequestJournal(args.journal) if args.journal
               else None)
    _, engine = _engine(journal)
    server = ReplicaServer(engine, rid=args.rid, role=args.role)
    server.start()
    tmp = args.addr_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(server.address)
    os.replace(tmp, args.addr_file)  # atomic: parent never reads half
    print(f"graftwire smoke replica {args.rid}: listening on "
          f"{server.address} (pid {os.getpid()})", flush=True)
    server.serve_forever()
    return 0


# -------------------------------------------------------------- parent

def _spawn(tmpdir, rid, role, journal=None):
    addr_file = os.path.join(tmpdir, f"addr_{rid}")
    cmd = [sys.executable, os.path.abspath(__file__),
           "--serve_replica", "--rid", rid, "--role", role,
           "--addr_file", addr_file]
    if journal:
        cmd += ["--journal", journal]
    proc = subprocess.Popen(cmd, cwd=REPO)
    return proc, addr_file


def _wait_addr(proc, addr_file, deadline_s=120.0):
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < deadline_s:
        if os.path.exists(addr_file):
            with open(addr_file) as f:
                return f.read().strip()
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica subprocess exited {proc.returncode} before "
                "publishing its address")
        time.sleep(0.1)
    raise RuntimeError(
        f"replica subprocess published no address within "
        f"{deadline_s}s ({addr_file})")


def _reap(procs, timeout_s=30.0):
    """Children must exit on their own after a drain; anything still
    alive past the deadline is a bug — killed loudly, never leaked."""
    leaked = []
    for proc in procs:
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            leaked.append(proc.pid)
            proc.kill()
            proc.wait()
    return leaked


def run_smoke(verbose: bool = True) -> dict:
    from pytorch_multiprocessing_distributed_tpu.runtime import wire
    from pytorch_multiprocessing_distributed_tpu.serving import (
        RemoteReplica, Router, ServingReplica)

    def note(msg):
        if verbose:
            print(msg, flush=True)

    prompts = _prompts()

    # ---- the byte-identity reference: the IN-PROCESS fleet
    base_router = Router([ServingReplica("a", _engine()[1]),
                          ServingReplica("b", _engine()[1])])
    ref = {f"u{i}": list(r.tokens) for i, r in enumerate(
        base_router.serve((p, MAX_NEW) for p in prompts))}
    total_unique = sum(len(t) for t in ref.values())
    note(f"baseline: {len(ref)} in-process fleet streams, "
         f"{total_unique} tokens")

    tmpdir = tempfile.mkdtemp(prefix="pmdt_wire_smoke_")
    out = {"killed": False, "redelivered": 0, "streams_ok": False}
    procs = []
    try:
        # ---- 1. prefill/decode split across REAL processes:
        # PageTransfer rides the wire, metered, then a clean drain
        pf, pf_addr = _spawn(tmpdir, "pf", "prefill")
        dc, dc_addr = _spawn(tmpdir, "dc", "decode")
        procs += [pf, dc]
        replicas = [RemoteReplica(_wait_addr(pf, pf_addr)),
                    RemoteReplica(_wait_addr(dc, dc_addr))]
        meter0 = wire.wire_meter()["wire_bytes_sent"]
        router = Router(replicas)
        served = router.serve([(p, MAX_NEW) for p in prompts])
        for i, rec in enumerate(served):
            assert rec.state == "done", (rec.state, rec.finish_reason)
            assert list(rec.tokens) == ref[f"u{i}"], (
                f"disaggregated stream {i} diverged from the "
                "in-process fleet over the wire")
        assert router.transfers_routed == len(prompts), (
            "every prompt should prefill remotely and transfer: "
            f"{router.transfers_routed}/{len(prompts)}")
        assert router.transfer_bytes > 0
        wire_sent = wire.wire_meter()["wire_bytes_sent"] - meter0
        assert wire_sent >= router.transfer_bytes, (
            "the wire meter missed the KV payload: "
            f"{wire_sent} < {router.transfer_bytes}")
        router.drain(None)
        leaked = _reap([pf, dc])
        assert not leaked, (
            f"drained replica processes failed to exit: {leaked}")
        out["transfers"] = router.transfers_routed
        out["transfer_bytes"] = router.transfer_bytes
        out["wire_bytes_sent"] = wire_sent
        note(f"disagg: {router.transfers_routed} PageTransfers, "
             f"{router.transfer_bytes} KV bytes over the wire "
             f"({wire_sent} framed bytes total); both processes "
             "drained and exited 0")

        # ---- 2. SIGKILL a replica PROCESS mid-run -> WAL redelivery
        wals = [os.path.join(tmpdir, f"wal{i}.jsonl") for i in range(2)]
        r0, a0 = _spawn(tmpdir, "r0", "both", journal=wals[0])
        r1, a1 = _spawn(tmpdir, "r1", "both", journal=wals[1])
        procs += [r0, r1]
        replicas = [RemoteReplica(_wait_addr(r0, a0)),
                    RemoteReplica(_wait_addr(r1, a1))]
        by_pid = {replicas[0].engine.pid: r0,
                  replicas[1].engine.pid: r1}
        router = Router(replicas)
        for i, p in enumerate(prompts):
            router.submit(p, MAX_NEW, uid=f"u{i}")
        for _ in range(3):
            router.step()  # tokens into both WALs before the kill
        victim = max(replicas, key=lambda r: r.in_flight)
        assert victim.in_flight > 0
        victim_proc = by_pid[victim.engine.pid]
        # graftlink: kill with a PIPELINED frame in flight — a step
        # submitted but not completed. The completion handle must
        # fail NAMED (WireDead), never hang and never leak, and the
        # WAL must still redeliver token-exact afterwards.
        from pytorch_multiprocessing_distributed_tpu.runtime.wire \
            import WireDead
        handle = victim.step_submit()
        assert handle is not None, (
            "pipelined submit surface missing: RemoteReplica should "
            "default to a pipelined client")
        os.kill(victim_proc.pid, signal.SIGKILL)
        victim_proc.wait()
        out["killed"] = True
        try:
            victim.step_complete(handle)
            raise AssertionError(
                "completing a frame submitted to a SIGKILLed replica "
                "did not fail")
        except WireDead as e:
            out["handle_failed_named"] = f"WireDead: {e}"[:120]
        lane = victim._client._lanes.get("eng")
        assert lane is None or not lane._pending, (
            "pipelined completion handle leaked past the kill")
        note(f"kill: SIGKILLed replica {victim.rid} "
             f"(pid {victim_proc.pid}, {victim.in_flight} in flight, "
             "1 pipelined frame submitted-uncompleted -> failed "
             "named, not leaked)")
        deadline = time.perf_counter() + 120.0
        while router.in_flight:
            assert time.perf_counter() < deadline, (
                "post-kill serve did not converge")
            router.step()
        assert victim.reaped
        assert "WireDead" in victim.engine.health.reason
        assert router.requests_redelivered >= 1, (
            "the victim's WAL redelivered nothing")
        recs = router.records()
        for uid, want in ref.items():
            got = list(recs[uid].tokens)
            assert got == want, (
                f"stream {uid} diverged across the process kill: "
                f"{got} vs {want}")
        merged = router.merged_metrics()
        assert merged["tokens_generated"] == total_unique, (
            "redelivery dedup broke the fleet token count: "
            f"{merged['tokens_generated']} vs {total_unique} unique")
        out["redelivered"] = router.requests_redelivered
        out["replayed_tokens"] = router.redelivery_replayed_tokens
        out["merged_tokens"] = merged["tokens_generated"]
        out["streams_ok"] = True
        router.drain(None)
        leaked = _reap([r1])
        assert not leaked, (
            f"surviving replica failed to exit after drain: {leaked}")
        note(f"redelivery: {out['redelivered']} requests replayed "
             f"from the victim's WAL ({out['replayed_tokens']} "
             f"tokens deduped), all {len(ref)} streams byte-exact, "
             f"merged tokens {merged['tokens_generated']} == unique "
             f"{total_unique}; survivor drained and exited 0")
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--serve_replica", action="store_true",
                        help="internal: run as one replica-server "
                             "subprocess")
    parser.add_argument("--rid", default="r0")
    parser.add_argument("--role", default="both")
    parser.add_argument("--journal", default="")
    parser.add_argument("--addr_file", default="")
    args = parser.parse_args(argv)
    from pytorch_multiprocessing_distributed_tpu.utils.hostenv import (
        force_cpu_devices_from_env)

    force_cpu_devices_from_env()
    if args.serve_replica:
        if not args.addr_file:
            raise SystemExit("--serve_replica needs --addr_file")
        return serve_replica(args)
    out = run_smoke(verbose=True)
    print("graftwire smoke OK " + json.dumps(
        {k: out[k] for k in ("killed", "redelivered",
                             "transfer_bytes")}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
