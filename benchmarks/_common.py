"""Shared helpers for the standalone benchmark scripts."""

import os


def apply_platform_env() -> None:
    """Force ``JAX_PLATFORMS`` through ``jax.config`` before the first
    device query.

    In a fresh interpreter JAX honors the env var natively and this is
    a no-op. It exists because some PJRT plugin environments initialize
    their platform regardless of ``JAX_PLATFORMS`` once the backend
    comes up (bench.py's ``init_devices`` documents the same behavior),
    and a sick accelerator then hangs the whole script at the first
    ``jax.devices()``. Setting the config before any backend init is
    the reliable selector either way.

    (``decode_bench.py`` deliberately does not call this: it never
    imports jax — decode is pure PIL/numpy — so no backend can
    initialize.)
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
