"""Shared helpers for the standalone benchmark scripts."""

import os
import time


def timeit(fn, args, min_window=0.5):
    """ms-accurate adaptive timing: drain the queue, grow the window to
    >= ``min_window`` seconds, end every window on a real D2H readback
    (``utils.profiler.sync`` — same discipline as bench.py)."""
    from pytorch_multiprocessing_distributed_tpu.utils.profiler import sync

    out = fn(*args)
    sync(out)  # compile + drain
    n = 2
    while True:
        sync(fn(*args))  # drain boundary
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        sync(out)
        dt = time.perf_counter() - t0
        if dt >= min_window or n >= 10_000:
            return dt / n
        n = min(10_000, max(n + 1, int(n * 1.3 * min_window / dt)))


def enable_compile_cache() -> None:
    """Persistent XLA executable cache (~/.cache/pmdt_xla): on a short
    chip grant, the first script pays each compile once and every later
    harness invocation reuses it. PMDT_XLA_CACHE=off disables."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from pytorch_multiprocessing_distributed_tpu.utils.compile_cache import (
        enable_compilation_cache)

    enable_compilation_cache()


def apply_platform_env() -> None:
    """Force ``JAX_PLATFORMS`` through ``jax.config`` before the first
    device query.

    In a fresh interpreter JAX honors the env var natively and this is
    a no-op. It exists because some PJRT plugin environments initialize
    their platform regardless of ``JAX_PLATFORMS`` once the backend
    comes up (bench.py's ``init_devices`` documents the same behavior),
    and a sick accelerator then hangs the whole script at the first
    ``jax.devices()``. Setting the config before any backend init is
    the reliable selector either way.

    (``decode_bench.py`` deliberately does not call this: it never
    imports jax — decode is pure PIL/numpy — so no backend can
    initialize.)
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # every jax-using benchmark script also gets the persistent compile
    # cache — on a short chip grant the scripts share compiled programs
    enable_compile_cache()
