"""All-reduce bandwidth microbenchmark (BASELINE.json metric
"DDP-vs-psum allreduce BW").

Measures the bus bandwidth of ``lax.psum`` over the ``data`` mesh axis for
a sweep of payload sizes — the number to hold against NCCL's all-reduce
bandwidth on the reference's hardware. Bus bandwidth uses the standard
ring formula: ``bytes * 2 * (n-1)/n / time``.

Run:  python benchmarks/allreduce_bw.py [--sizes-mb 1 16 64 256]
Emits one JSON line per payload size.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import benchmarks._common as _common  # noqa: E402
from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh  # noqa: E402


def _bench(mesh, size_bytes: int, iters: int, body, metric: str,
           out_specs) -> dict:
    """Shared harness: same payload, warmup, timing, and bus-bandwidth
    formula for every all-reduce implementation under comparison."""
    n = mesh.shape["data"]
    elems = size_bytes // 4
    x = jnp.ones((n, elems), jnp.float32)

    f = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("data"),
                      out_specs=out_specs, check_vma=False)
    )
    out = f(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    bus_bw = size_bytes * 2 * (n - 1) / n / dt
    return {
        "metric": metric,
        "payload_mb": round(size_bytes / 2**20, 2),
        "devices": n,
        "time_ms": round(dt * 1e3, 3),
        "bus_gb_per_sec": round(bus_bw / 2**30, 2),
        "platform": jax.devices()[0].platform,
    }


def bench_psum(mesh, size_bytes: int, iters: int = 20) -> dict:
    return _bench(
        mesh, size_bytes, iters,
        lambda v: jax.lax.psum(v, "data"),  # per-shard [1, elems]
        "psum_allreduce_bus_bw", P(),
    )


def bench_ring(mesh, size_bytes: int, iters: int = 20) -> dict:
    """Same payload through the hand-built Pallas RDMA ring
    (:func:`...ops.pallas.ring_all_reduce`) — the NCCL-analogue number."""
    from pytorch_multiprocessing_distributed_tpu.ops.pallas import (
        ring_all_reduce,
    )

    return _bench(
        mesh, size_bytes, iters,
        lambda v: ring_all_reduce(v[0], "data")[None],
        "pallas_ring_allreduce_bus_bw", P("data"),
    )



def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--sizes-mb", nargs="+", type=float, default=[1, 16, 64])
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--ring", action="store_true",
                   help="also run the Pallas RDMA ring kernel")
    args = p.parse_args()
    mesh = make_mesh(jax.device_count())
    for mb in args.sizes_mb:
        print(json.dumps(bench_psum(mesh, int(mb * 2**20), args.iters)))
        if args.ring and mesh.shape["data"] > 1:
            print(json.dumps(bench_ring(mesh, int(mb * 2**20), args.iters)))


if __name__ == "__main__":
    main()
