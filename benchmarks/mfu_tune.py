"""MFU tuning sweep: bench configs x XLA flag sets x batch sizes.

Round-3 VERDICT next #2: resnet50_imagenet sits at mfu 0.29 while
resnet18/vit prove 0.46+ is reachable on the same chip — close the gap
with scheduler/fusion flags and batch geometry. Each combo runs
``bench.py`` in a FRESH subprocess (XLA flags only apply at backend
init), results are ranked by MFU and written to
``benchmarks/mfu_tune_results.json``. Flag sets that crash or regress
are recorded, not fatal.

Run (on chip): ``python benchmarks/mfu_tune.py --config resnet50_imagenet``
"""

import argparse
import itertools
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mfu_tune_results.json")

# Public XLA:TPU knobs worth sweeping for dense conv workloads. Applied
# ON TOP of whatever XLA_FLAGS the environment already carries.
FLAG_SETS = {
    "baseline": "",
    "lhs": "--xla_tpu_enable_latency_hiding_scheduler=true",
    "lhs+aggr": ("--xla_tpu_enable_latency_hiding_scheduler=true "
                 "--xla_tpu_aggressive_opt_barrier_removal=ENABLED"),
    "flash_fusion": "--xla_tpu_enable_flash_attention=true",
    "bf16_sum": "--xla_tpu_rwb_fusion=false",
}


def run_one(config, flags, batch, timeout):
    env = dict(os.environ)
    base = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = f"{base} {flags}".strip()
    # one probe attempt: the sweep runs many combos; a wedged backend
    # should fail the whole sweep fast, not 3x180s per combo
    env.setdefault("PMDT_BENCH_PROBE_ATTEMPTS", "1")
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--config", config]
    if batch:
        cmd += ["--batch_size", str(batch)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout {timeout}s"}
    lines = (proc.stdout or "").strip().splitlines()
    try:
        return json.loads(lines[-1])
    except (IndexError, json.JSONDecodeError):
        return {"error": f"no JSON (rc={proc.returncode}): "
                         f"{(proc.stderr or '')[-300:]}"}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet50_imagenet")
    p.add_argument("--batches", default="0,128,512", type=str,
                   help="0 = config default")
    p.add_argument("--flag_sets", default=",".join(FLAG_SETS), type=str)
    p.add_argument("--timeout", default=1200, type=int)
    args = p.parse_args()

    combos = list(itertools.product(
        [b for b in (int(x) for x in args.batches.split(","))],
        [f for f in args.flag_sets.split(",") if f in FLAG_SETS],
    ))
    results = []
    for batch, name in combos:
        r = run_one(args.config, FLAG_SETS[name], batch, args.timeout)
        row = {
            "flag_set": name,
            "flags": FLAG_SETS[name],
            "batch": batch or "default",
            "value": r.get("value"),
            "mfu": r.get("mfu"),
            "platform": r.get("extra", {}).get("platform"),
            "error": r.get("error"),
        }
        results.append(row)
        print(json.dumps(row), flush=True)
        if r.get("extra", {}).get("platform") == "cpu":
            print("# backend fell back to CPU — aborting sweep "
                  "(no TPU to tune)", file=sys.stderr)
            break

    ranked = sorted(
        (r for r in results if r.get("mfu")),
        key=lambda r: -r["mfu"],
    )
    out = {"config": args.config, "results": results,
           "best": ranked[0] if ranked else None}
    with open(RESULTS, "w") as f:
        json.dump(out, f, indent=2)
    if ranked:
        print(f"# best: {json.dumps(ranked[0])}", file=sys.stderr)


if __name__ == "__main__":
    main()
