"""graftmeter smoke: the capacity/efficiency surface must round-trip.

The ``make meter`` target (and the tier-1 test that drives this module
in-process) runs a short synthetic workload and asserts the whole
graftmeter stack end-to-end:

1. **costs.json freshness** — a cheap subset of the registry
   re-measures clean against the committed ``analysis/costs.json``
   budgets (the full 15-program gate is ``make check``; this is the
   fast canary that the comparison machinery itself works);
2. **planner round-trip** — ``plan_capacity``'s slot prediction is
   validated against a REAL CPU-backend :class:`SlotPool` allocation:
   predicted per-slot/pool bytes must match the arrays actually
   allocated within 0.5% (in practice they are byte-exact — the
   planner and the allocator share one shape x dtype product);
3. **live gauges** — a served engine with the HBM ledger armed
   exposes ``pmdt_hbm_*`` gauges (params, KV pool, per-bucket decode
   temps) on a live ``/metrics`` scrape, beside the serving meters;
4. **breakdown artifact** — ``utils.plotting.draw_hbm_breakdown``
   renders the ledger to a PNG (the plot_curves-parity artifact for
   memory).

Exit code 0 and ``graftmeter smoke OK`` = the capacity surface is
wired. Run: ``python benchmarks/meter_smoke.py [--out_dir DIR]``
(CPU-safe: gpt_tiny, a handful of requests, seconds of work — the
registry subset re-compile is the slowest part).
"""

import argparse
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import benchmarks._common as _common  # noqa: E402

# the cheap canary subset: the MoE expert-parallel layer + the
# all-reduce microprogram — sub-second compiles that still exercise
# build -> compile -> cost/memory -> compare end-to-end. The full
# 15-program registry is `make check`.
CANARY_PROGRAMS = ("collectives_all_reduce", "moe_mlp_ep")

# planner-vs-allocation tolerance, pinned by the tier-1 twin of this
# smoke: the planner and SlotPool share one shape x dtype product, so
# the match is byte-exact in practice; 0.5% absorbs a future dtype/
# padding surprise without letting a real drift (a forgotten cache
# copy doubles bytes) through.
PLAN_TOLERANCE = 0.005


def run(out_dir: str) -> dict:
    """The smoke body; returns the measured pieces for the caller
    (the tier-1 test asserts on them in-process)."""
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.analysis import meter
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        scope as graftscope)
    from pytorch_multiprocessing_distributed_tpu.runtime import hbm
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, init_params)
    from pytorch_multiprocessing_distributed_tpu.serving.kv_slots import (
        SlotPool)
    from pytorch_multiprocessing_distributed_tpu.serving.scheduler import (
        DONE)
    from pytorch_multiprocessing_distributed_tpu.utils.plotting import (
        draw_hbm_breakdown)

    os.makedirs(out_dir, exist_ok=True)

    # ---- 1. committed cost budgets: canary subset re-measures clean
    findings, cost_records, skipped = meter.run_meter(CANARY_PROGRAMS)
    assert not findings, ("graftmeter canary RED vs analysis/costs."
                          "json:\n" + "\n".join(f.render()
                                                for f in findings))
    assert not skipped, f"canary programs skipped: {skipped}"
    for name in CANARY_PROGRAMS:
        rec = cost_records[name]
        assert rec["flops"] and rec["flops"] > 0, (name, rec)
        assert rec["memory"]["peak_bytes"] > 0, (name, rec)

    # ---- 2. planner round-trip vs REAL CPU-backend allocation
    model = models.get_model("gpt_tiny", attn_impl="xla")
    params = init_params(model, 0)
    params_bytes = hbm.tree_nbytes(params)
    s_max = 32
    budget = params_bytes + 4 * (
        SlotPool.per_slot_kv_bytes(model, s_max)
        + SlotPool.per_slot_state_bytes()) + 1000
    plan = meter.plan_capacity(model, s_max, budget, params=params)
    assert plan["max_slots"] == 4, plan
    pool = SlotPool(model, plan["max_slots"], s_max)
    predicted = plan["max_slots"] * plan["per_slot_bytes"]
    actual = pool.hbm_bytes
    rel_err = abs(predicted - actual) / actual
    assert rel_err <= PLAN_TOLERANCE, (
        f"plan_capacity predicted {predicted} bytes for "
        f"{plan['max_slots']} slots, the pool actually allocated "
        f"{actual} ({100 * rel_err:.2f}% off > "
        f"{100 * PLAN_TOLERANCE}% tolerance)")

    # ---- 3. live gauges: served engine, ledger armed, one scrape
    with hbm.scoped_ledger() as ledger:
        engine = ServingEngine(model, params, max_slots=2, s_max=32,
                               min_bucket=8, decode_horizon=2)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, model.vocab_size,
                                (int(rng.integers(3, 12)),)).tolist()
                   for _ in range(4)]
        served = engine.serve([(p, 5) for p in prompts])
        assert all(r.state == DONE for r in served)

        def live_snapshot():
            snap = engine.metrics.snapshot()
            snap.update(ledger.snapshot())
            snap["hbm_per_slot_bytes"] = engine.pool.per_slot_bytes
            return snap

        server = graftscope.start_stats_server(live_snapshot, port=0,
                                               prefix="pmdt")
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                live_prom = resp.read().decode()
        finally:
            server.shutdown()
        breakdown = ledger.breakdown()
        snapshot = ledger.snapshot()

    # the ledger saw every allocation site: params, KV pool, slot
    # state, and at least one per-bucket decode-program temp
    assert "params" in breakdown and "kv" in breakdown, breakdown
    assert "serving.kv_pool" in breakdown["kv"], breakdown
    assert any(n.startswith("serving.decode_temp_w")
               for n in breakdown.get("temps", {})), breakdown
    samples = {}
    for line in live_prom.splitlines():
        if line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            samples[name] = float(value)
    hbm_gauges = {k: v for k, v in samples.items()
                  if k.startswith("pmdt_hbm_")}
    assert "pmdt_hbm_total_bytes" in hbm_gauges, sorted(samples)[:20]
    assert hbm_gauges["pmdt_hbm_total_bytes"] > params_bytes
    assert "pmdt_hbm_per_slot_bytes" in samples

    # ---- 4. breakdown artifact renders
    png = draw_hbm_breakdown(
        breakdown, os.path.join(out_dir, "hbm_breakdown.png"),
        title="meter smoke HBM", budget_bytes=2 * snapshot[
            "hbm_total_bytes"])
    assert os.path.getsize(png) > 0

    return {"plan": plan, "pool_bytes": actual,
            "cost_records": cost_records, "breakdown": breakdown,
            "snapshot": snapshot, "samples": samples, "png": png}


def main(argv=None):
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/pmdt_meter_smoke",
                   help="artifact directory (hbm_breakdown.png)")
    args = p.parse_args(argv)
    out = run(args.out_dir)
    plan = out["plan"]
    print(f"# plan: {plan['max_slots']} slots x "
          f"{plan['per_slot_bytes']} B/slot beside "
          f"{plan['params_bytes']} B params; pool allocated "
          f"{out['pool_bytes']} B; "
          f"hbm_total={out['snapshot']['hbm_total_bytes']} B; "
          f"artifacts in {args.out_dir}")
    print("graftmeter smoke OK")


if __name__ == "__main__":
    main()
