"""graftzero smoke: the sharded weight update proves its claims on a
2-shard CPU mesh in seconds.

Asserts, end to end (same body runs in tier-1 as
``tests/test_graftzero.py::test_zero_smoke_end_to_end``):

1. **budget flip** — the traced zero DP step moves gradients as exactly
   ONE reduce-scatter + ONE all-gather on the data axis and has ZERO
   grad-sized psums (the replicated twin has its per-leaf psums), with
   the NaN-guard's summed non-finite scalar psum still present;
2. **ledger delta** — ``hbm_opt_state_bytes`` with sharded moments is
   exactly the plan's per-chip shard bytes (~1/N of the replicated
   gauge), measured off the armed graftmeter ledger, and
   ``plan_capacity(zero_shards=N)`` quotes the SAME number byte-exactly;
3. **trajectory** — 3 sharded steps land bit-identical to 3 replicated
   steps (params AND gathered moments);
4. **round-trip** — gather-on-save: the sharded state's checkpoint
   restores into a replicated run and re-shards back, values intact.

Run via ``make zero`` (sets the 8-virtual-device CPU env; the smoke
uses 2 of them for the 2-shard mesh).
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu.analysis import ir
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        plan_capacity)
    from pytorch_multiprocessing_distributed_tpu.analysis.programs import (
        audit_tiny_gpt)
    from pytorch_multiprocessing_distributed_tpu.parallel import (
        make_mesh, zero as zero_mod)
    from pytorch_multiprocessing_distributed_tpu.runtime import hbm
    from pytorch_multiprocessing_distributed_tpu.train.checkpoint import (
        load_checkpoint, save_checkpoint)
    from pytorch_multiprocessing_distributed_tpu.train.lm import (
        create_lm_train_state, make_lm_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.train.step import (
        register_state_hbm, shard_batch)

    n = 2
    mesh = make_mesh(n, devices=jax.devices()[:n])
    # half the audit geometry: the smoke proves the contract, not the
    # model — compile time is the whole cost of this gate
    model = audit_tiny_gpt(dtype=jnp.float32, num_layers=1,
                           hidden_size=16, mlp_dim=32, num_heads=2)
    opt = sgd(learning_rate=0.1)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, model.vocab_size, (8, 16)))
    base = create_lm_train_state(model, jax.random.PRNGKey(0),
                                 toks[:2], opt)

    # ---- 1. budget flip -------------------------------------------
    s_zero = zero_mod.zeroify_state(jax.tree.map(jnp.array, base), mesh)
    step_zero = make_lm_train_step(model, opt, mesh, zero=True)
    step_rep = make_lm_train_step(model, opt, mesh)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), s_zero)
    atoks = jax.ShapeDtypeStruct(toks.shape, toks.dtype)
    closed = ir.trace(step_zero.jit_program(abstract), abstract, atoks)
    budget = ir.collective_budget(closed)
    pb = hbm.tree_nbytes(base.params)
    assert budget.get("reduce_scatter@data", {}).get("count") == 1, budget
    assert budget.get("all_gather@data", {}).get("count") == 1, budget
    assert sum(1 for s in ir.psum_sizes(closed) if s == pb) == 0
    assert max(ir.psum_sizes(closed)) <= 4  # loss/count/guard scalars
    rep_closed = ir.trace(step_rep, jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), base), atoks)
    rep_budget = ir.collective_budget(rep_closed)
    assert "reduce_scatter@data" not in rep_budget
    assert rep_budget["psum@data"]["count"] > budget.get(
        "psum@data", {}).get("count", 0)
    print(f"[zero_smoke] budget flip OK: zero={budget} "
          f"(replicated psums: {rep_budget['psum@data']['count']})")

    # ---- 2. ledger delta + planner agreement ----------------------
    plan = s_zero.opt_state.plan
    with hbm.scoped_ledger() as ledger:
        register_state_hbm(s_zero)
        sharded_bytes = ledger.snapshot()["hbm_opt_state_bytes"]
    with hbm.scoped_ledger() as ledger:
        register_state_hbm(base)
        replicated_bytes = ledger.snapshot()["hbm_opt_state_bytes"]
    # the ledger charges the whole opt_state: the sharded moment
    # buckets (the plan's exact per-chip bytes) plus the replicated
    # scalars (step count + init flag)
    scalar_bytes = (hbm.tree_nbytes(base.opt_state)
                    - hbm.tree_nbytes(base.opt_state.momentum))
    assert sharded_bytes == plan.shard_bytes + scalar_bytes, (
        sharded_bytes, plan.shard_bytes, scalar_bytes)
    assert sharded_bytes < replicated_bytes / (n - 0.5), (
        "sharded gauge is not ~1/N of replicated")
    cap = plan_capacity(model, 64, 1 << 30, params=base.params,
                        optimizer_moments=1, zero_shards=n)
    assert cap["opt_state_bytes"] == plan.shard_bytes, (
        cap["opt_state_bytes"], plan.shard_bytes)
    print(f"[zero_smoke] ledger delta OK: {replicated_bytes} -> "
          f"{sharded_bytes} bytes/chip (x{n} shards), planner agrees")

    # ---- 3. bit-identical trajectory ------------------------------
    s_rep = jax.tree.map(jnp.array, base)
    (tb,) = shard_batch((toks,), mesh)
    for _ in range(3):
        s_rep, m_rep = step_rep(s_rep, tb)
        s_zero, m_zero = step_zero(s_zero, tb)
    assert float(m_rep["loss"]) == float(m_zero["loss"])
    pr = jax.tree.leaves(jax.device_get(s_rep.params))
    pz = jax.tree.leaves(jax.device_get(s_zero.params))
    assert all(np.array_equal(a, b) for a, b in zip(pr, pz)), (
        "sharded trajectory diverged from replicated")
    inner = zero_mod.gather_opt_state(s_zero.opt_state, s_zero.params)
    mr = jax.tree.leaves(jax.device_get(s_rep.opt_state.momentum))
    mz = jax.tree.leaves(inner.momentum)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(mr, mz))
    print("[zero_smoke] 3-step trajectory bit-identical "
          "(params + gathered moments)")

    # ---- 4. gather-on-save round-trip ------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        save_checkpoint(tmp, s_zero, epoch=3)
        restored = load_checkpoint(
            os.path.join(tmp, "model_3.pth"),
            jax.tree.map(jnp.array, base))
    rz = jax.tree.leaves(jax.device_get(restored.opt_state.momentum))
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(mz, rz))
    rezero = zero_mod.zeroify_state(restored, mesh)
    for a, b in zip(jax.tree.leaves(rezero.opt_state.inner.momentum),
                    jax.tree.leaves(s_zero.opt_state.inner.momentum)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    print("[zero_smoke] checkpoint round-trip OK "
          "(sharded -> replicated artifact -> re-sharded)")

    print("zero smoke OK")


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(run())
