"""graftscope smoke: a synthetic engine run must emit every exporter's
artifact, and every artifact must PARSE.

The ``make scope`` target (and the tier-1 test that drives this module
in-process) runs a short synthetic serving workload with a full-log
scope armed, then asserts the whole observability surface end-to-end:

1. Chrome-trace JSON — loads as the Perfetto/chrome://tracing schema
   (required keys per event, microsecond timestamps from 0);
2. JSONL event log — every line parses; the per-request lifecycles are
   COMPLETE (each served uid has submit → admit → first_token → done,
   and a terminal ``request.timeline`` summary);
3. Prometheus text exposition — the same text ``serve_lm.py
   --stats_port`` serves at ``/metrics``; every sample line parses and
   the p50/p95/p99 TTFT gauges are present;
4. the stats endpoint itself — one live scrape of ``/metrics`` +
   ``/snapshot.json`` over stdlib ``http.server``.

Exit code 0 and a one-line ``graftscope smoke OK`` = the observability
stack is wired. Any schema drift fails loudly here, before a real
incident needs the artifacts.

Run: ``python benchmarks/scope_smoke.py [--out_dir DIR]``
(CPU-safe: gpt_tiny, a handful of requests, seconds of work).
"""

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import benchmarks._common as _common  # noqa: E402


def run(out_dir: str) -> dict:
    """The smoke body; returns the parsed artifacts for the caller
    (the tier-1 test asserts on them in-process)."""
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        scope as graftscope)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, init_params)

    os.makedirs(out_dir, exist_ok=True)
    trace_path = os.path.join(out_dir, "scope_trace.json")
    events_path = os.path.join(out_dir, "scope_events.jsonl")
    prom_path = os.path.join(out_dir, "scope_metrics.prom")

    model = models.get_model("gpt_tiny", attn_impl="xla")
    params = init_params(model, 0)
    engine = ServingEngine(model, params, max_slots=2, s_max=32,
                           min_bucket=8, decode_horizon=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size,
                            (int(rng.integers(3, 12)),)).tolist()
               for _ in range(4)]

    scope = graftscope.arm(graftscope.Scope(
        keep=True, flight_path=os.path.join(out_dir, "flight.jsonl")))
    try:
        served = engine.serve([(p, 5) for p in prompts])
        for request in served:
            graftscope.emit("request.timeline", cat="request",
                            **request.timeline())
        snap = engine.metrics.snapshot()
        events = scope.events()
        graftscope.write_chrome_trace(trace_path, events, t0=scope.t0)
        graftscope.write_jsonl(events_path, events)
        with open(prom_path, "w") as fh:
            fh.write(graftscope.prometheus_text(snap))

        # live endpoint: one scrape of both routes
        server = graftscope.start_stats_server(engine.metrics.snapshot,
                                               port=0)
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as resp:
                live_prom = resp.read().decode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/snapshot.json") as resp:
                live_snap = json.loads(resp.read())
        finally:
            server.shutdown()
    finally:
        graftscope.disarm()

    # ---- assert: Chrome-trace schema
    trace = json.load(open(trace_path))
    assert trace["traceEvents"], "empty trace"
    for ev in trace["traceEvents"]:
        missing = {"name", "ph", "ts", "pid", "tid"} - set(ev)
        assert not missing, f"trace event missing {missing}: {ev}"
        assert ev["ts"] >= 0.0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0.0

    # ---- assert: JSONL lifecycles are complete
    log = graftscope.events_from_jsonl(events_path)
    assert len(log) == len(events)
    uids = {e["uid"] for e in log if e["name"] == "request.timeline"}
    assert len(uids) == len(prompts), "a request has no timeline record"
    for name in ("request.submit", "request.admit",
                 "request.first_token", "request.done"):
        reached = {e["req"] for e in log if e["name"] == name}
        assert reached == uids, (
            f"lifecycle incomplete: {name} missing for "
            f"{uids - reached}")

    # ---- assert: Prometheus exposition parses, tails present
    def parse_prom(text):
        samples = {}
        for line in text.splitlines():
            if not line or line.startswith("#"):
                if line.startswith("#"):
                    assert line.startswith("# TYPE "), line
                continue
            name, value = line.rsplit(" ", 1)
            samples[name] = float(value)  # every sample line parses
        return samples

    samples = parse_prom(open(prom_path).read())
    for q in ("p50", "p95", "p99"):
        assert f"pmdt_serving_ttft_{q}_s" in samples, q
    assert samples["pmdt_serving_requests_completed"] == len(prompts)
    live = parse_prom(live_prom)
    assert live["pmdt_serving_requests_completed"] == len(prompts)
    assert live_snap["requests_completed"] == len(prompts)

    return {"trace": trace, "log": log, "samples": samples,
            "snapshot": snap}


def main(argv=None):
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--out_dir", default="/tmp/pmdt_scope_smoke",
                   help="artifact directory (trace/jsonl/prom)")
    args = p.parse_args(argv)
    out = run(args.out_dir)
    print(f"# {len(out['log'])} events, "
          f"ttft_p99_s={out['snapshot']['ttft_p99_s']:.4f}, "
          f"artifacts in {args.out_dir}")
    print("graftscope smoke OK")


if __name__ == "__main__":
    main()
