"""Flash-attention kernel vs the XLA dense path, fwd+bwd, on chip.

Round-2 VERDICT next #5 "done" gate: the Pallas kernel must beat the
dense ``softmax(QK^T)V`` XLA lowering at S >= 1024 on TPU. Timing uses
the same discipline as bench.py: drained queue, >=min_window windows,
real D2H readback boundaries (``utils.profiler.sync``).

Run: ``python benchmarks/attention_bench.py [--causal] [--dtype bf16]``
Prints one line per (impl, seq_len) with ms/iter and the speedup.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import benchmarks._common as _common  # noqa: E402
from benchmarks._common import timeit  # noqa: E402
from pytorch_multiprocessing_distributed_tpu.ops.pallas.flash_attention import (
    flash_attention)


def dense_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--causal", action="store_true")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--batch", default=4, type=int)
    p.add_argument("--heads", default=8, type=int)
    p.add_argument("--head_dim", default=64, type=int)
    p.add_argument("--seqs", default="1024,2048,4096", type=str)
    p.add_argument("--block_q", default=0, type=int,
                   help="0 = kernel default")
    p.add_argument("--block_k", default=0, type=int)
    args = p.parse_args()
    blocks = {}
    if args.block_q:
        blocks["block_q"] = args.block_q
    if args.block_k:
        blocks["block_k"] = args.block_k
    flash = lambda q, k, v, **kw: flash_attention(q, k, v, **kw, **blocks)  # noqa: E731

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    platform = jax.devices()[0].platform
    print(f"# platform={platform} dtype={args.dtype} causal={args.causal} "
          f"b={args.batch} h={args.heads} d={args.head_dim}")

    # Every timed function reduces to a SCALAR inside jit: the window
    # boundary is a D2H readback, and shipping the full [b,s,h,d] output
    # (megabytes) through the device tunnel would swamp the window with
    # transfer time. The added sum is noise next to the attention cost.
    def make_loss(attn):
        def loss(q, k, v):
            return jnp.sum(
                (attn(q, k, v) if not args.causal
                 else attn(q, k, v, causal=True)).astype(jnp.float32)
            )
        grad_fn = jax.grad(loss, argnums=(0, 1, 2))

        def scalar_bwd(q, k, v):
            g = grad_fn(q, k, v)
            return sum(jnp.sum(x.astype(jnp.float32)) for x in g)

        return jax.jit(scalar_bwd)

    def make_fwd(attn):
        return jax.jit(lambda q, k, v: jnp.sum(
            (attn(q, k, v) if not args.causal
             else attn(q, k, v, causal=True)).astype(jnp.float32)))

    fwd_flash = make_fwd(flash)
    fwd_dense = make_fwd(dense_attention)
    bwd_flash = make_loss(flash)
    bwd_dense = make_loss(dense_attention)

    for s in [int(x) for x in args.seqs.split(",")]:
        rng = np.random.default_rng(0)
        shape = (args.batch, s, args.heads, args.head_dim)
        q = jnp.asarray(rng.normal(size=shape), dtype)
        k = jnp.asarray(rng.normal(size=shape), dtype)
        v = jnp.asarray(rng.normal(size=shape), dtype)

        tf = timeit(fwd_flash, (q, k, v))
        td = timeit(fwd_dense, (q, k, v))
        bf = timeit(bwd_flash, (q, k, v))
        bd = timeit(bwd_dense, (q, k, v))
        print(f"S={s:5d}  fwd: flash {tf * 1e3:8.3f} ms  dense "
              f"{td * 1e3:8.3f} ms  ({td / tf:5.2f}x)   "
              f"fwd+bwd: flash {bf * 1e3:8.3f} ms  dense {bd * 1e3:8.3f} ms"
              f"  ({bd / bf:5.2f}x)", flush=True)


if __name__ == "__main__":
    main()
