"""graftscale smoke: the full elastic-fleet lifecycle against REAL
``--listen`` replica subprocesses — spawn-from-zero, a traffic burst
that scales the fleet UP, an idle plateau that drains it back DOWN,
then a rolling v1->v2 weight rollout under load — children reaped
loudly, zero failed requests, every stream pinned to exactly one
weight version.

The ``make scale`` target (and the slow tier-1 mirror,
``test_scale_smoke_script_end_to_end``) runs this module. The parent
holds the router + :class:`FleetAutoscaler` over a
:class:`ProcessReplicaSpawner`; every replica is a subprocess
(``python benchmarks/scale_smoke.py --serve_replica --tag vN ...``)
building a tiny engine from a per-version seed (v1 = seed 1, v2 =
seed 2 — so per-version byte-exactness is checkable against
in-parent reference engines) and publishing its bound address
atomically through ``--addr_file``.

Asserted end to end:

1. **spawn-from-zero** — the spawner boots the first replica; the
   autoscaler's min floor owns fleet existence, not a CLI constant;
2. **burst -> scale-up** — sustained ``FleetSaturated`` sheds grow
   the fleet (bounded by max), and every burst request completes;
3. **idle -> scale-down** — a quiet plateau drains the extra
   replicas (hysteresis: one change at a time, cooldown between),
   their CHILD PROCESSES exit (wait-then-kill, loudly);
4. **rolling rollout** — v2 replicas join + prewarm BEFORE v1
   replicas drain; zero failed requests, every stream byte-identical
   to a fixed single-version engine (v1 or v2, never a mix);
5. **no leaks** — at exit every spawned pid has been reaped; a
   leaked child is a test FAILURE, not a shrug.

Exit code 0 and one ``graftscale smoke OK`` line = the elastic fleet
is deployable. Run: ``python benchmarks/scale_smoke.py``
(CPU-runnable; tiny model, a few minutes — each subprocess pays the
jax import).
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_NEW = 4
SEEDS = {"v1": 1, "v2": 2}


def _tiny_model():
    from pytorch_multiprocessing_distributed_tpu import models

    return models.GPT(vocab_size=61, max_seq_len=64, hidden_size=32,
                      num_layers=2, num_heads=2, mlp_dim=64,
                      attn_impl="xla")


def _engine(tag="v1"):
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, init_params)

    model = _tiny_model()
    # per-version seeds: parent reference engines and every child of
    # that tag build bit-identical params, so per-version exactness
    # is a ROLLOUT claim, not a luck claim
    params = init_params(model, SEEDS[tag])
    return ServingEngine(model, params, max_slots=2, s_max=32,
                         min_bucket=8, retry_backoff_s=0.0)


def _prompts(n=6):
    import numpy as np

    rng = np.random.default_rng(0)
    return [rng.integers(0, 61, (int(rng.integers(4, 16)),)).tolist()
            for _ in range(n)]


# --------------------------------------------------------------- child

def serve_replica(args) -> int:
    """The subprocess body: one tagged engine behind a ReplicaServer,
    address handed to the parent through ``--addr_file``, alive until
    the autoscaler drains it."""
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ReplicaServer)

    engine = _engine(args.tag)
    server = ReplicaServer(engine, rid=args.rid, role=args.role)
    server.start()
    tmp = args.addr_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(server.address)
    os.replace(tmp, args.addr_file)  # atomic: parent never reads half
    print(f"graftscale smoke replica {args.rid} ({args.tag}): "
          f"listening on {server.address} (pid {os.getpid()})",
          flush=True)
    server.serve_forever()
    return 0


# -------------------------------------------------------------- parent

def run_smoke(verbose: bool = True) -> dict:
    from pytorch_multiprocessing_distributed_tpu.serving import (
        FleetAutoscaler, FleetSaturated, ProcessReplicaSpawner,
        RollingRollout, Router)

    def note(msg):
        if verbose:
            print(msg, flush=True)

    prompts = _prompts()
    # per-version byte-identity references, computed in-parent
    ref = {}
    for tag in ("v1", "v2"):
        engine = _engine(tag)
        out = engine.serve([(list(p), MAX_NEW) for p in prompts])
        ref[tag] = {tuple(prompts[i]): list(r.tokens)
                    for i, r in enumerate(out)}
    note(f"references: {len(prompts)} streams per version, "
         f"{sum(len(t) for t in ref['v1'].values())} tokens each")

    tmpdir = tempfile.mkdtemp(prefix="pmdt_scale_smoke_")

    def argv_for(rid, role, tag, addr_file):
        return [sys.executable, os.path.abspath(__file__),
                "--serve_replica", "--rid", rid, "--role", role,
                "--tag", tag or "v1", "--addr_file", addr_file]

    spawner = ProcessReplicaSpawner(argv_for, tmpdir,
                                    spawn_timeout_s=180.0)
    report = {"scale_ups": 0, "scale_downs": 0,
              "requests_failed": -1, "leaked_children": None}
    try:
        # ---- 1. spawn-from-zero: the spawner boots the first
        # replica; the scaler's min floor owns it from here
        t0 = time.perf_counter()
        boot = spawner.spawn("s0", "both", "v1")
        note(f"spawn-from-zero: s0 up in "
             f"{time.perf_counter() - t0:.1f}s (pid "
             f"{spawner.children['s0']})")
        router = Router([boot], max_pending=4)
        scaler = FleetAutoscaler(
            router, spawner, min_replicas=1, max_replicas=3,
            up_after=2, down_after=8, cooldown=4, model_tag="v1",
            rid_prefix="s", spawn_retries=1)
        scaler._seq = 1  # s0 is the boot replica
        timeline = []

        def pump():
            events = router.step()
            scaler.tick()
            timeline.append((scaler._tick, len(router.replicas)))
            return events

        # ---- 2. burst -> scale-up: sustained sheds past max_pending
        uid = [0]

        def offer(n):
            for _ in range(n):
                p = prompts[uid[0] % len(prompts)]
                try:
                    router.submit(list(p), MAX_NEW,
                                  uid=f"u{uid[0]}")
                    uid[0] += 1
                except FleetSaturated:
                    pass
        for _ in range(20):
            offer(2)
            pump()
        steps = 0
        while (router.in_flight or router.pending_depth) \
                and steps < 5000:
            pump()
            steps += 1
        assert scaler.scale_ups >= 1, (
            f"burst never scaled up: {scaler.signals()}")
        peak = max(n for _, n in timeline)
        note(f"burst: scaled up to {peak} replicas "
             f"({scaler.scale_ups} spawn(s)), {uid[0]} requests "
             "admitted and drained")

        # ---- 3. idle plateau -> scale-down to min, children exit
        for _ in range(40):
            pump()
        assert len(router.replicas) == 1, (
            f"idle fleet should drain to min: "
            f"{[r.rid for r in router.replicas]}")
        assert scaler.scale_downs >= 1
        assert len(spawner.children) == 1, (
            f"drained children must be reaped: {spawner.children}")
        note(f"idle: drained back to 1 replica "
             f"({scaler.scale_downs} retire(s)); drained children "
             "exited on their own")

        # ---- 4. rolling rollout v1 -> v2 under continuous load
        rollout = RollingRollout(scaler, "v2")
        target = uid[0] + 2 * len(prompts)
        for _ in range(5000):
            if uid[0] < target:
                offer(1)
            pump()
            rollout.tick()
            if (rollout.done and uid[0] >= target
                    and not router.in_flight
                    and not router.pending_depth):
                break
        assert rollout.done, "rollout did not converge"
        assert all(r.model_tag == "v2" for r in router.replicas)
        recs = router.records()
        failed = [u for u, r in recs.items() if r.state != "done"]
        assert not failed, f"rollout failed requests: {failed}"
        mixed = []
        for u, rec in recs.items():
            key = tuple(rec.prompt)
            want = (ref["v1"].get(key), ref["v2"].get(key))
            if list(rec.tokens) not in want:
                mixed.append(u)
        assert not mixed, (
            f"streams matching NEITHER version (mixed weights): "
            f"{mixed}")
        note(f"rollout: {len(rollout.replaced)} replica(s) replaced "
             f"v1->v2 in {rollout.duration_s:.1f}s under load; "
             f"{len(recs)} streams total, 0 failed, every stream "
             "byte-exact to one version")

        # ---- 5. teardown: drain the fleet, reap every child
        router.drain(None)
        scaler.shutdown()
        leaked = sorted(spawner.children)
        report.update({
            "scale_ups": scaler.scale_ups,
            "scale_downs": scaler.scale_downs,
            "spawn_failures": scaler.spawn_failures,
            "requests_total": len(recs),
            "requests_failed": len(failed),
            "peak_replicas": peak,
            "replicas_timeline": timeline[-200:],
            "events": [e.to_dict() for e in scaler.events],
            "rollout": {"duration_s": rollout.duration_s,
                        "replaced": rollout.replaced},
            "leaked_children": leaked,
        })
        assert not leaked, f"leaked replica children: {leaked}"
        note("teardown: every child reaped; no leaks")
    finally:
        spawner.shutdown(deadline_s=5.0)
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--serve_replica", action="store_true",
                        help="internal: run as one replica-server "
                             "subprocess")
    parser.add_argument("--rid", default="s0")
    parser.add_argument("--role", default="both")
    parser.add_argument("--tag", default="v1", choices=sorted(SEEDS))
    parser.add_argument("--addr_file", default="")
    parser.add_argument("--out", default="",
                        help="write the smoke report JSON here")
    args = parser.parse_args(argv)
    from pytorch_multiprocessing_distributed_tpu.utils.hostenv import (
        force_cpu_devices_from_env)

    force_cpu_devices_from_env()
    if args.serve_replica:
        if not args.addr_file:
            raise SystemExit("--serve_replica needs --addr_file")
        return serve_replica(args)
    report = run_smoke(verbose=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
    print("graftscale smoke OK " + json.dumps(
        {k: report[k] for k in ("scale_ups", "scale_downs",
                                "requests_failed",
                                "leaked_children")}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
