#!/bin/bash
# Background TPU liveness watcher. Probes the backend in short-lived
# subprocesses (a wedged probe cannot poison anything) and records the first
# success to .tpu_alive so long-running work can react.
# Usage: bash benchmarks/tpu_watch.sh [interval_seconds] [probe_timeout]
INTERVAL=${1:-120}
PROBE_TIMEOUT=${2:-150}
cd "$(dirname "$0")/.." || exit 1
rm -f .tpu_alive
while true; do
  if timeout "$PROBE_TIMEOUT" python -c "
import jax
ds = jax.devices()
assert ds and ds[0].platform != 'cpu', ds
print(len(ds), ds[0].device_kind)
" > .tpu_probe_out 2> .tpu_probe_err; then
    date -u +%FT%TZ > .tpu_alive
    cat .tpu_probe_out >> .tpu_alive
    echo "[tpu_watch] TPU alive: $(cat .tpu_probe_out)"
    exit 0
  fi
  echo "[tpu_watch] $(date -u +%FT%TZ) probe failed/hung; retrying in ${INTERVAL}s"
  sleep "$INTERVAL"
done
