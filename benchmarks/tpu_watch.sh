#!/bin/bash
# Background TPU liveness watcher — PATIENT probes (no kill).
#
# Evidence from this environment (see memory/VERDICT r3): killing a
# probe mid-bring-up is what wedges the axon tunnel for hours; a probe
# left alone either completes or errors out (observed ~25 min to an
# UNAVAILABLE). So each probe runs with NO timeout; failures back off
# and retry. First success writes .tpu_alive.
# Usage: bash benchmarks/tpu_watch.sh [retry_sleep_seconds]
SLEEP=${1:-180}
cd "$(dirname "$0")/.." || exit 1
rm -f .tpu_alive
while true; do
  if python -c "
import jax
ds = jax.devices()
assert ds and ds[0].platform != 'cpu', ds
print(len(ds), ds[0].device_kind)
" > .tpu_probe_out 2> .tpu_probe_err; then
    date -u +%FT%TZ > .tpu_alive
    cat .tpu_probe_out >> .tpu_alive
    echo "[tpu_watch] TPU alive: $(cat .tpu_probe_out)"
    exit 0
  fi
  echo "[tpu_watch] $(date -u +%FT%TZ) probe errored ($(tail -1 .tpu_probe_err | cut -c1-120)); retrying in ${SLEEP}s"
  sleep "$SLEEP"
done
