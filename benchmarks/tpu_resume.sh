#!/bin/bash
# Patient resumption of the TPU evidence capture after a tunnel wedge.
#
# The wedge pattern (seen round 3 and again round 4): a bench process
# killed mid-run wedges the axon tunnel; the NEXT process hangs in
# backend init for ~25 min (sometimes hours). Killing the hung process
# mid-bring-up deepens the wedge, so this script never kills anything —
# it probes the backend in short-lived throwaway subprocesses and only
# when a probe comes back healthy does it run the remaining capture
# steps, each under a generous timeout so one sick step can't block the
# rest.
#
# Usage: bash benchmarks/tpu_resume.sh [steps...]
#   steps default: resnet50 vit attn generate mfu convergence
set -u
cd "$(dirname "$0")/.." || exit 1
note() { echo "=== $* ($(date -u +%T))" >&2; }

probe() {
    timeout 240 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
x = jax.numpy.ones((128, 128))
jax.block_until_ready(x @ x)
EOF
}

run_step() {
    case "$1" in
    resnet50)
        note "baseline: resnet50_imagenet"
        timeout 2400 python benchmarks/record_baselines.py \
            --configs resnet50_imagenet ;;
    vit)
        note "baseline: vit_b16_imagenet"
        timeout 2400 python benchmarks/record_baselines.py \
            --configs vit_b16_imagenet ;;
    attn)
        note "attention bench"
        timeout 1800 python benchmarks/attention_bench.py \
            > benchmarks/attention_bench_tpu.txt 2>&1
        timeout 1800 python benchmarks/attention_bench.py --causal \
            >> benchmarks/attention_bench_tpu.txt 2>&1 ;;
    generate)
        note "generate bench"
        timeout 1800 python benchmarks/generate_bench.py \
            > benchmarks/generate_bench_tpu.txt 2>&1 ;;
    mfu)
        note "MFU tune sweep (resnet50 north star)"
        timeout 5400 python benchmarks/mfu_tune.py \
            --config resnet50_imagenet ;;
    convergence)
        note "convergence (framework on TPU vs torch CPU)"
        timeout 3600 python benchmarks/convergence.py \
            --epochs 8 --train_size 2048 ;;
    *)
        echo "unknown step: $1" >&2 ;;
    esac
}

steps=("${@:-}")
if [ -z "${steps[0]:-}" ]; then
    steps=(resnet50 vit attn generate mfu convergence)
fi

for step in "${steps[@]}"; do
    until probe; do
        note "backend unhealthy — sleeping 8 min before reprobe"
        sleep 480
    done
    run_step "$step"
done
note "done"
