"""Serving-engine throughput: offered load, sequence-length cost, and
decode-horizon dispatch overhead.

Three sweeps over the continuous-batching :class:`ServingEngine`:

1. **Load sweep** (``--sweep load``, the original): an open-loop
   request stream (arrival times fixed in advance — the load does NOT
   slow down when the server lags, which is what "heavy traffic"
   means) at several slot counts; per point: delivered tokens/sec,
   TTFT mean/p95 (submit -> first token, queueing included), queue
   wait p95, mean occupancy and queue depth.

2. **Length sweep** (``--sweep length``): short / long / mixed prompt
   length distributions, each served twice — length-bucketed decode
   (``decode_buckets=auto``) vs the full-``s_max`` window
   (``decode_buckets=off``, the pre-bucketing engine). The point of
   record: ``decode_step_avg_s`` tracking ``decode_window_avg``
   instead of staying flat at ``s_max`` — serving cost following the
   ACTIVE sequences. Chunked prefill is exercised on the long/mixed
   distributions (``--prefill_chunk``).

3. **Horizon sweep** (``--sweep horizon``): a slot-saturating,
   queue-empty steady state (requests == slots, long budgets) served
   at each ``--horizons`` value. The point of record: steady-state
   decode tokens/sec vs H, with ``host_syncs_per_token`` collapsing
   toward 1/H — the evidence that per-step dispatch + readback
   latency, not TPU compute, bounded the H=1 engine (on the CPU
   dispatch-bound config the speedup target is >= 2x at H=8).

4. **Chaos sweep** (``--sweep chaos``): the same steady state served
   fault-free and then under a BACKGROUND fault rate (a graftfault
   ``every=K`` rule injecting a transient dispatch error every K-th
   dispatch — every one recovered by bounded retry, with the
   post-fault H=1 cooldown engaged). The point of record: the
   throughput degradation budget — tok/s under faults vs fault-free,
   with the injected/retry/collapse counts printed beside it, so the
   cost of surviving a given fault rate is RECORDED, never silently
   eaten.

5. **Paged sweep** (``--sweep paged``, graftpage): dense slots vs the
   paged KV cache at a FIXED HBM budget (the dense pool's own KV
   bytes), across short/long/mixed length distributions and prefix-
   hit rates {0, 0.5, 0.9}. Two points of record per cell: (a)
   **resident requests at fixed HBM** — peak concurrent occupancy
   when the backlog saturates the pool, dense vs paged (the paged
   pool holds MORE requests in the same bytes because a request pins
   ``ceil(total / page_size)`` pages, not ``s_max`` columns; the
   planner's prediction is pinned byte-exact against the real
   allocation); (b) **TTFT under prefix hits** — closed-loop
   single-request serves at each hit rate, TTFT split hit vs miss (a
   full hit skips prefill entirely: state splice + at most one COW
   page fork). Paged streams are asserted token-exact vs dense.

6. **Spec sweep** (``--sweep spec``, graftspec): speculative decode —
   accepted-tokens/target-step, TTFT and decode tok/s at draft length
   k ∈ {0, 2, 4, 8} x draft source {self-draft n-gram, draft model}
   on REPETITIVE vs RANDOM prompt families. The repetitive family's
   target is briefly trained on the motif stream (a few seconds of
   SGD) so its greedy continuation genuinely continues the pattern —
   acceptance is then structural, not luck; the random family is the
   adversarial floor (acceptance ~0, and the adaptive
   ``pick_draft_k`` ladder collapses k so throughput holds). Points
   of record: ``spec_accepted_per_target_step`` > 1.0 on the
   repetitive config (more tokens per weight stream — THE speculative
   claim), accept_len p50/p95/p99 in the JSON, and k=0 reproducing
   the non-speculative engine exactly (no spec passes, same program
   ladder — disarmed costs nothing). Off-TPU the draft model is the
   target itself (structural full acceptance — the mode's smoke);
   on TPU pass ``--draft_model`` for a real small-drafts-big setup.

7. **Drain sweep** (``--sweep drain``, graftheal): the elastic-
   lifecycle latencies. Point one: **drain latency** — a loaded
   engine flips to DRAINING mid-serve (the SIGTERM path) and the
   clock runs until every in-flight request finished (admission
   closed throughout). Point two: **recovery time-to-first-token**
   after a supervised restart — an engine with a request-redelivery
   journal is abandoned mid-run (the crash shape), a fresh engine
   replays the WAL ON THE CLOCK (journal load + redelivery + prefill)
   until the first redelivered token lands, and the redelivered
   streams are asserted token-exact vs the pre-crash prefix. The
   recorded numbers are the two SLOs a replica router needs: how long
   a drain holds a slot hostage, and how long a restarted replica
   takes to resume visible progress.

8. **Fleet sweep** (``--sweep fleet``, graftroute): the
   disaggregated-fleet evidence. Point one: a 2-replica router's
   streams are BYTE-IDENTICAL to the single-engine baseline —
   aggregate tok/s vs one engine, per-replica ``goodput_frac`` with
   the straggler named, work steals counted. Point two:
   prefill/decode disaggregation (one prefill replica handing KV
   blocks to a decode replica over the host round-trip) is
   token-exact vs monolithic, transfer bytes per request recorded.
   Point three: one injected replica death mid-run — the dead
   replica's journal redelivers to the peer, every stream still
   byte-exact, fleet ``tokens_generated`` dedup-verified, and the
   **redelivery recovery TTFT** (death detection to the first
   redelivered token) wall-clocked.

9. **Wire sweep** (``--sweep wire``, graftwire): the socket-transport
   cost, measured against the in-process fleet it must be
   semantically identical to. Point one: the SAME 2-replica fleet
   served in-process and then over localhost sockets (thread-hosted
   ``ReplicaServer``\\ s — real TCP, zero subprocess noise): tok/s
   side by side with the **per-RPC overhead p50/p95** from the
   client's own call clock, streams asserted BYTE-IDENTICAL. Point
   two: prefill→decode disaggregation over the wire — the KV block
   rides as raw framed numpy, **transfer bytes/request** recorded at
   both layers (PageTransfer payload and the framed wire meter).
   Point three: a socket-level replica kill mid-run (the SIGKILL
   shape the smoke does to a real process) — WAL redelivery to the
   peer, **kill→recovery TTFT** wall-clocked, streams exact, fleet
   metrics dedup-verified.

10. **Autoscale sweep** (``--sweep autoscale``, graftscale): the
    elastic fleet under time-varying load. A **bursty** (square-wave)
    and a **diurnal** (ramp) arrival trace each drive the
    :class:`FleetAutoscaler` over a 1..3-replica in-process fleet —
    **replicas-over-time** (change-points), **shed rate**, and **TTFT
    p50/p99 across the scale events** per point, every admitted
    request asserted complete. Then a **rolling v1→v2 rollout** under
    steady load: wall-clock **rollout duration**, zero failed
    requests, every stream byte-exact to exactly one weight version.

11. **Quant sweep** (``--sweep quant``, graftquant): int8 KV + f32
    per-page-per-head scales vs model-dtype KV at **FIXED HBM**.
    Point one: the planner inversion in both modes, pinned byte-exact
    against real pools, with the per-slot KV byte ratio gated at its
    own geometry floor (>= **1.8x** wherever ``head_dim >= 64`` —
    every TPU registry model). Point two: ``run_point`` model-dtype
    at the budget's dense slot count vs int8 at the planned quantized
    count — resident requests and tok/s side by side at the same
    byte budget. Point three: greedy transcripts asserted EQUAL on a
    canonical subset and the max-abs teacher-forced **logit delta**
    vs the model-dtype cache recorded and gated (audited, not
    asserted away — int8 KV is not token-exact by construction).

``offered=inf`` is the closed-loop limit: every request submitted
up front, measuring peak engine throughput. CPU-runnable (shapes clamp
down off-TPU, same convention as ``generate_bench.py``), TPU-ready.
``--json_out`` records every point (plus the compiled window set per
engine) for the round's evidence JSON.

Run: ``python benchmarks/serving_bench.py [--model gpt_small]
[--sweep load,length,horizon] [--slots 2,4,8] [--offered inf,8]
[--horizons 1,4,8] [--json_out benchmarks/serving_bench_tpu.json]``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import benchmarks._common as _common  # noqa: E402


def _percentile(values, q):
    return float(np.percentile(np.asarray(values), q)) if values else 0.0


def run_point(model, params, prompts, new_tokens, slots, offered_rps,
              s_max, warmup=False, arm_plan=None, **engine_kwargs):
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        hbm as hbm_ledger)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        faults, fleet, life)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        scope as graftscope)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine)
    from pytorch_multiprocessing_distributed_tpu.utils.metrics import (
        ServingMetrics)

    # graftmeter: one fresh ledger per point, armed BEFORE the engine
    # so the pool/params registrations land — every sweep point then
    # records its resident HBM beside its throughput. Armed inside
    # the try: a failed engine construction must still disarm (a
    # stale process-wide ledger would silently absorb later points'
    # registrations).
    # graftfleet: one fresh full-log scope per point — the engine's
    # prefill/drain spans feed the point's goodput fraction.
    ledger = hbm_ledger.arm(hbm_ledger.HbmLedger())
    point_scope = graftscope.arm(graftscope.Scope(keep=True))
    # graftlife: a fresh ownership ledger per point — the leaked_*
    # numbers below must all be 0 (a bench point that strands slots
    # or pages is measuring a leak, not throughput)
    life_led = life.arm(life.OwnershipLedger())
    try:
        engine = ServingEngine(model, params, max_slots=slots,
                               s_max=s_max, **engine_kwargs)
        if arm_plan is not None:
            # chaos sweep: arm BEFORE the warm-up pass so the
            # degraded-mode programs (collapsed-horizon windows) also
            # compile before the clock; ``injected`` below counts the
            # measured window only
            faults.arm(arm_plan)
        if warmup:
            # steady-state sweeps: pay every compile before the clock,
            # then measure on fresh meters (the horizon sweep compiles
            # up to 2x the programs of H=1 — charging compiles to the
            # point would invert the comparison)
            engine.serve([(p, new_tokens) for p in prompts])
            engine.metrics = ServingMetrics()
        injected_base = (arm_plan.triggered() if arm_plan is not None
                         else 0)
        # arrival schedule: evenly spaced at the offered rate (inf =
        # all at t=0). Open loop — lateness accumulates if the engine
        # can't keep up
        arrivals = ([0.0] * len(prompts) if offered_rps == float("inf")
                    else [i / offered_rps for i in range(len(prompts))])
        t_start = time.perf_counter()
        pending = list(zip(prompts, arrivals))
        finished = []
        while pending or engine.in_flight:
            now = time.perf_counter() - t_start
            while pending and pending[0][1] <= now:
                prompt, _ = pending.pop(0)
                engine.submit(prompt, new_tokens)
            if engine.in_flight:
                for request, _, done in engine.step():
                    if done:
                        finished.append(request)
            elif pending:
                time.sleep(min(0.005, pending[0][1] - now))
    finally:
        if arm_plan is not None:
            faults.disarm()
        hbm_ledger.disarm()
        graftscope.disarm()
        life.disarm()
    wall = time.perf_counter() - t_start
    # graftfleet: goodput over the point's own timeline (engine
    # prefill + drain spans vs the point's wall); collective skew only
    # when a fleet monitor is armed (multi-rank run) — None-safe
    # off-TPU and single-host, never a fake number
    goodput = fleet.GoodputLedger.from_events(point_scope.events())
    goodput_frac = (round(goodput.gauges()["goodput_frac"], 4)
                    if goodput.wall_s > 0 else None)
    collective_skew_p95_s = None
    collective_straggler_rank = None
    monitor = fleet.active_fleet()
    if monitor is not None:
        report = fleet.FleetCollector(
            monitor.store, run_uid=monitor.run_uid,
            prefix=monitor.prefix).straggler_report()
        if report["collectives"]:
            collective_skew_p95_s = report["skew_p95_s"]
            collective_straggler_rank = report["straggler_rank"]
    ttfts = [r.first_token_time - r.submit_time for r in finished]
    waits = [r.admit_time - r.submit_time for r in finished]
    total_tokens = sum(len(r.tokens) for r in finished)
    snap = engine.metrics.snapshot()
    # graftmeter efficiency attribution: decode MFU charges the run's
    # total DISPATCHED scan steps (the horizon meter's sum — collapsed
    # H=1 dispatches in the chaos sweep's cooldowns count as 1, not
    # H_max) at the steady-state program's per-step static FLOPs; the
    # chip does that work regardless of occupancy, so this IS the
    # utilization (window variation across buckets is the remaining
    # approximation). Null off-TPU (no peak) — never a fake number.
    mfu = None
    decode_flops = None
    if engine.decode_programs:
        import bench

        w, h = max(engine.decode_programs, key=lambda p: (p[1], p[0]))
        decode_flops = engine.decode_program_analysis(w, h).get("flops")
        peak = bench.chip_peak_flops(jax.devices()[0])
        if decode_flops and peak and wall > 0:
            steps_dispatched = engine.metrics.horizon.sum
            mfu = round((decode_flops / h) * steps_dispatched
                        / wall / peak, 4)
    return {
        "hbm_resident_bytes": ledger.total_bytes,
        "hbm_per_slot_bytes": engine.pool.per_slot_bytes,
        # graftlife: the drained point must hold NOTHING (0s, pinned)
        "leaked_slots": life_led.live("slot"),
        "leaked_pages": life_led.live("page"),
        "leaked_threads": life_led.live("thread"),
        "decode_flops_per_dispatch": decode_flops,
        "mfu": mfu,
        # graftfleet: wall-time accounting + cross-rank attribution
        # for EVERY sweep point (None-safe single-host/off-TPU)
        "goodput_frac": goodput_frac,
        "collective_skew_p95_s": collective_skew_p95_s,
        "collective_straggler_rank": collective_straggler_rank,
        "completed": len(finished),
        "wall_s": wall,
        "tokens_per_sec": total_tokens / wall,
        "ttft_avg_ms": 1e3 * float(np.mean(ttfts)),
        # tail latencies for EVERY sweep point (graftscope): a change
        # that keeps the mean but breaks the p99 is bench-visible
        "ttft_p50_ms": 1e3 * _percentile(ttfts, 50),
        "ttft_p95_ms": 1e3 * _percentile(ttfts, 95),
        "ttft_p99_ms": 1e3 * _percentile(ttfts, 99),
        "queue_wait_p95_ms": 1e3 * _percentile(waits, 95),
        "queue_wait_p99_ms": 1e3 * _percentile(waits, 99),
        "decode_step_avg_s": snap["decode_step_avg_s"],
        "decode_step_p50_s": snap["decode_step_p50_s"],
        "decode_step_p95_s": snap["decode_step_p95_s"],
        "decode_step_p99_s": snap["decode_step_p99_s"],
        "decode_window_avg": snap["decode_window_avg"],
        "decode_tokens_per_sec": snap["decode_tokens_per_sec"],
        "decode_horizon_avg": snap["decode_horizon_avg"],
        "decode_dispatches": snap["decode_dispatches"],
        "host_syncs_per_token": snap["host_syncs_per_token"],
        "overlapped_dispatches": snap["overlapped_dispatches"],
        "occupancy_avg": engine.metrics.occupancy.avg,
        "occupancy_max": snap["occupancy_max"],
        "queue_depth_avg": engine.metrics.queue_depth.avg,
        "decode_compiles": engine.decode_step_compiles,
        "decode_windows": list(engine.decode_windows),
        "decode_programs": [list(p) for p in engine.decode_programs],
        "dispatch_retries": snap["dispatch_retries"],
        "requests_failed": snap["requests_failed"],
        "horizon_collapses": snap["horizon_collapses"],
        # graftspec telemetry (all zero when spec is disarmed)
        "spec_tokens_drafted": snap["spec_tokens_drafted"],
        "spec_tokens_accepted": snap["spec_tokens_accepted"],
        "spec_verify_passes": snap["spec_verify_passes"],
        "spec_accept_rate": snap["spec_accept_rate"],
        "spec_accepted_per_target_step":
            snap["spec_accepted_per_target_step"],
        "accept_len_p50": snap["accept_len_p50"],
        "accept_len_p95": snap["accept_len_p95"],
        "accept_len_p99": snap["accept_len_p99"],
        "spec_programs": [list(p) for p in engine.spec_programs],
        "injected": (arm_plan.triggered() - injected_base
                     if arm_plan is not None else 0),
    }


def _draw_lengths(rng, dist, n, lo, hi):
    """Prompt lengths for one distribution family. ``short`` exercises
    the small decode buckets, ``long`` pins near ``s_max``, ``mixed``
    interleaves both — the case where per-step bucketing (cost follows
    the longest ACTIVE sequence as long requests retire) shows up."""
    short = (max(1, lo), max(1, hi // 4))
    long_ = (max(1, (3 * hi) // 4), hi)
    if dist == "short":
        bands = [short] * n
    elif dist == "long":
        bands = [long_] * n
    else:  # mixed: alternate so both kinds are resident together
        bands = [short if i % 2 == 0 else long_ for i in range(n)]
    return [int(rng.integers(a, b + 1)) for a, b in bands]


def run_length_sweep(model, params, args, s_max, prompt_hi, rng):
    """short/long/mixed x (bucketed | full-window) grid; the JSON
    evidence that decode step time scales with the active bucket."""
    results = []
    chunk = args.prefill_chunk or None
    for dist in args.len_dist.split(","):
        lengths = _draw_lengths(rng, dist, args.requests,
                                prompt_hi // 8, prompt_hi)
        prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
                   for n in lengths]
        for label, buckets in (("auto", None), ("off", ())):
            r = run_point(model, params, prompts, args.new_tokens,
                          int(args.slots.split(",")[0]), float("inf"),
                          s_max, decode_buckets=buckets,
                          prefill_chunk=chunk)
            r.update(dist=dist, buckets=label,
                     prompt_len_min=min(lengths),
                     prompt_len_max=max(lengths),
                     prefill_chunk=chunk or 0)
            results.append(r)
            print(f"dist={dist:6s} buckets={label:4s}  "
                  f"{r['tokens_per_sec']:9.1f} tok/s  "
                  f"step={1e3 * r['decode_step_avg_s']:7.2f} ms  "
                  f"window={r['decode_window_avg']:6.1f}/{s_max}  "
                  f"ttft p95={r['ttft_p95_ms']:8.1f} ms  "
                  f"(compiles={r['decode_compiles']} "
                  f"windows={r['decode_windows']})", flush=True)
    return results


def run_horizon_sweep(model, params, args, rng):
    """Steady-state dispatch-overhead grid: requests == slots (queue
    drains at admission, so the adaptive horizon is not forced to 1)
    with budgets of several horizons, served at each --horizons value.
    The record: decode tokens/sec vs H and syncs/token -> 1/H."""
    horizons = [int(x) for x in args.horizons.split(",")]
    # ONE slot: the most dispatch-bound shape (per-dispatch compute is
    # minimal, per-dispatch overhead is constant), and syncs/token
    # reads exactly 1/H — the README cost-model term, measured
    slots = 1
    # budgets long enough that most dispatches run at full H (the
    # CPU-clamped --new_tokens would leave every budget below H_max,
    # and a budget of a few H leaves the H=1 tail dominating the mean);
    # +1: the prefill token, so the DECODE budget divides every horizon
    # exactly and no point pays a remainder of single-step dispatches
    new_tokens = max(args.new_tokens, 16 * max(horizons) + 1)
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    lengths = [int(rng.integers(max(1, prompt_hi // 2), prompt_hi + 1))
               for _ in range(slots)]
    prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
               for n in lengths]
    results = []
    for h in horizons:
        # full s_max window: the sweep isolates dispatch+readback
        # overhead (the length sweep owns the bucketing evidence), so
        # boundary-forced H=1 stretches would only blur the comparison.
        # Best-of-N: the point is a latency floor, and host scheduling
        # noise only ever ADDS time — the max is the honest estimator
        r = max((run_point(model, params, prompts, new_tokens, slots,
                           float("inf"), s_max, warmup=True,
                           decode_buckets=(), decode_horizon=h)
                 for _ in range(args.horizon_repeats)),
                key=lambda p: p["decode_tokens_per_sec"])
        r.update(horizon=h, slots=slots, new_tokens=new_tokens,
                 s_max=s_max)
        results.append(r)
        print(f"H={h:3d}  decode {r['decode_tokens_per_sec']:9.1f} "
              f"tok/s  syncs/tok={r['host_syncs_per_token']:6.3f}  "
              f"h_avg={r['decode_horizon_avg']:5.2f}  "
              f"overlapped={r['overlapped_dispatches']:4d}  "
              f"(programs={r['decode_programs']})", flush=True)
    if len(results) > 1 and results[0]["decode_tokens_per_sec"] > 0:
        speedup = (results[-1]["decode_tokens_per_sec"]
                   / results[0]["decode_tokens_per_sec"])
        print(f"# steady-state decode speedup H={horizons[-1]} vs "
              f"H={horizons[0]}: {speedup:.2f}x", flush=True)
    return results


def run_chaos_sweep(model, params, args, rng):
    """Fault-free vs background-fault-rate steady state: the recorded
    degradation budget. One transient error every --chaos_every
    decode-dispatch ATTEMPTS (seeded, deterministic; each recovered
    fault adds one retry attempt, so the realized per-dispatch rate is
    1/(chaos_every - 1)), every one recovered by the engine's bounded
    retry + cooldown — the sweep measures what that survival COSTS in
    tok/s."""
    from pytorch_multiprocessing_distributed_tpu.runtime.faults import (
        FaultPlan, FaultRule)

    if args.chaos_every < 2:
        # every=1 would fault every attempt INCLUDING the retries —
        # retries exhaust and the run dies instead of measuring
        raise SystemExit("--chaos_every must be >= 2 (every attempt "
                         "faulting leaves no attempt to recover on)")

    new_tokens = max(args.new_tokens, 65)
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    slots = int(args.slots.split(",")[0])
    lengths = [int(rng.integers(max(1, prompt_hi // 2), prompt_hi + 1))
               for _ in range(slots)]
    prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
               for n in lengths]
    point = dict(decode_buckets=(), decode_horizon=4,
                 retry_backoff_s=0.0)
    base = run_point(model, params, prompts, new_tokens, slots,
                     float("inf"), s_max, warmup=True, **point)
    plan = FaultPlan([FaultRule("serving.decode_dispatch", "error",
                                times=0, every=args.chaos_every)],
                     seed=7)
    fault = run_point(model, params, prompts, new_tokens, slots,
                      float("inf"), s_max, warmup=True, arm_plan=plan,
                      **point)
    base_tps = base["decode_tokens_per_sec"]
    fault_tps = fault["decode_tokens_per_sec"]
    degradation = (0.0 if base_tps == 0
                   else 1.0 - fault_tps / base_tps)
    results = []
    for label, r in (("fault-free", base), ("faulted", fault)):
        r.update(mode=label, chaos_every=args.chaos_every)
        results.append(r)
        print(f"chaos {label:10s}  {r['decode_tokens_per_sec']:9.1f} "
              f"decode tok/s  injected={r['injected']:3d}  "
              f"retries={r['dispatch_retries']:3d}  "
              f"collapses={r['horizon_collapses']:3d}  "
              f"failed={r['requests_failed']}", flush=True)
    # dispatch_retries counts retries from EVERY engine fault domain;
    # equality holds here because the sweep injects only dispatch
    # faults and the local CPU run has no real transients to add
    assert fault["dispatch_retries"] == fault["injected"], (
        "every injected fault must be VISIBLY retried, none eaten")
    assert fault["requests_failed"] == 0, (
        "a background transient rate must be fully recovered")
    print(f"# degradation budget at 1/{args.chaos_every - 1} "
          f"per-dispatch fault rate: {100 * degradation:.1f}% "
          f"({base_tps:.1f} -> {fault_tps:.1f} tok/s)", flush=True)
    results.append({"mode": "budget", "chaos_every": args.chaos_every,
                    "degradation_frac": degradation})
    return results


def _hit_prompts(rng, model, dist, n, lo, hi, hit_rate):
    """Request stream at a prefix-hit rate: ``hit_rate`` of the
    requests re-use one of two "popular" prompts (identical full
    prompts — FULL hits once cached), the rest are unique."""
    lengths = _draw_lengths(rng, dist, n + 2, lo, hi)
    popular = [rng.integers(0, model.vocab_size, (lengths[i],)).tolist()
               for i in range(2)]
    prompts = []
    for i in range(n):
        if rng.random() < hit_rate:
            prompts.append(list(popular[i % 2]))
        else:
            prompts.append(rng.integers(
                0, model.vocab_size, (lengths[2 + i],)).tolist())
    return prompts


def run_paged_sweep(model, params, args, rng):
    """Dense vs paged at fixed HBM x length dist x prefix-hit rate.
    See the module docstring (sweep 5); CPU-runnable, TPU-ready."""
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        plan_capacity)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        hbm as hbm_ledger)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, SlotPool)

    new_tokens = args.new_tokens
    # the pool must ADMIT up to the model's own max length (that is
    # what s_max is for); traffic runs mostly shorter — exactly the
    # gap dense slots pay worst-case for and pages do not
    s_max = model.max_seq_len
    prompt_hi = max(2, min(args.prompt_max, s_max - new_tokens) - 1)
    slots_dense = int(args.slots.split(",")[0])
    page_size = max(4, args.page_size)
    # FIXED budget: params + exactly the dense pool's worst-case KV
    # bytes — the planner charges params first, so the page pool gets
    # precisely the bytes the dense slots occupied
    kv_budget = slots_dense * SlotPool.per_slot_kv_bytes(model, s_max)
    budget = hbm_ledger.tree_nbytes(params) + kv_budget
    results = []
    for dist in args.len_dist.split(","):
        lengths = _draw_lengths(rng, dist, args.requests,
                                max(1, prompt_hi // 8), prompt_hi)
        prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
                   for n in lengths]
        plan = plan_capacity(
            model, s_max, budget, params=params, page_size=page_size,
            length_dist=[n + new_tokens for n in lengths])
        num_pages = plan["max_pages"] + 1  # + scratch
        paged_slots = max(slots_dense + 1,
                          min(plan["expected_resident_requests"] + 2,
                              args.requests))

        # ---- point (a): resident requests at the fixed budget
        dense = run_point(model, params, prompts, new_tokens,
                          slots_dense, float("inf"), s_max)
        paged = run_point(model, params, prompts, new_tokens,
                          paged_slots, float("inf"), s_max,
                          kv_layout="paged", page_size=page_size,
                          num_pages=num_pages)
        # planner-vs-allocation byte-exactness pin (the graftmeter
        # contract): a real pool of the planned page count holds
        # exactly the planned KV bytes
        with hbm_ledger.scoped_ledger() as ledger:
            from pytorch_multiprocessing_distributed_tpu.serving import (
                PagePool)

            pool = PagePool(model, paged_slots, s_max,
                            page_size=page_size, num_pages=num_pages)
            kv_entry = ledger.entries()["serving.kv_pages"]
        assert kv_entry[1] == plan["paged_kv_bytes_at_max"], (
            "planner and PagePool disagree on the page bytes")
        del pool
        for mode, r, eng_slots in (("dense", dense, slots_dense),
                                   ("paged", paged, paged_slots)):
            r.update(mode=mode, dist=dist, slots=eng_slots,
                     hbm_budget_bytes=budget,
                     hbm_kv_budget_bytes=kv_budget, s_max=s_max,
                     page_size=(page_size if mode == "paged" else 0),
                     num_pages=(num_pages if mode == "paged" else 0),
                     resident_requests=r["occupancy_max"],
                     planner_expected_resident=plan[
                         "expected_resident_requests"])
            results.append(r)
        gain = (paged["occupancy_max"] / dense["occupancy_max"]
                if dense["occupancy_max"] else 0.0)
        print(f"paged dist={dist:6s}  resident dense="
              f"{dense['occupancy_max']:3d} paged="
              f"{paged['occupancy_max']:3d} ({gain:.1f}x at "
              f"{budget / (1 << 20):.1f} MiB KV)  "
              f"planner={plan['expected_resident_requests']}",
              flush=True)

        # ---- point (b): TTFT at prefix-hit rates (closed loop: one
        # request in flight, so TTFT is the prefill-side latency the
        # prefix cache actually removes)
        for hit_rate in (0.0, 0.5, 0.9):
            prompts_h = _hit_prompts(rng, model, dist, args.requests,
                                     max(1, prompt_hi // 8), prompt_hi,
                                     hit_rate)
            engine = ServingEngine(
                model, params, max_slots=paged_slots, s_max=s_max,
                kv_layout="paged", page_size=page_size,
                num_pages=num_pages, prefix_cache=16)
            ref = ServingEngine(model, params, max_slots=slots_dense,
                                s_max=s_max)
            # warm compiles off the clock (one throwaway miss)
            engine.serve([(prompts_h[0], new_tokens)])
            finished = []
            for p in prompts_h:
                finished.append(engine.serve([(p, new_tokens)])[0])
            ttft = {"hit": [], "miss": []}
            for r in finished:
                key = "hit" if r.prefix_hit == "full" else "miss"
                ttft[key].append(r.first_token_time - r.submit_time)
            # token-exactness vs the dense engine, per unique prompt
            for p, r in list(zip(prompts_h, finished))[:4]:
                (d,) = ref.serve([(p, new_tokens)])
                assert r.tokens == d.tokens, (
                    "paged stream diverged from dense")
            snap = engine.metrics.snapshot()
            point = {
                "mode": "ttft", "dist": dist, "hit_rate": hit_rate,
                "page_size": page_size,
                "requests": len(prompts_h),
                "prefix_hits": snap["prefix_hits"],
                "prefix_partial_hits": snap["prefix_partial_hits"],
                "prefix_misses": snap["prefix_misses"],
                # None, not 0, when a rate produced no samples of a
                # kind (e.g. every popular prompt shorter than one
                # page -> no hits; hit_rate ~1 -> possibly no misses)
                "ttft_hit_p50_ms": (1e3 * _percentile(ttft["hit"], 50)
                                    if ttft["hit"] else None),
                "ttft_hit_p95_ms": (1e3 * _percentile(ttft["hit"], 95)
                                    if ttft["hit"] else None),
                "ttft_miss_p50_ms": (1e3 * _percentile(ttft["miss"], 50)
                                     if ttft["miss"] else None),
                "ttft_miss_p95_ms": (1e3 * _percentile(ttft["miss"], 95)
                                     if ttft["miss"] else None),
                "hbm_per_slot_bytes": engine.pool.per_slot_bytes,
            }
            ratio = (point["ttft_hit_p50_ms"]
                     / point["ttft_miss_p50_ms"]
                     if point["ttft_hit_p50_ms"] is not None
                     and point["ttft_miss_p50_ms"] else None)
            point["ttft_hit_over_miss_p50"] = ratio

            def ms(v):
                return "     n/a" if v is None else f"{v:8.2f}"

            results.append(point)
            print(f"paged dist={dist:6s} hit={hit_rate:.1f}  "
                  f"ttft p50 hit={ms(point['ttft_hit_p50_ms'])} ms "
                  f"miss={ms(point['ttft_miss_p50_ms'])} ms  "
                  f"(ratio={ratio if ratio is None else round(ratio, 3)}"
                  f", hits={snap['prefix_hits']})", flush=True)
    return results


def run_quant_sweep(model, params, args, rng):
    """graftquant (sweep 11): int8 KV at fixed HBM — residency gain
    (planner, byte-exact vs real pools), measured occupancy + tok/s
    both modes, transcript equality on a canonical subset, and the
    teacher-forced logit-delta audit. See module docstring."""
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        plan_capacity)
    from pytorch_multiprocessing_distributed_tpu.inference import (
        teacher_forced_logits)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        hbm as hbm_ledger)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, SlotPool)

    new_tokens = args.new_tokens
    s_max = model.max_seq_len
    prompt_hi = max(2, min(args.prompt_max, s_max - new_tokens) - 1)
    lengths = _draw_lengths(rng, "mixed", args.requests,
                            max(1, prompt_hi // 8), prompt_hi)
    prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
               for n in lengths]
    results = []

    # ---- point (a): the byte claim, planner == allocator both modes
    kv_model = SlotPool.per_slot_kv_bytes(model, s_max)
    kv_int8 = SlotPool.per_slot_kv_bytes(model, s_max, "int8")
    kv_ratio = kv_model / kv_int8
    head_dim = model.hidden_size // model.num_heads
    itemsize = jnp.dtype(model.dtype).itemsize
    # int8 stores head_dim 1-byte lanes + one f32 scale per group:
    # the achievable ratio IS itemsize*Dh/(Dh+4). Gate at that floor,
    # clamped to the 1.8x headline it clears at head_dim >= 64
    # (gpt_small/gpt_medium) for bf16 and at any registry geometry
    # for f32 — a layout regression (fatter sidecar, padding) trips
    # this before it ships
    ratio_floor = min(1.8, itemsize * head_dim / (head_dim + 4)
                      * 0.999)
    assert kv_ratio >= ratio_floor, (
        f"int8 per-slot KV ratio {kv_ratio:.3f} under the geometry "
        f"floor {ratio_floor:.3f}")
    # FIXED budget: params + exactly N model-dtype slots (KV + scalar
    # state) — plan_ref inverts it back to N, plan_q to what int8
    # fits in the same bytes. N >= 5 so integer slot-count floors
    # cannot mask the gain at small --slots
    slots_dense = max(int(args.slots.split(",")[0]), 5)
    per_slot_full = kv_model + SlotPool.per_slot_state_bytes()
    budget = (hbm_ledger.tree_nbytes(params)
              + slots_dense * per_slot_full)
    plan_ref = plan_capacity(model, s_max, budget, params=params)
    plan_q = plan_capacity(model, s_max, budget, params=params,
                           kv_dtype="int8")
    assert plan_ref["max_slots"] == slots_dense
    planned_gain = plan_q["max_slots"] / plan_ref["max_slots"]
    assert planned_gain >= min(1.8, ratio_floor), (
        f"planned residency gain {planned_gain:.2f}x under the floor "
        f"at a {slots_dense}-slot budget")
    # planner-vs-allocation byte-exactness pin (the graftmeter
    # contract, quantized mode): a real int8 pool of the planned slot
    # count registers exactly the planned KV bytes
    with hbm_ledger.scoped_ledger() as ledger:
        pool = SlotPool(model, plan_q["max_slots"], s_max,
                        kv_dtype="int8")
        kv_entry = ledger.entries()["serving.kv_pool"]
    assert kv_entry[1] == plan_q["max_slots"] * kv_int8, (
        "planner and quantized SlotPool disagree on the KV bytes")
    del pool

    # ---- point (b): measured residency + throughput at the budget
    quant_slots = max(slots_dense + 1,
                      min(plan_q["max_slots"], args.requests))
    ref = run_point(model, params, prompts, new_tokens, slots_dense,
                    float("inf"), s_max)
    quant = run_point(model, params, prompts, new_tokens, quant_slots,
                      float("inf"), s_max, kv_dtype="int8")
    for mode, r, eng_slots in (("model", ref, slots_dense),
                               ("int8", quant, quant_slots)):
        r.update(mode=mode, kv_dtype=mode, slots=eng_slots,
                 hbm_budget_bytes=budget, s_max=s_max,
                 per_slot_kv_bytes=(kv_int8 if mode == "int8"
                                    else kv_model),
                 per_slot_kv_ratio=kv_ratio,
                 resident_requests=r["occupancy_max"],
                 planner_max_slots=(plan_q if mode == "int8"
                                    else plan_ref)["max_slots"],
                 planned_residency_gain=planned_gain)
        results.append(r)
    print(f"quant    KV/slot {kv_model} -> {kv_int8} B "
          f"({kv_ratio:.2f}x, head_dim={head_dim})  planned slots "
          f"{plan_ref['max_slots']} -> {plan_q['max_slots']} "
          f"({planned_gain:.2f}x at {budget / (1 << 20):.1f} MiB)  "
          f"resident {ref['occupancy_max']} -> "
          f"{quant['occupancy_max']}  "
          f"{ref['tokens_per_sec']:.1f} -> "
          f"{quant['tokens_per_sec']:.1f} tok/s", flush=True)

    # ---- point (c): quality audit — transcripts + logit delta.
    # int8 KV is NOT token-exact by construction; the bench pins the
    # canonical subset byte-equal and puts the honest logit delta on
    # the record (gated at the committed tolerance per dtype)
    eng_ref = ServingEngine(model, params, max_slots=2, s_max=s_max)
    eng_q = ServingEngine(model, params, max_slots=2, s_max=s_max,
                          kv_dtype="int8")
    canon = prompts[:4]
    out_ref = eng_ref.serve([(p, new_tokens) for p in canon])
    out_q = eng_q.serve([(p, new_tokens) for p in canon])
    for i, (a, b) in enumerate(zip(out_q, out_ref)):
        assert list(a.tokens) == list(b.tokens), (
            f"int8 stream {i} diverged from the model-dtype engine")
    full = jnp.asarray(list(canon[0])
                       + list(out_ref[0].tokens))[None, :]
    lg_ref = teacher_forced_logits(model, params, full, len(canon[0]))
    lg_q = teacher_forced_logits(model, params, full, len(canon[0]),
                                 kv_dtype="int8")
    delta = float(np.max(np.abs(np.asarray(lg_ref)
                                - np.asarray(lg_q))))
    tol = 5e-3 if itemsize >= 4 else 6e-2
    assert 0.0 < delta < tol, (
        f"teacher-forced logit delta {delta:.2e} outside (0, {tol})")
    point = {
        "mode": "quant_quality", "kv_dtype": "int8",
        "requests": len(canon), "transcripts_equal": True,
        "logit_delta_max": delta, "logit_delta_tol": tol,
    }
    results.append(point)
    print(f"quant    {len(canon)} canonical streams byte-equal, "
          f"max |logit delta| = {delta:.2e} (tol {tol:.0e})",
          flush=True)
    return results


def train_repetitive(model, params, motif, steps=60, lr=0.1,
                     seq=64, batch=8, seed=0):
    """Quick plain-SGD fit of ``model`` on the cyclic ``motif``
    stream (a few seconds on CPU for the tiny geometry): repetition is
    the easiest structure a LM learns, so the trained target's greedy
    continuation genuinely loops — the spec sweep's repetitive family
    then measures STRUCTURAL acceptance (the model really continues
    the pattern the n-gram drafter indexes), not random-params luck."""
    rng = np.random.default_rng(seed)

    def make_batch():
        rows = []
        for _ in range(batch):
            off = int(rng.integers(0, len(motif)))
            rows.append([motif[(off + i) % len(motif)]
                         for i in range(seq)])
        return jnp.asarray(rows, jnp.int32)

    def loss_fn(p, toks):
        logits = model.apply({"params": p}, toks, train=False)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(
            lp, toks[:, 1:][..., None], -1))

    step = jax.jit(lambda p, t: jax.tree.map(
        lambda a, g: (a - lr * g).astype(a.dtype), p,
        jax.grad(loss_fn)(p, t)))
    for _ in range(steps):
        params = step(params, make_batch())
    return params


def run_spec_sweep(model, params, args, rng):
    """Speculative-decode grid (graftspec): {repetitive, random}
    prompts x {self-draft, draft-model} x k. See the module docstring
    (sweep 6). Asserted invariants: k=0 runs ZERO spec passes with
    the non-spec program ladder (disarmed reproduces the plain
    engine), and the repetitive family's best k>0 point clears >1.0
    accepted tokens per target step."""
    from pytorch_multiprocessing_distributed_tpu import models

    platform = jax.devices()[0].platform
    ks = [int(x) for x in args.spec_ks.split(",")]
    new_tokens = max(args.new_tokens, 48)
    motif = [7, 19, 3, 42, 11, 58, 23, 5]
    motif = [t % model.vocab_size for t in motif]
    n_req = min(args.requests, 4 if platform != "tpu" else args.requests)
    s_max = min(model.max_seq_len, 32 + new_tokens)
    prompt_len = min(30, s_max - new_tokens - 1)

    # draft model: a REAL registry model on TPU (--draft_model), the
    # target itself off-TPU (structural acceptance — the mode's smoke)
    if args.draft_model:
        draft_model = models.get_model(
            args.draft_model, dtype=model.dtype,
            vocab_size=model.vocab_size, attn_impl="xla")
        from pytorch_multiprocessing_distributed_tpu.serving import (
            init_params)

        draft_params = init_params(draft_model, 7)
    else:
        draft_model, draft_params = model, None  # filled per family

    # the repetitive family's target: briefly trained on the motif
    rep_params = train_repetitive(model, params, motif)
    families = {
        "repetitive": (rep_params,
                       [[motif[i % len(motif)] for i in range(prompt_len)]
                        for _ in range(n_req)]),
        "random": (params,
                   [rng.integers(0, model.vocab_size,
                                 (prompt_len,)).tolist()
                    for _ in range(n_req)]),
    }
    results = []
    best_rep = 0.0
    for family, (fam_params, prompts) in families.items():
        for mode in args.spec_modes.split(","):
            for k in ks:
                kwargs = dict(decode_buckets=(), decode_horizon=4,
                              draft_k=k)
                if k and mode == "model":
                    kwargs.update(
                        draft_model=draft_model,
                        draft_params=(draft_params if draft_params
                                      is not None else fam_params))
                elif k == 0 and mode == "model":
                    continue  # k=0 is mode-less; keep one baseline row
                r = run_point(model, fam_params, prompts, new_tokens,
                              min(4, n_req), float("inf"), s_max,
                              warmup=True, **kwargs)
                r.update(family=family, mode=(mode if k else "off"),
                         draft_k=k, new_tokens=new_tokens)
                results.append(r)
                if k == 0:
                    assert r["spec_verify_passes"] == 0, (
                        "k=0 must run ZERO speculative passes")
                    assert not r["spec_programs"], (
                        "k=0 must not compile spec programs")
                if family == "repetitive" and k:
                    best_rep = max(
                        best_rep, r["spec_accepted_per_target_step"])
                print(f"spec {family:10s} {r['mode']:5s} k={k}  "
                      f"acc/step={r['spec_accepted_per_target_step']:5.2f}  "
                      f"rate={r['spec_accept_rate']:4.2f}  "
                      f"accept_len p50/p95="
                      f"{r['accept_len_p50']:.1f}/"
                      f"{r['accept_len_p95']:.1f}  "
                      f"{r['decode_tokens_per_sec']:8.1f} decode tok/s  "
                      f"ttft p95={r['ttft_p95_ms']:7.1f} ms", flush=True)
    assert best_rep > 1.0, (
        f"repetitive-prompt config must clear >1.0 accepted tokens "
        f"per target step, got {best_rep:.3f} — the speculative claim "
        "is the whole point")
    print(f"# spec: repetitive best accepted/target-step = "
          f"{best_rep:.2f}", flush=True)
    return results


def run_drain_sweep(model, params, args, rng):
    """Drain latency + post-restart recovery TTFT (graftheal), both
    wall-clocked on a loaded engine; the redelivered streams are
    verified token-exact against the pre-crash prefixes."""
    import tempfile

    from pytorch_multiprocessing_distributed_tpu.runtime import heal
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine)

    new_tokens = max(args.new_tokens, 8)
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    slots = int(args.slots.split(",")[0])
    prompts = [rng.integers(0, model.vocab_size, (int(rng.integers(
        max(1, prompt_hi // 2), prompt_hi + 1)),)).tolist()
        for _ in range(2 * slots)]
    tmpdir = tempfile.mkdtemp(prefix="pmdt_drain_bench_")

    def mk(journal=None):
        return ServingEngine(model, params, max_slots=slots,
                             s_max=s_max, decode_horizon=4,
                             decode_buckets=(), retry_backoff_s=0.0,
                             journal=journal)

    # ---- point 1: drain latency (the SIGTERM path, no deadline)
    engine = mk()
    engine.serve([(prompts[0], 2)])  # compiles off the clock
    reqs = [engine.submit(p, new_tokens) for p in prompts]
    engine.step()  # mid-serve: slots resident, queue non-empty
    engine.begin_drain("bench")
    t0 = time.perf_counter()
    engine.drain(None)
    drain_latency = time.perf_counter() - t0
    drained = sum(r.state == "done" for r in reqs)
    point = {
        "mode": "drain",
        "slots": slots,
        "requests": len(prompts),
        "drain_latency_s": drain_latency,
        "drained_completed": drained,
        "drained_failed": sum(r.state == "failed" for r in reqs),
        "drain_tokens": sum(len(r.tokens) for r in reqs),
    }
    print(f"drain    latency={drain_latency:8.3f} s  "
          f"completed={drained}/{len(prompts)}  "
          f"tokens={point['drain_tokens']}", flush=True)
    results = [point]

    # ---- point 2: recovery TTFT after a supervised restart
    wal = os.path.join(tmpdir, "wal.jsonl")
    journal = heal.RequestJournal(wal)
    crashed = mk(journal)
    pre = [crashed.submit(p, new_tokens) for p in prompts]
    for _ in range(3):
        crashed.step()  # partial progress into the WAL
    prefix = {r.uid: list(r.tokens) for r in pre}
    del crashed  # abandoned mid-run: the crash shape (WAL not closed)

    t0 = time.perf_counter()  # journal replay ON the clock
    journal2 = heal.RequestJournal(wal)
    unfinished = journal2.unfinished()
    # snapshot NOW: the live entries grow as the fresh engine re-serves
    replayed_tokens = sum(len(e.tokens) for e in unfinished)
    fresh = mk(journal2)
    redelivered = fresh.redeliver(unfinished)
    t_first = None
    while fresh.in_flight and t_first is None:
        for request, _tok, _done in fresh.step():
            t_first = time.perf_counter()
            break
    recovery_ttft = (t_first - t0) if t_first is not None else None
    fresh.drain(None)
    # redelivery is token-exact: every pre-crash prefix is a prefix
    # of the recovered stream (greedy determinism, bench-asserted)
    for r in redelivered:
        want = prefix.get(r.uid, [])
        assert r.tokens[:len(want)] == want, (
            f"redelivered request {r.uid} diverged from its "
            "pre-crash prefix")
    point = {
        "mode": "recovery",
        "slots": slots,
        "redelivered": len(redelivered),
        "replayed_tokens": replayed_tokens,
        "recovery_ttft_s": recovery_ttft,
        "recovered_completed": sum(r.state == "done"
                                   for r in redelivered),
    }
    print(f"recovery ttft={recovery_ttft:8.3f} s  "
          f"redelivered={len(redelivered)}  "
          f"replayed_tokens={point['replayed_tokens']}", flush=True)
    results.append(point)
    import shutil

    shutil.rmtree(tmpdir, ignore_errors=True)
    return results


def run_fleet_sweep(model, params, args, rng):
    """graftroute (sweep 8): the fleet evidence — (1) a 2-replica
    router's streams are BYTE-IDENTICAL to the single-engine baseline
    at higher aggregate tok/s, with per-replica goodput_frac and the
    straggler named; (2) prefill/decode disaggregation is token-exact
    vs monolithic, transfer bytes recorded; (3) one injected replica
    death mid-run -> journal redelivery to the peer, every stream
    still exact, recovery TTFT wall-clocked."""
    import tempfile

    from pytorch_multiprocessing_distributed_tpu.runtime import (
        faults, fleet as graftfleet, heal)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        Router, ServingEngine, ServingReplica)

    new_tokens = max(4, min(args.new_tokens, 16))
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    slots = int(args.slots.split(",")[0])
    n_req = max(2 * slots + 2, min(args.requests, 12))
    prompts = [rng.integers(0, model.vocab_size, (int(rng.integers(
        max(1, prompt_hi // 2), prompt_hi + 1)),)).tolist()
        for _ in range(n_req)]

    def mk(journal=None, dispatch_retries=3):
        return ServingEngine(model, params, max_slots=slots,
                             s_max=s_max, decode_buckets=(),
                             retry_backoff_s=0.0, journal=journal,
                             dispatch_retries=dispatch_retries)

    # ---- baseline: ONE engine, same request set
    base = mk()
    base.serve([(prompts[0], 2)])  # compiles off the clock
    t0 = time.perf_counter()
    ref = base.serve([(p, new_tokens) for p in prompts])
    base_s = time.perf_counter() - t0
    ref_tokens = {i: list(r.tokens) for i, r in enumerate(ref)}
    total_tokens = sum(len(t) for t in ref_tokens.values())
    results = []

    # ---- point 1: 2-replica fleet, byte-identical + aggregate tok/s
    router = Router([ServingReplica("r0", mk()),
                     ServingReplica("r1", mk())])
    for replica in router.replicas:  # compiles off the clock, like
        replica.engine.serve([(prompts[0], 2)])  # the baseline's
    t0 = time.perf_counter()
    out = router.serve([(p, new_tokens) for p in prompts])
    fleet_s = time.perf_counter() - t0
    for i, r in enumerate(out):
        assert r.state == "done" and list(r.tokens) == ref_tokens[i], (
            f"fleet stream {i} diverged from the single-engine "
            "baseline")
    merged = router.merged_metrics()
    report = graftfleet.fleet_serving_report(merged["per_replica"])
    point = {
        "mode": "fleet", "replicas": 2, "slots": slots,
        "requests": n_req,
        "baseline_tokens_per_sec": total_tokens / base_s,
        "tokens_per_sec": total_tokens / fleet_s,
        "speedup": base_s / fleet_s,
        "steals": router.steals,
        "goodput_frac_per_replica":
            report.get("goodput_frac_per_replica", {}),
        "straggler": report.get("straggler"),
        "byte_identical": True,
    }
    print(f"fleet    2 replicas  {point['tokens_per_sec']:9.1f} tok/s "
          f"(1 engine: {point['baseline_tokens_per_sec']:9.1f})  "
          f"speedup={point['speedup']:5.2f}x  steals={router.steals}",
          flush=True)
    results.append(point)

    # ---- point 2: prefill/decode split vs monolithic (token-exact)
    router = Router([ServingReplica("pf", mk(), role="prefill"),
                     ServingReplica("dc", mk(), role="decode")])
    router.serve([(prompts[0], 2)])  # both halves' compiles off-clock
    t0 = time.perf_counter()
    out = router.serve([(p, new_tokens) for p in prompts])
    disagg_s = time.perf_counter() - t0
    for i, r in enumerate(out):
        assert r.state == "done" and list(r.tokens) == ref_tokens[i], (
            f"disaggregated stream {i} diverged from monolithic")
    pf = router._by_rid["pf"]
    point = {
        "mode": "disagg", "slots": slots, "requests": n_req,
        "tokens_per_sec": total_tokens / disagg_s,
        "transfers": router.transfers_routed,
        "transfer_bytes": router.transfer_bytes,
        "transfer_bytes_per_request":
            router.transfer_bytes // max(1, router.transfers_routed),
        "prefill_transfers": pf.transfers_out,
        "token_exact": True,
    }
    print(f"disagg   prefill->decode  "
          f"{point['tokens_per_sec']:9.1f} tok/s  "
          f"transfers={router.transfers_routed} (token-exact)",
          flush=True)
    results.append(point)

    # ---- point 3: injected replica death -> redelivery recovery TTFT
    tmpdir = tempfile.mkdtemp(prefix="pmdt_fleet_bench_")

    def mkrep(i):
        journal = heal.RequestJournal(
            os.path.join(tmpdir, f"wal{i}.jsonl"))
        return ServingReplica(f"r{i}", mk(journal, dispatch_retries=1),
                              journal=journal)

    router = Router([mkrep(0), mkrep(1)])
    reqs = [router.submit(p, new_tokens, uid=f"u{i}")
            for i, p in enumerate(prompts)]
    for _ in range(3):
        router.step()  # tokens into both WALs before the kill
    plan = faults.FaultPlan(seed=7, rules=[faults.FaultRule(
        "serving.decode_dispatch", "fatal", times=1)])
    faults.arm(plan)
    t_death = None
    t_recover = None
    try:
        while router.in_flight:
            before = router.requests_redelivered
            t_pre = time.perf_counter()
            events = router.step()
            if router.requests_redelivered > before and t_death is None:
                # the dying dispatch, the reap AND the journal replay
                # all happen inside this one step — clock recovery
                # from the step's START, or the interval measures the
                # microseconds between two post-step reads
                t_death = t_pre
            if t_death is not None and t_recover is None:
                redelivered = set(router.redelivered_uids)
                for request, _tok, _done in events:
                    if request.uid in redelivered:
                        t_recover = time.perf_counter()
                        break
    finally:
        faults.disarm()
    recs = router.records()
    for i in range(n_req):
        r = recs[f"u{i}"]
        assert r.state == "done" and list(r.tokens) == ref_tokens[i], (
            f"post-death stream u{i} diverged")
    merged = router.merged_metrics()
    assert merged["tokens_generated"] == total_tokens, (
        "redelivery dedup broke the fleet token count")
    point = {
        "mode": "redelivery", "slots": slots, "requests": n_req,
        "redelivered": router.requests_redelivered,
        "replayed_tokens": router.redelivery_replayed_tokens,
        "recovery_ttft_s": (t_recover - t_death
                            if t_recover and t_death else None),
        "replicas_dead": merged["fleet_replicas_dead"],
        "token_exact": True,
    }
    print(f"redeliver dead=1  redelivered={point['redelivered']}  "
          f"recovery_ttft="
          f"{point['recovery_ttft_s'] if point['recovery_ttft_s'] is None else round(point['recovery_ttft_s'], 4)} s",
          flush=True)
    results.append(point)
    import shutil

    shutil.rmtree(tmpdir, ignore_errors=True)
    return results


def run_wire_sweep(model, params, args, rng):
    """graftwire + graftlink (sweep 9): the socket transport vs the
    in-process seam it mirrors — (1) same fleet, THREE transports
    (in-process, blocking wire, pipelined wire): tok/s side by side,
    streams byte-identical, per-RPC overhead p50/p95, and a scraper
    thread hammering the snapshot verb through the timed run so the
    sweep records snapshot p99 with a long engine verb in flight (the
    head-of-line headline: blocking queues the scrape behind every
    step RPC, pipelined answers it on the obs lane); (2)
    disaggregation over the wire: PageTransfer bytes/request at the
    payload and framing layers (wire bytes ~ payload bytes — the
    zero-copy scatter-gather claim) plus prefill->decode handoff
    latency — then the SAME split with int8 KV (graftquant),
    bytes/request halved vs the model-dtype run; (3) socket-level
    kill -> WAL redelivery with the recovery TTFT on the clock."""
    import tempfile
    import threading

    from pytorch_multiprocessing_distributed_tpu.runtime import (
        heal, wire)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        RemoteReplica, ReplicaServer, Router, ServingEngine,
        ServingReplica)

    new_tokens = max(4, min(args.new_tokens, 16))
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    slots = int(args.slots.split(",")[0])
    n_req = max(2 * slots + 2, min(args.requests, 12))
    prompts = [rng.integers(0, model.vocab_size, (int(rng.integers(
        max(1, prompt_hi // 2), prompt_hi + 1)),)).tolist()
        for _ in range(n_req)]

    def mk(journal=None, dispatch_retries=3, kv_dtype="model"):
        return ServingEngine(model, params, max_slots=slots,
                             s_max=s_max, decode_buckets=(),
                             retry_backoff_s=0.0, journal=journal,
                             dispatch_retries=dispatch_retries,
                             kv_dtype=kv_dtype)

    def socket_fleet(journals=None, roles=("both", "both"),
                     kv_dtype="model", pipelined=True):
        servers = []
        for i, role in enumerate(roles):
            journal = journals[i] if journals else None
            servers.append(ReplicaServer(
                mk(journal, dispatch_retries=1 if journals else 3,
                   kv_dtype=kv_dtype),
                rid=f"r{i}", role=role).start())
        replicas = [RemoteReplica(s.address, backoff_s=0.0,
                                  pipelined=pipelined)
                    for s in servers]
        return Router(replicas), servers, replicas

    def rpc_stats(replicas):
        samples = [s for r in replicas for s in r._client.rpc_s]
        if not samples:
            return {"rpcs": 0}
        return {"rpcs": len(samples),
                "rpc_p50_ms": _percentile(samples, 50) * 1e3,
                "rpc_p95_ms": _percentile(samples, 95) * 1e3}

    results = []

    # ---- point 1: one fleet, two transports (byte-identical)
    router = Router([ServingReplica("r0", mk()),
                     ServingReplica("r1", mk())])
    # full warm pass off the clock (every prefill bucket + decode
    # program compiled) so the timed runs compare TRANSPORT, not
    # compile order — the socket fleet gets the identical warmup
    router.serve([(p, new_tokens) for p in prompts])
    t0 = time.perf_counter()
    ref = router.serve([(p, new_tokens) for p in prompts])
    inproc_s = time.perf_counter() - t0
    ref_tokens = {i: list(r.tokens) for i, r in enumerate(ref)}
    total_tokens = sum(len(t) for t in ref_tokens.values())

    # the SAME socket fleet twice: blocking (pipelined=False — the
    # pre-graftlink wire, one exchange at a time) then pipelined (the
    # default). A scraper thread hits replica 0's snapshot verb
    # through the timed run: blocking queues each scrape behind the
    # in-flight step RPC (head-of-line), pipelined answers it from
    # the obs lane — snapshot p99 under load is the HOL headline.
    by_transport = {}
    for transport, pipelined in (("blocking", False),
                                 ("pipelined", True)):
        router, servers, replicas = socket_fleet(pipelined=pipelined)
        stop = threading.Event()
        scrape_s = []

        def scrape_loop(replica=replicas[0], samples=scrape_s):
            while not stop.is_set():
                t_s = time.perf_counter()
                try:
                    replica.scrape()
                except Exception:
                    return
                samples.append(time.perf_counter() - t_s)
                stop.wait(0.002)

        try:
            router.serve([(p, new_tokens) for p in prompts])  # warmup
            for replica in replicas:
                replica._client.rpc_s.clear()
            scraper = threading.Thread(target=scrape_loop, daemon=True)
            scraper.start()
            t0 = time.perf_counter()
            out = router.serve([(p, new_tokens) for p in prompts])
            socket_s = time.perf_counter() - t0
            stop.set()
            scraper.join(timeout=10.0)
            for i, r in enumerate(out):
                assert r.state == "done" and \
                    list(r.tokens) == ref_tokens[i], (
                        f"{transport} socket-fleet stream {i} "
                        "diverged from the in-process fleet")
            point = {
                "mode": "wire_fleet", "transport": transport,
                "replicas": 2, "slots": slots, "requests": n_req,
                "inproc_tokens_per_sec": total_tokens / inproc_s,
                "tokens_per_sec": total_tokens / socket_s,
                "wire_overhead_frac": socket_s / inproc_s - 1.0,
                "byte_identical": True,
                "snapshot_scrapes": len(scrape_s),
            }
            if scrape_s:
                point["snapshot_p50_ms"] = \
                    _percentile(scrape_s, 50) * 1e3
                point["snapshot_p99_ms"] = \
                    _percentile(scrape_s, 99) * 1e3
            point.update(rpc_stats(replicas))
            by_transport[transport] = point
            print(f"wire     2 replicas {transport:9s} "
                  f"{point['tokens_per_sec']:9.1f} tok/s "
                  f"(in-process: "
                  f"{point['inproc_tokens_per_sec']:9.1f})  "
                  f"overhead="
                  f"{point['wire_overhead_frac'] * 100:5.1f}%  "
                  f"rpc p50={point.get('rpc_p50_ms', 0):6.2f} ms "
                  f"p95={point.get('rpc_p95_ms', 0):6.2f} ms  "
                  f"snapshot p99="
                  f"{point.get('snapshot_p99_ms', 0):7.2f} ms "
                  f"({point['snapshot_scrapes']} scrapes)",
                  flush=True)
            results.append(point)
        finally:
            stop.set()
            for server in servers:
                server.stop()
    pipe = by_transport["pipelined"]
    blk = by_transport["blocking"]
    pipe["speedup_vs_blocking"] = (pipe["tokens_per_sec"]
                                   / blk["tokens_per_sec"])
    if "snapshot_p99_ms" in pipe and "snapshot_p99_ms" in blk:
        pipe["snapshot_p99_vs_blocking"] = (pipe["snapshot_p99_ms"]
                                            / blk["snapshot_p99_ms"])
    print(f"wire     pipelined vs blocking  "
          f"{pipe['speedup_vs_blocking']:.2f}x tok/s, snapshot p99 "
          f"{pipe.get('snapshot_p99_vs_blocking', float('nan')):.2f}x",
          flush=True)

    # ---- point 2: disaggregation over the wire (PageTransfer bytes)
    meter0 = wire.wire_meter()["wire_bytes_sent"]
    router, servers, replicas = socket_fleet(
        roles=("prefill", "decode"))
    try:
        router.serve([(prompts[0], 2)])
        t0 = time.perf_counter()
        out = router.serve([(p, new_tokens) for p in prompts])
        disagg_s = time.perf_counter() - t0
        for i, r in enumerate(out):
            assert r.state == "done" and \
                list(r.tokens) == ref_tokens[i], (
                    f"wire-disagg stream {i} diverged from the "
                    "in-process fleet")
        wire_sent = wire.wire_meter()["wire_bytes_sent"] - meter0
        point = {
            "mode": "wire_disagg", "slots": slots, "requests": n_req,
            "tokens_per_sec": total_tokens / disagg_s,
            "transfers": router.transfers_routed,
            "transfer_bytes": router.transfer_bytes,
            "transfer_bytes_per_request":
                router.transfer_bytes // max(1,
                                             router.transfers_routed),
            "wire_bytes_sent": wire_sent,
            # payload bytes as a fraction of EVERYTHING that hit the
            # socket (transfers + every verb header + token events):
            # the zero-copy scatter-gather claim is wire ~ payload,
            # so this should sit near 1.0 — recorded, not asserted
            # (tiny bench models inflate the verb-header share)
            "wire_payload_frac":
                router.transfer_bytes / max(1, wire_sent),
            "token_exact": True,
        }
        if router.transfer_handoff_s:
            point["handoff_p50_ms"] = \
                _percentile(router.transfer_handoff_s, 50) * 1e3
            point["handoff_p95_ms"] = \
                _percentile(router.transfer_handoff_s, 95) * 1e3
        assert wire_sent >= router.transfer_bytes
        print(f"wire     prefill->decode  "
              f"{point['tokens_per_sec']:9.1f} tok/s  "
              f"{point['transfer_bytes_per_request']} KV B/req over "
              f"{router.transfers_routed} transfers  payload/wire="
              f"{point['wire_payload_frac']:.3f}  handoff p95="
              f"{point.get('handoff_p95_ms', 0):6.2f} ms "
              "(token-exact)", flush=True)
        results.append(point)
        model_bytes_per_request = point["transfer_bytes_per_request"]
    finally:
        for server in servers:
            server.stop()

    # ---- point 2b: the SAME disaggregation, int8 KV on the wire
    # (graftquant): the PageTransfer rides as int8 blocks + f32
    # scales (4 raw segments). int8 is not token-exact vs the
    # model-dtype fleet, so the reference is an in-process int8
    # engine — transport must not change ONE token of it — and the
    # headline is transfer bytes/request against point 2's run
    eng_q = mk(kv_dtype="int8")
    ref_q = eng_q.serve([(p, new_tokens) for p in prompts])
    ref_q_tokens = {i: list(r.tokens) for i, r in enumerate(ref_q)}
    q_tokens = sum(len(t) for t in ref_q_tokens.values())
    meter0 = wire.wire_meter()["wire_bytes_sent"]
    router, servers, replicas = socket_fleet(
        roles=("prefill", "decode"), kv_dtype="int8")
    try:
        router.serve([(prompts[0], 2)])
        t0 = time.perf_counter()
        out = router.serve([(p, new_tokens) for p in prompts])
        quant_s = time.perf_counter() - t0
        for i, r in enumerate(out):
            assert r.state == "done" and \
                list(r.tokens) == ref_q_tokens[i], (
                    f"quantized wire-disagg stream {i} diverged from "
                    "the in-process int8 engine")
        wire_sent = wire.wire_meter()["wire_bytes_sent"] - meter0
        bpr = router.transfer_bytes // max(1, router.transfers_routed)
        point = {
            "mode": "wire_disagg_quant", "kv_dtype": "int8",
            "slots": slots, "requests": n_req,
            "tokens_per_sec": q_tokens / quant_s,
            "transfers": router.transfers_routed,
            "transfer_bytes": router.transfer_bytes,
            "transfer_bytes_per_request": bpr,
            "model_dtype_bytes_per_request": model_bytes_per_request,
            "transfer_bytes_ratio": bpr / model_bytes_per_request,
            "wire_bytes_sent": wire_sent,
            "wire_payload_frac":
                router.transfer_bytes / max(1, wire_sent),
            "token_exact_vs_int8_engine": True,
        }
        if router.transfer_handoff_s:
            point["handoff_p50_ms"] = \
                _percentile(router.transfer_handoff_s, 50) * 1e3
            point["handoff_p95_ms"] = \
                _percentile(router.transfer_handoff_s, 95) * 1e3
        assert wire_sent >= router.transfer_bytes
        # the halving claim: int8 lanes + f32 scales vs model-dtype
        # blocks over the SAME prompt set — (Dh+4)/(itemsize*Dh),
        # < 0.6 for bf16 at head_dim >= 16 and any f32 geometry
        assert bpr < 0.6 * model_bytes_per_request, (
            f"quantized transfer {bpr} B/req is not < 0.6x the "
            f"model-dtype {model_bytes_per_request} B/req")
        print(f"wire     prefill->decode int8  "
              f"{point['tokens_per_sec']:9.1f} tok/s  "
              f"{bpr} KV B/req vs {model_bytes_per_request} "
              f"model-dtype ({point['transfer_bytes_ratio']:.2f}x, "
              f"token-exact vs int8 engine)", flush=True)
        results.append(point)
    finally:
        for server in servers:
            server.stop()

    # ---- point 3: kill -> WAL redelivery, recovery TTFT
    tmpdir = tempfile.mkdtemp(prefix="pmdt_wire_bench_")
    journals = [heal.RequestJournal(
        os.path.join(tmpdir, f"wal{i}.jsonl")) for i in range(2)]
    router, servers, replicas = socket_fleet(journals=journals)
    t_death = None
    t_recover = None
    try:
        for i, p in enumerate(prompts):
            router.submit(p, new_tokens, uid=f"u{i}")
        for _ in range(3):
            router.step()  # tokens into both WALs before the kill
        victim = max(replicas, key=lambda r: r.in_flight)
        servers[replicas.index(victim)].kill()
        while router.in_flight:
            before = router.requests_redelivered
            t_pre = time.perf_counter()
            events = router.step()
            if (router.requests_redelivered > before
                    and t_death is None):
                # reap + WAL read + replay happen inside this one
                # step: clock recovery from the step's start
                t_death = t_pre
            if t_death is not None and t_recover is None:
                redelivered = set(router.redelivered_uids)
                for request, _tok, _done in events:
                    if request.uid in redelivered:
                        t_recover = time.perf_counter()
                        break
        recs = router.records()
        for i in range(n_req):
            r = recs[f"u{i}"]
            assert r.state == "done" and \
                list(r.tokens) == ref_tokens[i], (
                    f"post-kill stream u{i} diverged")
        merged = router.merged_metrics()
        assert merged["tokens_generated"] == total_tokens, (
            "redelivery dedup broke the fleet token count")
        point = {
            "mode": "wire_kill", "slots": slots, "requests": n_req,
            "redelivered": router.requests_redelivered,
            "replayed_tokens": router.redelivery_replayed_tokens,
            "recovery_ttft_s": (t_recover - t_death
                                if t_recover and t_death else None),
            "token_exact": True,
        }
        rec_s = point["recovery_ttft_s"]
        print(f"wire     kill dead=1  "
              f"redelivered={point['redelivered']}  recovery_ttft="
              f"{rec_s if rec_s is None else round(rec_s, 4)} s",
              flush=True)
        results.append(point)
    finally:
        for server in servers:
            server.stop()
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return results


def run_autoscale_sweep(model, params, args, rng):
    """graftscale (sweep 10): the elastic-fleet evidence — (1) a
    BURSTY arrival trace (square-wave offered load) and (2) a
    DIURNAL one (ramp up, plateau, ramp down) each drive the
    autoscaler over a 1..3-replica fleet: replicas-over-time, shed
    rate, and TTFT p50/p99 ACROSS the scale events land in the
    record; (3) a rolling v1->v2 weight rollout under steady load:
    duration on the clock, zero failed requests, every stream
    byte-exact to one version."""
    from pytorch_multiprocessing_distributed_tpu.serving import (
        EngineReplicaSpawner, FleetAutoscaler, FleetSaturated,
        RollingRollout, Router, ServingEngine, ServingReplica,
        init_params)

    new_tokens = max(4, min(args.new_tokens, 8))
    prompt_hi = max(2, min(args.prompt_max,
                           model.max_seq_len - new_tokens) - 1)
    s_max = min(model.max_seq_len, prompt_hi + new_tokens)
    slots = int(args.slots.split(",")[0])
    prompts = [rng.integers(0, model.vocab_size, (int(rng.integers(
        max(1, prompt_hi // 2), prompt_hi + 1)),)).tolist()
        for _ in range(8)]
    versions = {"v1": params, "v2": init_params(model, 2)}

    def mk(tag="v1"):
        return ServingEngine(model, versions[tag], max_slots=slots,
                             s_max=s_max, decode_buckets=(),
                             retry_backoff_s=0.0)

    def mk_fleet(n=1, **scale_kw):
        router = Router(
            [ServingReplica(f"r{i}", mk(), model_tag="v1")
             for i in range(n)], max_pending=4)
        scale_kw.setdefault("min_replicas", n)
        scale_kw.setdefault("max_replicas", 3)
        scale_kw.setdefault("up_after", 2)
        scale_kw.setdefault("down_after", 8)
        scale_kw.setdefault("cooldown", 4)
        scaler = FleetAutoscaler(
            router, EngineReplicaSpawner(
                lambda tag, journal: mk(tag or "v1")),
            model_tag="v1", sleep=lambda s: None, **scale_kw)
        return router, scaler

    # arrival traces: offered requests per tick
    def bursty(t):
        return 3 if (t // 20) % 2 == 0 else 0  # square wave

    def diurnal(t):
        # ramp 0 -> peak -> 0 over the trace (the day curve)
        period = 80
        phase = (t % period) / period
        return round(3 * min(phase, 1 - phase) * 2)

    results = []
    for trace_name, trace in (("bursty", bursty),
                              ("diurnal", diurnal)):
        router, scaler = mk_fleet(1)
        router.submit(list(prompts[0]), 2, uid="warm0")
        while router.in_flight:  # compiles off the clock
            router.step()
        uid, shed = 0, 0
        replicas_over_time = [(0, 1)]
        t0 = time.perf_counter()
        for t in range(80):
            for _ in range(trace(t)):
                try:
                    router.submit(
                        list(prompts[uid % len(prompts)]),
                        new_tokens, uid=f"u{uid}")
                    uid += 1
                except FleetSaturated:
                    shed += 1
            router.step()
            scaler.tick()
            if replicas_over_time[-1][1] != len(router.replicas):
                replicas_over_time.append(
                    (t + 1, len(router.replicas)))
        steps, idle_tail = 80, 0
        while (router.in_flight or router.pending_depth
               or idle_tail < 30):  # tail: let scale-down fire too
            if not (router.in_flight or router.pending_depth):
                idle_tail += 1
            router.step()
            scaler.tick()
            steps += 1
            if replicas_over_time[-1][1] != len(router.replicas):
                replicas_over_time.append(
                    (steps, len(router.replicas)))
        wall_s = time.perf_counter() - t0
        finished = [r for u, r in router.records().items()
                    if not str(u).startswith("warm")
                    and r.state == "done"
                    and r.first_token_time is not None]
        ttfts = [r.first_token_time - r.submit_time
                 for r in finished]
        point = {
            "mode": "autoscale", "trace": trace_name,
            "slots": slots, "offered": uid + shed,
            "completed": len(finished),
            "shed": shed,
            "shed_rate": shed / max(1, uid + shed),
            "scale_ups": scaler.scale_ups,
            "scale_downs": scaler.scale_downs,
            "peak_replicas": max(n for _, n in replicas_over_time),
            "replicas_over_time": replicas_over_time,
            "scale_events": [e.to_dict() for e in scaler.events],
            "ttft_p50_ms": 1e3 * _percentile(ttfts, 50),
            "ttft_p99_ms": 1e3 * _percentile(ttfts, 99),
            "wall_s": wall_s,
        }
        assert len(finished) == uid, (
            f"{trace_name}: {uid - len(finished)} admitted "
            "request(s) never completed")
        print(f"autoscale {trace_name:8s}  peak={point['peak_replicas']} "
              f"replicas  ups={scaler.scale_ups} "
              f"downs={scaler.scale_downs}  "
              f"shed={100 * point['shed_rate']:4.1f}%  "
              f"ttft p99={point['ttft_p99_ms']:7.1f} ms", flush=True)
        results.append(point)

    # ---- rolling rollout under steady load, duration on the clock
    router, scaler = mk_fleet(2, cooldown=0, down_after=50)
    router.submit(list(prompts[0]), 2, uid="warm0")
    while router.in_flight:
        router.step()
    ref = {}
    for tag in ("v1", "v2"):
        out = mk(tag).serve([(list(p), new_tokens) for p in prompts])
        ref[tag] = {tuple(prompts[i]): list(r.tokens)
                    for i, r in enumerate(out)}
    rollout = RollingRollout(scaler, "v2")
    uid = 0
    total = 3 * len(prompts)
    for _ in range(5000):
        if uid < total:
            try:
                router.submit(list(prompts[uid % len(prompts)]),
                              new_tokens, uid=f"u{uid}")
                uid += 1
            except FleetSaturated:
                pass
        router.step()
        scaler.tick()
        rollout.tick()
        if (rollout.done and uid >= total and not router.in_flight
                and not router.pending_depth):
            break
    recs = {u: r for u, r in router.records().items()
            if not u.startswith("warm")}
    failed = [u for u, r in recs.items() if r.state != "done"]
    mixed = [u for u, r in recs.items()
             if list(r.tokens) not in (
                 ref["v1"].get(tuple(r.prompt)),
                 ref["v2"].get(tuple(r.prompt)))]
    assert rollout.done and not failed and not mixed, (
        f"rollout: done={rollout.done} failed={failed} "
        f"mixed-version={mixed}")
    point = {
        "mode": "rollout", "slots": slots, "requests": len(recs),
        "replaced": rollout.replaced,
        "duration_s": rollout.duration_s,
        "failed": 0, "version_exact": True,
    }
    print(f"rollout  v1->v2  {len(rollout.replaced)} replica(s) in "
          f"{rollout.duration_s:6.2f}s under load  "
          f"({len(recs)} streams, 0 failed, version-exact)",
          flush=True)
    results.append(point)
    return results


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt_small")
    p.add_argument("--requests", default=32, type=int)
    p.add_argument("--prompt_max", default=96, type=int,
                   help="ragged prompt lengths drawn in "
                        "[prompt_max//4, prompt_max]")
    p.add_argument("--new_tokens", default=64, type=int)
    p.add_argument("--slots", default="2,4,8", type=str)
    p.add_argument("--offered", default="inf,8", type=str,
                   help="offered loads in requests/sec ('inf' = all "
                        "submitted up front)")
    p.add_argument("--sweep", default="load,length,horizon", type=str,
                   help="which sweeps to run: load, length, horizon, "
                        "chaos, drain, paged, spec, fleet, wire, "
                        "autoscale, quant, or "
                        "any comma list")
    p.add_argument("--chaos_every", default=5, type=int,
                   help="chaos sweep: inject one transient fault every "
                        "K-th dispatch ATTEMPT, K >= 2 (realized "
                        "per-dispatch rate 1/(K-1): each recovered "
                        "fault adds one retry attempt)")
    p.add_argument("--len_dist", default="short,long,mixed", type=str,
                   help="length-sweep prompt distributions")
    p.add_argument("--prefill_chunk", default=32, type=int,
                   help="length sweep: admit prompts in chunks of N "
                        "(0 = whole-prompt)")
    p.add_argument("--horizons", default="1,4,8", type=str,
                   help="horizon-sweep decode_horizon values")
    p.add_argument("--page_size", default=8, type=int,
                   help="paged sweep: KV page size (columns per page)")
    p.add_argument("--horizon_repeats", default=3, type=int,
                   help="horizon sweep: best-of-N runs per point "
                        "(host-noise suppression)")
    p.add_argument("--spec_ks", default="0,2,4,8", type=str,
                   help="spec sweep: draft lengths k (0 = the "
                        "non-speculative baseline the k>0 points "
                        "must not regress when disarmed)")
    p.add_argument("--spec_modes", default="self,model", type=str,
                   help="spec sweep: draft sources (self = n-gram "
                        "self-drafting, model = draft model)")
    p.add_argument("--draft_model", default="", type=str,
                   help="spec sweep: registry name of the draft "
                        "model ('' = off-TPU smoke uses the target "
                        "as its own draft)")
    p.add_argument("--json_out", default="", type=str,
                   help="record every sweep point as JSON")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.serving import (
        init_params)

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if platform != "tpu":
        args.model = "gpt_tiny"
        args.requests = min(args.requests, 8)
        args.prompt_max = min(args.prompt_max, 24)
        args.new_tokens = min(args.new_tokens, 8)
        args.prefill_chunk = min(args.prefill_chunk, 8)
        dtype = jnp.float32
    model = models.get_model(
        args.model, dtype=dtype,
        attn_impl="flash" if platform == "tpu" else "xla")
    params = init_params(model)
    rng = np.random.default_rng(0)
    s_max = min(model.max_seq_len, args.prompt_max + args.new_tokens)
    # prompts must pass static-fit admission: len + new_tokens <= s_max
    prompt_hi = s_max - args.new_tokens
    if prompt_hi < 1:
        raise SystemExit(
            f"--new_tokens {args.new_tokens} leaves no room for a "
            f"prompt within s_max={s_max} "
            f"(max_seq_len={model.max_seq_len})")
    print(f"# platform={platform} model={args.model} "
          f"requests={args.requests} prompt<= {args.prompt_max} "
          f"new={args.new_tokens} s_max={s_max}")

    record = {"platform": platform, "model": args.model,
              "requests": args.requests, "new_tokens": args.new_tokens,
              "s_max": s_max, "load_sweep": [], "length_sweep": [],
              "horizon_sweep": [], "chaos_sweep": [], "drain_sweep": [],
              "paged_sweep": [], "spec_sweep": [], "fleet_sweep": [],
              "wire_sweep": [], "autoscale_sweep": [],
              "quant_sweep": []}
    sweeps = args.sweep.split(",")

    if "load" in sweeps:
        prompts = [
            rng.integers(0, model.vocab_size,
                         (int(rng.integers(max(1, prompt_hi // 4),
                                           prompt_hi + 1)),)).tolist()
            for _ in range(args.requests)]
        for slots in [int(x) for x in args.slots.split(",")]:
            for load in args.offered.split(","):
                rps = float("inf") if load == "inf" else float(load)
                r = run_point(model, params, prompts, args.new_tokens,
                              slots, rps, s_max)
                r.update(slots=slots, offered=load)
                record["load_sweep"].append(r)
                print(f"slots={slots:3d} offered={load:>5s} req/s  "
                      f"completed={r['completed']:3d}  "
                      f"{r['tokens_per_sec']:9.1f} tok/s  "
                      f"ttft avg={r['ttft_avg_ms']:8.1f} ms "
                      f"p95={r['ttft_p95_ms']:8.1f} ms  "
                      f"occ={r['occupancy_avg']:5.2f} "
                      f"queue={r['queue_depth_avg']:5.2f} "
                      f"(compiles={r['decode_compiles']})", flush=True)

    if "length" in sweeps:
        record["length_sweep"] = run_length_sweep(
            model, params, args, s_max, prompt_hi, rng)

    if "horizon" in sweeps:
        record["horizon_sweep"] = run_horizon_sweep(
            model, params, args, rng)

    if "paged" in sweeps:
        record["paged_sweep"] = run_paged_sweep(model, params, args,
                                                rng)

    if "spec" in sweeps:
        record["spec_sweep"] = run_spec_sweep(model, params, args,
                                              rng)

    if "chaos" in sweeps:
        record["chaos_sweep"] = run_chaos_sweep(model, params, args,
                                                rng)

    if "drain" in sweeps:
        record["drain_sweep"] = run_drain_sweep(model, params, args,
                                                rng)

    if "fleet" in sweeps:
        record["fleet_sweep"] = run_fleet_sweep(model, params, args,
                                                rng)

    if "wire" in sweeps:
        record["wire_sweep"] = run_wire_sweep(model, params, args,
                                              rng)

    if "autoscale" in sweeps:
        record["autoscale_sweep"] = run_autoscale_sweep(
            model, params, args, rng)

    if "quant" in sweeps:
        record["quant_sweep"] = run_quant_sweep(model, params, args,
                                                rng)

    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
        print(f"# wrote {args.json_out}", flush=True)


if __name__ == "__main__":
    main()
