"""Serving-engine throughput: offered load vs tokens/sec and TTFT.

Drives the continuous-batching :class:`ServingEngine` with an
open-loop request stream (arrival times fixed in advance — the load
does NOT slow down when the server lags, which is what "heavy traffic"
means) at several slot counts, and reports per-point:

- delivered tokens/sec (decode throughput across the run);
- TTFT mean/p95 (submit -> first token, queueing included);
- mean slot occupancy and queue depth (is the pool or the arrival
  process the bottleneck?).

``offered=inf`` is the closed-loop limit: every request submitted
up front, measuring peak engine throughput. CPU-runnable (shapes clamp
down off-TPU, same convention as ``generate_bench.py``), TPU-ready.

Run: ``python benchmarks/serving_bench.py [--model gpt_small]
[--slots 2,4,8] [--offered inf,8]``
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import benchmarks._common as _common  # noqa: E402


def _percentile(values, q):
    return float(np.percentile(np.asarray(values), q)) if values else 0.0


def run_point(model, params, prompts, new_tokens, slots, offered_rps,
              s_max):
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine)

    engine = ServingEngine(model, params, max_slots=slots, s_max=s_max)
    # arrival schedule: evenly spaced at the offered rate (inf = all at
    # t=0). Open loop — lateness accumulates if the engine can't keep up
    arrivals = ([0.0] * len(prompts) if offered_rps == float("inf")
                else [i / offered_rps for i in range(len(prompts))])
    t_start = time.perf_counter()
    pending = list(zip(prompts, arrivals))
    finished = []
    while pending or engine.scheduler.queue_depth or engine.pool.occupancy:
        now = time.perf_counter() - t_start
        while pending and pending[0][1] <= now:
            prompt, _ = pending.pop(0)
            engine.submit(prompt, new_tokens)
        if engine.scheduler.queue_depth or engine.pool.occupancy:
            for request, _, done in engine.step():
                if done:
                    finished.append(request)
        elif pending:
            time.sleep(min(0.005, pending[0][1] - now))
    wall = time.perf_counter() - t_start
    ttfts = [r.first_token_time - r.submit_time for r in finished]
    total_tokens = sum(len(r.tokens) for r in finished)
    return {
        "completed": len(finished),
        "wall_s": wall,
        "tokens_per_sec": total_tokens / wall,
        "ttft_avg_ms": 1e3 * float(np.mean(ttfts)),
        "ttft_p95_ms": 1e3 * _percentile(ttfts, 95),
        "occupancy_avg": engine.metrics.occupancy.avg,
        "queue_depth_avg": engine.metrics.queue_depth.avg,
        "decode_compiles": engine.decode_step_compiles,
    }


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt_small")
    p.add_argument("--requests", default=32, type=int)
    p.add_argument("--prompt_max", default=96, type=int,
                   help="ragged prompt lengths drawn in "
                        "[prompt_max//4, prompt_max]")
    p.add_argument("--new_tokens", default=64, type=int)
    p.add_argument("--slots", default="2,4,8", type=str)
    p.add_argument("--offered", default="inf,8", type=str,
                   help="offered loads in requests/sec ('inf' = all "
                        "submitted up front)")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.serving import (
        init_params)

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    if platform != "tpu":
        args.model = "gpt_tiny"
        args.requests = min(args.requests, 8)
        args.prompt_max = min(args.prompt_max, 24)
        args.new_tokens = min(args.new_tokens, 8)
        dtype = jnp.float32
    model = models.get_model(
        args.model, dtype=dtype,
        attn_impl="flash" if platform == "tpu" else "xla")
    params = init_params(model)
    rng = np.random.default_rng(0)
    s_max = min(model.max_seq_len, args.prompt_max + args.new_tokens)
    # prompts must pass static-fit admission: len + new_tokens <= s_max
    prompt_hi = s_max - args.new_tokens
    if prompt_hi < 1:
        raise SystemExit(
            f"--new_tokens {args.new_tokens} leaves no room for a "
            f"prompt within s_max={s_max} "
            f"(max_seq_len={model.max_seq_len})")
    prompts = [
        rng.integers(0, model.vocab_size,
                     (int(rng.integers(max(1, prompt_hi // 4),
                                       prompt_hi + 1)),)).tolist()
        for _ in range(args.requests)]
    print(f"# platform={platform} model={args.model} "
          f"requests={args.requests} prompt<= {args.prompt_max} "
          f"new={args.new_tokens} s_max={s_max}")

    for slots in [int(x) for x in args.slots.split(",")]:
        for load in args.offered.split(","):
            rps = float("inf") if load == "inf" else float(load)
            r = run_point(model, params, prompts, args.new_tokens,
                          slots, rps, s_max)
            print(f"slots={slots:3d} offered={load:>5s} req/s  "
                  f"completed={r['completed']:3d}  "
                  f"{r['tokens_per_sec']:9.1f} tok/s  "
                  f"ttft avg={r['ttft_avg_ms']:8.1f} ms "
                  f"p95={r['ttft_p95_ms']:8.1f} ms  "
                  f"occ={r['occupancy_avg']:5.2f} "
                  f"queue={r['queue_depth_avg']:5.2f} "
                  f"(compiles={r['decode_compiles']})", flush=True)


if __name__ == "__main__":
    main()
