"""graftlife smoke: a churny fleet soak under the armed ownership
ledger — every resource class audits EMPTY at the end.

The ``make life`` target drives a deliberately messy serving run:

1. **churn** — a journaled replica behind the router plus a
   :class:`FleetAutoscaler` that JOINS replicas under a burst and
   LEAVES them on the idle plateau; requests submitted with a mix of
   plentiful and already-hopeless deadlines (deadline evictions),
   two mid-run ``ServingEngine.withdraw`` calls (client
   abandonment), and the backlog imbalance that triggers work
   stealing;
2. **death** — one injected engine-fatal
   (``serving.decode_dispatch``, the existing graftfault site) kills
   a replica mid-stream: its WAL redelivers to a peer, its slots and
   pages hard-reclaim at the reap, its WAL's file handle closes;
3. **the audit** — after ``Router.drain`` the
   :class:`~pytorch_multiprocessing_distributed_tpu.runtime.life.
   OwnershipLedger` must be EMPTY for every kind (slots, pages,
   buffers, journal admissions, transfers, sockets, threads, files)
   and every realized acquire site must be one the static model
   (``analysis/lifecycle.py``) admits. Any leak is a named finding
   with holder/site/age — and a failed smoke.

Exit code 0 and one ``graftlife smoke OK`` line = drained means
empty, audited. Run: ``python benchmarks/life_smoke.py``
(CPU-runnable; tiny model, seconds).
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_smoke(verbose: bool = True) -> dict:
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        faults, heal, life)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        EngineReplicaSpawner, FleetAutoscaler, FleetSaturated,
        Router, ServingEngine, ServingReplica, init_params)

    def note(msg):
        if verbose:
            print(msg, flush=True)

    model = models.GPT(vocab_size=61, max_seq_len=64, hidden_size=32,
                       num_layers=2, num_heads=2, mlp_dim=64,
                       attn_impl="xla")
    params = init_params(model, 1)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, model.vocab_size, (n,)).tolist()
               for n in (3, 7, 12, 5, 9, 6, 4, 8)]

    def mk_engine(tag="r0", journal=None):
        return ServingEngine(model, params, max_slots=2, s_max=32,
                             min_bucket=8, retry_backoff_s=0.0,
                             kv_layout="paged", page_size=8,
                             journal=journal)

    tmp = tempfile.mkdtemp(prefix="graftlife_smoke_")
    summary = {}
    with life.armed() as led:
        journal = heal.RequestJournal(
            os.path.join(tmp, "wal0.jsonl"))
        router = Router([ServingReplica(
            "r0", mk_engine(journal=journal), journal=journal)],
            max_pending=4)
        scaler = FleetAutoscaler(
            router, EngineReplicaSpawner(
                lambda tag, journal: mk_engine(tag)),
            min_replicas=1, max_replicas=3, up_after=2, down_after=6,
            cooldown=3, sleep=lambda s: None)

        note("phase 1: burst churn (joins, deadlines, withdraws, "
             "steals)")
        uid = 0
        withdrawn = []
        for tick in range(30):
            for _ in range(2):
                try:
                    deadline = 1e-4 if uid % 7 == 3 else None
                    router.submit(
                        list(prompts[uid % len(prompts)]), 6,
                        uid=f"u{uid}", deadline_s=deadline)
                    uid += 1
                except FleetSaturated:
                    pass
            router.step()
            scaler.tick()
            if tick == 12:
                # client abandonment: withdraw two PLACED requests
                # wherever they sit (running, pending, or queued)
                for cand, rid in list(router._assigned.items()):
                    if len(withdrawn) >= 2:
                        break
                    rec = router.records().get(cand)
                    if rec is None or rec.state in ("done", "failed"):
                        continue
                    rep = next(r for r in router.replicas
                               if r.rid == rid)
                    if rep.engine.withdraw(cand):
                        withdrawn.append(cand)
        assert scaler.scale_ups >= 1, "burst never grew the fleet"
        assert len(withdrawn) == 2, "withdraw found no live target"

        note("phase 2: one injected replica death mid-stream")
        plan = faults.FaultPlan(seed=3, rules=[faults.FaultRule(
            "serving.decode_dispatch", "fatal", times=1)])
        faults.arm(plan)
        try:
            steps = 0
            while (router.in_flight or router.pending_depth) \
                    and steps < 5000:
                router.step()
                scaler.tick()
                steps += 1
        finally:
            faults.disarm()
        assert router.requests_redelivered >= 1, (
            "the injected death never redelivered")
        for _ in range(60):  # idle plateau: scale back down (leaves)
            router.step()
            scaler.tick()
        assert len(router.replicas) == 1, "idle fleet must shrink"

        note("phase 3: drain + the audit")
        router.drain(None)
        recs = router.records()
        states = {}
        for r in recs.values():
            states[r.state] = states.get(r.state, 0) + 1
        findings = led.audit_drained("life smoke drain")
        assert findings == [], "\n".join(findings)
        site_findings = led.audit_sites()
        assert site_findings == [], "\n".join(site_findings)
        counts = led.counts()
        assert not any(counts.values()), counts
        summary = {
            "submitted": uid,
            "states": states,
            "withdrawn": len(withdrawn),
            "deaths": sum(r.reaped for r in router.replicas),
            "redelivered": router.requests_redelivered,
            "scale_ups": scaler.scale_ups,
            "acquired": dict(led.acquired),
            "released": dict(led.released),
            "leaked": counts,
        }
    note(f"summary: {summary}")
    note("graftlife smoke OK")
    return summary


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args()
    run_smoke(verbose=not args.quiet)
    return 0


if __name__ == "__main__":
    sys.exit(main())
