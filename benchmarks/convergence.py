"""Convergence parity: this framework vs torch, SAME init, SAME batches.

The strongest "matching top-1" evidence available in a zero-egress
environment (no CIFAR download): train the reference's ResNet-18
([1,1,1,1]) in BOTH frameworks from identical weights (exported via
``utils.torch_interop``) on the identical augmented batch sequence
(both sides replay the framework loader's deterministic epochs), with
the reference optimizer (SGD lr 0.1 / momentum 0.9 / wd 1e-4 /
nesterov). Any trajectory gap is then pure framework semantics —
exactly what "the accuracy matches torch" must mean when the dataset is
fixed. On a real chip the framework side runs on TPU while torch stays
on CPU, making this the cross-hardware convergence check BASELINE.md
asks for.

Measured step-level parity (CPU, identical init/batch): step-0 loss
agrees to ~4e-6 relative; later steps diverge chaotically (x~40/step
amplification at lr 0.1 nesterov — float implementation differences,
not semantics; the framework's optimizer/BN are separately test-pinned
torch-exact). The meaningful convergence claim is therefore the
ACCURACY level both sides reach, recorded here per epoch.

Writes ``benchmarks/convergence_record.json`` and prints a one-line
JSON summary.

Run: ``python benchmarks/convergence.py [--epochs 5] [--train_size 2048]``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import benchmarks._common as _common  # noqa: E402

RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "convergence_record.json")


def make_loaders(args):
    from pytorch_multiprocessing_distributed_tpu.data.cifar import (
        synthetic_cifar10)
    from pytorch_multiprocessing_distributed_tpu.data.pipeline import (
        ShardedLoader)

    tr_x, tr_y = synthetic_cifar10(args.train_size, seed=0)
    te_x, te_y = synthetic_cifar10(max(1, args.train_size // 4), seed=1)

    def loaders():
        train = ShardedLoader(
            tr_x, tr_y, batch_size=args.batch_size, world_size=1,
            train=True, seed=0)
        test = ShardedLoader(
            te_x, te_y, batch_size=args.batch_size, world_size=1,
            train=False, shuffle=True, seed=0, with_valid=True)
        return train, test

    return loaders


def run_framework(args, loaders):
    import jax
    import jax.numpy as jnp

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh
    from pytorch_multiprocessing_distributed_tpu.train import (
        create_train_state, make_eval_step, make_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.train.step import shard_batch

    from pytorch_multiprocessing_distributed_tpu.train.optim import (
        multistep_lr)

    mesh = make_mesh(1, devices=jax.devices()[:1])
    model = models.get_model("res", bn_axis="data")
    # reference config: lr .1, momentum .9, wd 1e-4, nesterov (+ the
    # reference's MultiStepLR when --milestones is given — scaled-down
    # milestones make the terminal state stable, see main())
    lr = (multistep_lr(0.1, milestones=args.milestones)
          if args.milestones else 0.1)
    opt = sgd(learning_rate=lr)
    state = create_train_state(
        model, jax.random.PRNGKey(args.seed), jnp.zeros((2, 32, 32, 3)),
        opt)
    init_export = (jax.device_get(state.params),
                   jax.device_get(state.batch_stats))
    train_step = make_train_step(model, opt, mesh)
    eval_step = make_eval_step(model, mesh)

    train, test = loaders()
    accs, losses = [], []
    for epoch in range(1, args.epochs + 1):
        state = state.replace(epoch=jnp.asarray(epoch, jnp.int32))
        train.set_epoch(epoch)
        test.set_epoch(epoch)
        ep_loss = []
        for images, labels in train:
            batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)),
                                mesh)
            state, metrics = train_step(state, *batch)
            ep_loss.append(float(np.asarray(metrics["loss"])))
        correct = total = 0
        for images, labels, valid in test:
            batch = shard_batch(
                (jnp.asarray(images), jnp.asarray(labels),
                 jnp.asarray(valid)), mesh)
            m = eval_step(state, *batch)
            correct += int(np.asarray(m["correct"]))
            total += int(np.asarray(m["count"]))
        accs.append(100.0 * correct / max(1, total))
        losses.append(float(np.mean(ep_loss)))
        print(f"[framework] epoch {epoch}: loss {losses[-1]:.4f} "
              f"acc {accs[-1]:.2f}%", file=sys.stderr, flush=True)
    return init_export, losses, accs


def run_torch(args, loaders, init_export):
    import torch
    import torch.nn.functional as F

    from pytorch_multiprocessing_distributed_tpu.utils.torch_interop import (
        to_torch_state_dict, torch_functional_forward)

    params, stats = init_export
    sd = {}
    learnable = []
    for key, val in to_torch_state_dict(params, stats).items():
        t = torch.from_numpy(np.ascontiguousarray(val))
        if key.endswith(("running_mean", "running_var",
                         "num_batches_tracked")):
            sd[key] = t
        else:
            t.requires_grad_(True)
            sd[key] = t
            learnable.append(t)
    optimizer = torch.optim.SGD(learnable, lr=0.1, momentum=0.9,
                                weight_decay=1e-4, nesterov=True)

    train, test = loaders()
    accs, losses = [], []
    for epoch in range(1, args.epochs + 1):
        if args.milestones:
            # the framework side's exact schedule (train.optim.
            # multistep_lr = the reference's top-of-epoch
            # scheduler.step() semantics) evaluated for torch — ONE
            # formula, no drift
            from pytorch_multiprocessing_distributed_tpu.train.optim import (
                multistep_lr)

            lr = float(multistep_lr(
                0.1, milestones=args.milestones)(epoch))
            for g in optimizer.param_groups:
                g["lr"] = lr
        train.set_epoch(epoch)
        test.set_epoch(epoch)
        ep_loss = []
        for images, labels in train:
            x = torch.from_numpy(
                np.ascontiguousarray(images.transpose(0, 3, 1, 2)))
            y = torch.from_numpy(np.ascontiguousarray(labels)).long()
            logits = torch_functional_forward(sd, x, train=True)
            loss = F.cross_entropy(logits, y)
            optimizer.zero_grad()
            loss.backward()
            optimizer.step()
            ep_loss.append(float(loss.detach()))
        correct = total = 0
        with torch.no_grad():
            for images, labels, valid in test:
                x = torch.from_numpy(
                    np.ascontiguousarray(images.transpose(0, 3, 1, 2)))
                pred = torch_functional_forward(sd, x).argmax(-1).numpy()
                correct += int(((pred == labels) & valid).sum())
                total += int(valid.sum())
        accs.append(100.0 * correct / max(1, total))
        losses.append(float(np.mean(ep_loss)))
        print(f"[torch]     epoch {epoch}: loss {losses[-1]:.4f} "
              f"acc {accs[-1]:.2f}%", file=sys.stderr, flush=True)
    return losses, accs


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", default=5, type=int)
    p.add_argument("--batch_size", default=64, type=int)
    p.add_argument("--train_size", default=2048, type=int)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--milestones", default="", type=str,
                   help="comma-separated MultiStepLR epochs (e.g. '6,8' "
                        "with --epochs 10): the reference's own decay, "
                        "scaled down so the terminal state is STABLE — "
                        "at constant lr 0.1 per-epoch accuracy "
                        "oscillates once the set is memorized and the "
                        "final-epoch comparison is a noisy sample "
                        "(VERDICT r4 weak #3)")
    args = p.parse_args()
    args.milestones = ([int(x) for x in args.milestones.split(",")]
                       if args.milestones else [])

    import jax

    platform = jax.devices()[0].platform
    loaders = make_loaders(args)
    t0 = time.time()
    init_export, fw_loss, fw_acc = run_framework(args, loaders)
    fw_s = time.time() - t0
    t0 = time.time()
    th_loss, th_acc = run_torch(args, loaders, init_export)
    th_s = time.time() - t0

    record = {
        "platform": platform,
        "epochs": args.epochs,
        "batch_size": args.batch_size,
        "train_size": args.train_size,
        "milestones": args.milestones,
        "dataset": "synthetic_cifar10 (zero-egress environment)",
        "identical_init": True,
        "identical_batches": True,
        "framework": {"loss": fw_loss, "acc": fw_acc,
                      "seconds": round(fw_s, 1)},
        "torch_cpu": {"loss": th_loss, "acc": th_acc,
                      "seconds": round(th_s, 1)},
        # With --milestones the protocol's terminal state is stable
        # (post-decay both sides sit on the memorized set), so the
        # FINAL-epoch delta is the headline; best-epoch is kept for
        # comparability with older records. Without decay the final
        # epoch is a noisy sample of the lr-0.1 oscillation.
        "best_acc_delta": round(max(fw_acc) - max(th_acc), 3),
        "final_acc_delta": round(fw_acc[-1] - th_acc[-1], 3),
    }
    with open(RECORD, "w") as f:
        json.dump(record, f, indent=2)
    # headline follows the protocol: with a decay-stabilized terminal
    # state the FINAL epoch is the evidence; without decay only the
    # best epoch is meaningful (see the record comment above)
    if args.milestones:
        metric = ("resnet18_convergence_final_acc_delta_vs_torch",
                  record["final_acc_delta"], "best_acc_delta")
    else:
        metric = ("resnet18_convergence_best_acc_delta_vs_torch",
                  record["best_acc_delta"], "final_acc_delta")
    name, value, other = metric
    print(json.dumps({
        "metric": name,
        "value": value,
        "unit": "percentage points",
        "extra": {**{k: record[k] for k in
                     ("platform", "epochs", "train_size", "milestones")},
                  other: record[other]},
    }))


if __name__ == "__main__":
    main()
