"""graftroute smoke: a 2-replica fleet over an in-process store must
serve, survive a replica death, and route a warm prefix — end to end.

The ``make route`` target (and the tier-1 test that drives this module
in-process) builds two paged engine replicas behind one
:class:`~pytorch_multiprocessing_distributed_tpu.serving.Router` over
a ``MemStore`` (the same client surface the real C++ ``TCPStore``
serves), then asserts:

1. **byte-identity** — every routed stream equals the single-engine
   baseline, request for request;
2. **death → redelivery** — one injected engine-fatal
   (``serving.decode_dispatch``, the existing graftfault site) kills
   a replica mid-run; its journal's unfinished requests redeliver to
   the peer under their ORIGINAL uids, every stream still byte-exact,
   and the fleet-level ``tokens_generated`` merge is
   redelivery-deduped to the unique token count;
3. **warm prefix routing** — a prompt served once registers in the
   fleet :class:`PrefixCacheDirectory`; an identical prompt routes to
   the HOLDING replica and admits as an engine-level prefix-cache
   FULL hit (no prefill compute), with its TTFT beating the cold
   replica's;
4. **directory + health surfaces** — the store-published replica
   directory (``runtime.fleet.publish_replica`` /
   ``replica_directory``) lists both replicas with roles/states, and
   ``Router.healthz`` aggregates per-replica ``state_name``.

Exit code 0 and one ``graftroute smoke OK`` line = the fleet serving
stack is wired. Run: ``python benchmarks/route_smoke.py``
(CPU-runnable; tiny model, seconds).
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_smoke(verbose: bool = True) -> dict:
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        faults, fleet as graftfleet, heal)
    from pytorch_multiprocessing_distributed_tpu.runtime.store import (
        MemStore)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        Router, ServingEngine, ServingReplica, init_params)

    def note(msg):
        if verbose:
            print(msg, flush=True)

    model = models.GPT(vocab_size=61, max_seq_len=64, hidden_size=32,
                       num_layers=2, num_heads=2, mlp_dim=64,
                       attn_impl="xla")
    params = init_params(model, 1)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.vocab_size,
                            (int(rng.integers(4, 20)),)).tolist()
               for _ in range(6)]

    def mk(journal=None):
        return ServingEngine(model, params, max_slots=2, s_max=32,
                             min_bucket=8, kv_layout="paged",
                             page_size=8, prefix_cache=4,
                             retry_backoff_s=0.0, dispatch_retries=1,
                             journal=journal)

    # ---- single-engine baseline (the byte-identity reference)
    base = mk()
    ref = {f"u{i}": list(r.tokens) for i, r in enumerate(
        base.serve((p, 8) for p in prompts))}
    total_unique = sum(len(t) for t in ref.values())

    # ---- 2 replicas over MemStore, journals armed
    store = MemStore()
    tmpdir = tempfile.mkdtemp(prefix="pmdt_route_smoke_")

    def mkrep(i):
        journal = heal.RequestJournal(
            os.path.join(tmpdir, f"wal{i}.jsonl"))
        return ServingReplica(f"r{i}", mk(journal), journal=journal)

    router = Router([mkrep(0), mkrep(1)], store=store,
                    run_uid="smoke")

    # 4. store-published replica directory
    directory = graftfleet.replica_directory(store, run_uid="smoke")
    assert set(directory) == {"r0", "r1"}, directory
    assert all(d["role"] == "both" for d in directory.values())
    note(f"directory: {sorted(directory)} published over MemStore")

    # 2. one injected death mid-run -> journal redelivery to the peer
    for i, p in enumerate(prompts):
        router.submit(p, 8, uid=f"u{i}")
    for _ in range(3):
        router.step()  # tokens into both WALs before the kill
    plan = faults.FaultPlan(seed=7, rules=[faults.FaultRule(
        "serving.decode_dispatch", "fatal", times=1)])
    faults.arm(plan)
    try:
        while router.in_flight:
            router.step()
    finally:
        faults.disarm()
    dead = [r.rid for r in router.replicas if r.reaped]
    assert len(dead) == 1, f"expected exactly one dead replica: {dead}"
    assert router.requests_redelivered >= 1
    recs = router.records()
    for uid, want in ref.items():
        got = list(recs[uid].tokens)
        assert got == want, (
            f"stream {uid} diverged after the replica death: "
            f"{got} vs {want}")
    merged = router.merged_metrics()
    assert merged["tokens_generated"] == total_unique, (
        "redelivery dedup broke the fleet token count: "
        f"{merged['tokens_generated']} vs {total_unique} unique")
    note(f"death: {dead[0]} died, "
         f"{router.requests_redelivered} redelivered to the peer, "
         f"all {len(ref)} streams byte-exact, merged tokens "
         f"{merged['tokens_generated']} == unique {total_unique}")

    # fleet health: survivor READY, dead replica named DEAD
    hz = router.healthz()
    assert hz["state_name"] == "READY"
    assert hz["replicas"][dead[0]]["state_name"] == "DEAD"

    # 3. warm prefix routing: serve once, the identical prompt routes
    # to the holder and admits as a FULL engine-cache hit
    # a FRESH page-aligned prompt (sharing no served prefix — an
    # aligned subprompt of a longer cached one stays a partial hit by
    # the engine cache's own contract)
    warm = rng.integers(0, model.vocab_size, (16,)).tolist()
    router.serve([(warm, 4)])              # registers pages + entry
    # first hit pays the state-splice program's compile; steady-state
    # hits are what the ratio judges
    router.serve([(warm, 4)])
    routed_before = router.prefix_routed
    hits_before = sum(r.engine.metrics.prefix_hits
                      for r in router.replicas)
    # best-of-N on BOTH sides: single-shot millisecond TTFTs on a
    # noisy box flip on scheduler hiccups; the min is the number the
    # cache win actually controls
    warm_ttfts = []
    for _ in range(4):
        rec = router.serve([(warm, 4)])[0]
        warm_ttfts.append(rec.first_token_time - rec.submit_time)
    warm_ttft = min(warm_ttfts)
    assert router.prefix_routed == routed_before + 4, (
        "identical prompt did not route through the directory")
    assert sum(r.engine.metrics.prefix_hits
               for r in router.replicas) == hits_before + 4, (
        "directory-routed prompt was not an engine-level FULL hit")
    # cold TTFT: fresh same-length prompts MISSING the same engine's
    # cache (same replica, same compiled programs — the hit's win is
    # skipped prefill compute, not compile luck)
    cold_ttfts = []
    for _ in range(4):
        cold_prompt = rng.integers(0, model.vocab_size, (16,)).tolist()
        cold_rec = router.serve([(cold_prompt, 4)])[0]
        if cold_rec.first_token_time:
            cold_ttfts.append(cold_rec.first_token_time
                              - cold_rec.submit_time)
    ratio = None
    if cold_ttfts:
        cold_ttft = min(cold_ttfts)
        ratio = warm_ttft / cold_ttft
    note(f"prefix: warm TTFT {warm_ttft * 1e3:.2f} ms"
         + (f" vs cold {cold_ttft * 1e3:.2f} ms "
            f"(ratio {ratio:.2f}, min of 4)"
            if ratio is not None else ""))

    # 1. byte-identity on a FRESH healthy fleet (no faults in play)
    fresh = Router([ServingReplica("a", mk()),
                    ServingReplica("b", mk())])
    out = fresh.serve([(p, 8) for p in prompts])
    for i, r in enumerate(out):
        assert r.state == "done"
        assert list(r.tokens) == ref[f"u{i}"], (
            f"fresh-fleet stream {i} diverged from the baseline")
    note(f"fleet: {len(out)} streams byte-identical to the "
         "single-engine baseline across 2 replicas")

    import shutil

    shutil.rmtree(tmpdir, ignore_errors=True)
    return {
        "dead": dead[0],
        "redelivered": router.requests_redelivered,
        "replayed_tokens": router.redelivery_replayed_tokens,
        "merged_tokens": merged["tokens_generated"],
        "prefix_routed": router.prefix_routed,
        "warm_ttft_s": warm_ttft,
        "ttft_ratio_warm_over_cold": ratio,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.parse_args(argv)
    from pytorch_multiprocessing_distributed_tpu.utils.hostenv import (
        force_cpu_devices_from_env)

    force_cpu_devices_from_env()
    out = run_smoke(verbose=True)
    print(f"graftroute smoke OK ({out['redelivered']} redelivered, "
          f"ratio {out['ttft_ratio_warm_over_cold']})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
