#!/bin/bash
# One-shot TPU evidence capture — run the moment the chip is granted.
# Produces/refreshes every artifact the round needs:
#   benchmarks/baseline_record.json   (record_baselines.py, all configs
#                                      + gpt_lm, two_window_slope tags)
#   benchmarks/attention_bench_tpu.txt (flash vs XLA, fwd+bwd, causal +
#                                      non-causal — backs COVERAGE.md)
#   benchmarks/generate_bench_tpu.txt  (decode tokens/sec)
#   benchmarks/serving_bench_tpu.json  (load + length-bucket sweeps)
#   benchmarks/serving_bench_spec_tpu.json (graftspec accepted/step)
#   benchmarks/serving_bench_quant_tpu.json (graftquant int8-KV
#                                      residency + logit-delta sweep)
#   benchmarks/serving_bench_fleet_tpu.json (graftroute fleet/disagg/
#                                      redelivery sweep)
#   benchmarks/serving_bench_autoscale_tpu.json (graftscale traces +
#                                      rollout sweep)
#   benchmarks/scale_smoke_tpu.json    (graftscale subprocess lifecycle)
#   benchmarks/mfu_tune_results.json   (resnet50 flag/batch sweep)
#   benchmarks/convergence_record.json (framework-on-TPU vs torch-CPU)
# Prints a section header per step; steps are independent — a failure
# moves on so one flaky stage can't void the rest.
set -u
cd "$(dirname "$0")/.." || exit 1
note() { echo "=== $* ($(date -u +%T))" >&2; }

note "fleet observability smoke (graftfleet wiring sane before capture)"
python benchmarks/fleet_smoke.py

note "fleet serving smoke (graftroute wiring sane before capture)"
python benchmarks/route_smoke.py

note "ownership-ledger smoke (graftlife: drained means empty, audited)"
python benchmarks/life_smoke.py

note "baselines (all configs, slope estimator)"
python benchmarks/record_baselines.py

note "attention bench (non-causal)"
python benchmarks/attention_bench.py > benchmarks/attention_bench_tpu.txt 2>&1
note "attention bench (causal)"
python benchmarks/attention_bench.py --causal >> benchmarks/attention_bench_tpu.txt 2>&1
tail -8 benchmarks/attention_bench_tpu.txt >&2

note "generate bench"
python benchmarks/generate_bench.py > benchmarks/generate_bench_tpu.txt 2>&1
tail -4 benchmarks/generate_bench_tpu.txt >&2

note "serving bench (load + length/bucket + decode-horizon sweeps)"
python benchmarks/serving_bench.py \
    --sweep load,length,horizon \
    --json_out benchmarks/serving_bench_tpu.json \
    > benchmarks/serving_bench_tpu.txt 2>&1
tail -20 benchmarks/serving_bench_tpu.txt >&2

note "serving bench (paged KV + prefix cache: dense vs paged at fixed HBM)"
python benchmarks/serving_bench.py \
    --sweep paged \
    --json_out benchmarks/serving_bench_paged_tpu.json \
    > benchmarks/serving_bench_paged_tpu.txt 2>&1
tail -16 benchmarks/serving_bench_paged_tpu.txt >&2

note "serving bench (graftquant: int8 KV vs model-dtype at fixed HBM + wire halving)"
python benchmarks/serving_bench.py \
    --sweep quant \
    --json_out benchmarks/serving_bench_quant_tpu.json \
    > benchmarks/serving_bench_quant_tpu.txt 2>&1
tail -8 benchmarks/serving_bench_quant_tpu.txt >&2

note "serving bench (graftroute: 2-replica fleet + disagg + redelivery)"
python benchmarks/serving_bench.py \
    --sweep fleet \
    --json_out benchmarks/serving_bench_fleet_tpu.json \
    > benchmarks/serving_bench_fleet_tpu.txt 2>&1
tail -8 benchmarks/serving_bench_fleet_tpu.txt >&2

note "serving bench (graftwire: socket fleet vs in-process + kill recovery)"
python benchmarks/serving_bench.py \
    --sweep wire \
    --json_out benchmarks/serving_bench_wire_tpu.json \
    > benchmarks/serving_bench_wire_tpu.txt 2>&1
tail -8 benchmarks/serving_bench_wire_tpu.txt >&2

note "fleet autoscale smoke (graftscale: spawn/scale/rollout against real subprocesses)"
python benchmarks/scale_smoke.py --out benchmarks/scale_smoke_tpu.json \
    > benchmarks/scale_smoke_tpu.txt 2>&1
tail -6 benchmarks/scale_smoke_tpu.txt >&2

note "serving bench (graftscale: bursty/diurnal traces + rolling rollout)"
python benchmarks/serving_bench.py \
    --sweep autoscale \
    --json_out benchmarks/serving_bench_autoscale_tpu.json \
    > benchmarks/serving_bench_autoscale_tpu.txt 2>&1
tail -8 benchmarks/serving_bench_autoscale_tpu.txt >&2

note "serving bench (graftspec: accepted/target-step x k x draft source)"
python benchmarks/serving_bench.py \
    --sweep spec --draft_model gpt_tiny \
    --json_out benchmarks/serving_bench_spec_tpu.json \
    > benchmarks/serving_bench_spec_tpu.txt 2>&1
tail -20 benchmarks/serving_bench_spec_tpu.txt >&2

note "MFU tune sweep (resnet50 north star)"
python benchmarks/mfu_tune.py --config resnet50_imagenet

note "convergence (framework on TPU vs torch CPU)"
python benchmarks/convergence.py --epochs 8 --train_size 2048

note "graftzero sweep (sharded vs replicated step, grad-comm overlap, hbm_opt_state delta)"
python bench.py --zero --config resnet50_imagenet \
    > benchmarks/bench_zero_tpu.json 2> benchmarks/bench_zero_tpu.log
tail -1 benchmarks/bench_zero_tpu.json >&2
python bench.py --zero --config gpt_lm \
    >> benchmarks/bench_zero_tpu.json 2>> benchmarks/bench_zero_tpu.log
tail -1 benchmarks/bench_zero_tpu.json >&2

note "done — review artifacts, then commit"
