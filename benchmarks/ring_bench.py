"""Causal ring attention: contiguous vs zigzag layout, fwd+bwd.

The contiguous causal ring leaves later shards idle part of every
rotation (utilization ~(N+1)/2N); the zigzag layout balances the fold
work. This bench times both over the available devices' ``seq`` axis.
On a single chip the ring is degenerate (axis size 1) — run with
multiple devices (real or ``JAX_PLATFORMS=cpu`` +
``--xla_force_host_platform_device_count=8`` for a schedule sanity
check; CPU timings are not perf evidence).

Run: ``python benchmarks/ring_bench.py [--seqs 8192,16384] [--dtype bf16]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

import benchmarks._common as _common  # noqa: E402
from benchmarks._common import timeit  # noqa: E402
from pytorch_multiprocessing_distributed_tpu.parallel.ring_attention import (
    ring_attention)


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--batch", default=1, type=int)
    p.add_argument("--heads", default=8, type=int)
    p.add_argument("--head_dim", default=64, type=int)
    p.add_argument("--seqs", default="8192,16384", type=str)
    args = p.parse_args()

    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("seq",))
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    print(f"# platform={devices[0].platform} n_shards={n} "
          f"dtype={args.dtype} b={args.batch} h={args.heads} "
          f"d={args.head_dim}")
    if n == 1:
        print("# WARNING: 1 device — ring degenerate, layouts identical")

    def make(zigzag):
        def body(q, k, v):
            out = ring_attention(q, k, v, axis_name="seq", causal=True,
                                 zigzag=zigzag)
            return jnp.sum(out.astype(jnp.float32))

        sharded = jax.shard_map(
            body, mesh=mesh, in_specs=P(None, "seq"), out_specs=P(),
            check_vma=False,
        )
        grad_fn = jax.grad(
            lambda q, k, v: sharded(q, k, v), argnums=(0, 1, 2))

        def scalar_bwd(q, k, v):
            return sum(jnp.sum(x.astype(jnp.float32))
                       for x in grad_fn(q, k, v))

        return jax.jit(sharded), jax.jit(scalar_bwd)

    fwd_c, bwd_c = make(False)
    fwd_z, bwd_z = make(True)

    for s in [int(x) for x in args.seqs.split(",")]:
        rng = np.random.default_rng(0)
        shape = (args.batch, s, args.heads, args.head_dim)
        q = jnp.asarray(rng.normal(size=shape), dtype)
        k = jnp.asarray(rng.normal(size=shape), dtype)
        v = jnp.asarray(rng.normal(size=shape), dtype)
        tc, tz = timeit(fwd_c, (q, k, v)), timeit(fwd_z, (q, k, v))
        bc, bz = timeit(bwd_c, (q, k, v)), timeit(bwd_z, (q, k, v))
        print(f"S={s:6d}  fwd: contig {tc * 1e3:8.3f} ms  zigzag "
              f"{tz * 1e3:8.3f} ms  ({tc / tz:5.2f}x)   fwd+bwd: contig "
              f"{bc * 1e3:8.3f} ms  zigzag {bz * 1e3:8.3f} ms  "
              f"({bc / bz:5.2f}x)", flush=True)


if __name__ == "__main__":
    main()


