"""graftspec smoke: speculative decode end-to-end on the CPU mesh.

The contract, asserted in one short run (same body runs in tier-1 —
``tests/test_graftspec.py::test_spec_smoke_end_to_end``):

1. **Token-exactness**: the speculative engine's greedy streams
   (self-draft, dense AND paged) are byte-identical to the
   non-speculative engine and per-request ``generate()``.
2. **The speculative claim**: on a repetitive stream (target briefly
   trained on the motif so continuation is structural), self-drafting
   clears > 1.0 accepted tokens per target-model step AND finishes in
   fewer decode dispatches than the non-speculative engine — more
   tokens per weight stream, which is the whole point.
3. **Disarmed is free**: k=0 runs zero speculative passes and
   compiles zero spec programs.
4. **Telemetry**: acceptance counters/percentiles ride the metrics
   snapshot, ``spec.verify``/``spec.draft`` land on the graftscope
   bus, and the GoodputLedger books rejected-draft verify work as
   ``goodput_spec_waste_s``, not productive time.

Run: ``make spec`` (or ``python benchmarks/spec_smoke.py``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_smoke():
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.serving_bench import train_repetitive
    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.inference import (
        generate)
    from pytorch_multiprocessing_distributed_tpu.runtime import (
        fleet, scope as graftscope)
    from pytorch_multiprocessing_distributed_tpu.serving import (
        ServingEngine, init_params)

    model = models.GPT(vocab_size=61, max_seq_len=256, hidden_size=32,
                       num_layers=2, num_heads=2, mlp_dim=64,
                       attn_impl="xla")
    params = init_params(model, 1)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 61, (n,)).tolist() for n in (3, 12)]

    def ref_tail(p, n):
        out = generate(model, params, jnp.asarray(p)[None, :],
                       max_new_tokens=n)
        return np.asarray(out[0, -n:]).tolist()

    # ---- 1: token-exactness, H>1, ragged batch (the FULL pinned
    # matrix — paged, chunked, TP, EOS, fault quarantine — lives in
    # tests/test_graftspec.py; the smoke pins the dense core)
    dense_ref = ServingEngine(model, params, max_slots=2, s_max=32,
                              min_bucket=8, decode_horizon=4)
    ref = dense_ref.serve([(p, 6) for p in prompts])
    spec = ServingEngine(model, params, max_slots=2, s_max=32,
                         min_bucket=8, decode_horizon=4, draft_k=4)
    got = spec.serve([(p, 6) for p in prompts])
    for a, b, p in zip(got, ref, prompts):
        assert a.tokens == b.tokens == ref_tail(p, 6), (
            f"speculative stream diverged (prompt len {len(p)}): "
            f"{a.tokens} vs {b.tokens}")
    print("spec smoke: token-exact vs non-spec engine AND generate() "
          "OK")

    # ---- 2: the speculative claim on a repetitive stream
    motif = [7, 19, 3, 42, 11, 58, 23, 5]
    rep_params = train_repetitive(model, params, motif, steps=40,
                                  lr=0.3)
    prompt = (motif * 6)[:30]
    scope = graftscope.arm(graftscope.Scope(keep=True))
    try:
        spec = ServingEngine(model, rep_params, max_slots=1, s_max=128,
                             decode_buckets=(), decode_horizon=4,
                             draft_k=4)
        (r_spec,) = spec.serve([(prompt, 64)])
    finally:
        graftscope.disarm()
    base = ServingEngine(model, rep_params, max_slots=1, s_max=128,
                         decode_buckets=(), decode_horizon=4)
    (r_base,) = base.serve([(prompt, 64)])
    assert r_spec.tokens == r_base.tokens
    snap = spec.metrics.snapshot()
    per_step = snap["spec_accepted_per_target_step"]
    assert per_step > 1.0, (
        f"repetitive config must clear >1.0 accepted tokens per "
        f"target step, got {per_step:.3f}")
    assert (snap["decode_dispatches"]
            < base.metrics.snapshot()["decode_dispatches"]), (
        "speculation must finish the stream in fewer dispatches")
    assert snap["accept_len_p50"] > 0 and snap["spec_tokens_accepted"]
    print(f"spec smoke: accepted/target-step={per_step:.2f} "
          f"(accept p50/p95={snap['accept_len_p50']:.0f}/"
          f"{snap['accept_len_p95']:.0f}), dispatches "
          f"{snap['decode_dispatches']} vs "
          f"{base.metrics.snapshot()['decode_dispatches']} non-spec OK")

    # ---- 4: bus + goodput accounting
    names = {e.name for e in scope.events()}
    assert "spec.verify" in names, "spec.verify missing from the bus"
    assert "spec.draft" in names, "spec.draft missing from the bus"
    ledger = fleet.GoodputLedger.from_events(scope.events())
    gauges = ledger.gauges()
    assert "goodput_spec_waste_s" in gauges
    assert gauges["goodput_spec_waste_s"] >= 0.0
    verify = [e for e in scope.events() if e.name == "spec.verify"]
    assert all(e.attrs["accepted"] <= e.attrs["drafted"]
               for e in verify)
    print(f"spec smoke: bus + goodput OK (spec_waste="
          f"{gauges['goodput_spec_waste_s']:.4f}s over "
          f"{len(verify)} verify spans)")

    # ---- 3: disarmed spec is the plain engine (the draft_k=0
    # reference above IS the disarmed engine — no spec telemetry, no
    # spec programs)
    snap_off = dense_ref.metrics.snapshot()
    assert snap_off["spec_verify_passes"] == 0
    assert snap_off["spec_tokens_drafted"] == 0
    assert dense_ref.spec_programs == ()
    print("spec smoke: k=0 disarmed — zero spec passes/programs OK")


if __name__ == "__main__":
    run_smoke()
    print("spec smoke OK")
