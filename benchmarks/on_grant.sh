#!/bin/bash
# Priority-ordered TPU evidence capture for a SHORT grant window.
# Run the moment `.tpu_alive` appears (tpu_watch.sh) — highest-value
# steps first, so a window that closes mid-run costs the least-needed
# artifact. Complements record_all_tpu.sh (the exhaustive version).
#
# Each step is bounded by `timeout` as a last resort: a hung client
# kill risks re-wedging the tunnel (observed round 3/4), but an
# UNBOUNDED hang costs every later step of the window with certainty.
# 45 min comfortably covers the observed ~25 min error-out path.
set -u
cd "$(dirname "$0")/.." || exit 1
note() { echo "=== $* ($(date -u +%T))" >&2; }
T="timeout -k 30 2700"

note "0. graftlint gate (jit-hygiene static analysis — AST-only, instant)"
# A red lint gate means a hot path may host-sync or recompile per step;
# TPU numbers captured in that state are not evidence. Refuse the window.
if ! timeout -k 10 120 python -m pytorch_multiprocessing_distributed_tpu.analysis.lint; then
  echo "graftlint gate RED — fix findings (or baseline them with a" >&2
  echo "justification) before burning TPU time; see 'make lint'" >&2
  exit 1
fi

note "0b. graftcheck gate (jaxpr-level program audit — CPU trace, ~1 min)"
# A red program audit means a hot program's communication/donation/
# dtype contract drifted from its committed budget: a perf number
# captured on the drifted program proves nothing about the committed
# one. Runs on the HOST platform — never touches the TPU plugin.
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytorch_multiprocessing_distributed_tpu.analysis.check; then
  echo "graftcheck gate RED — inspect the named program/rule, fix (or" >&2
  echo "re-baseline deliberately with 'make check-update')" >&2
  exit 1
fi

note "1. baselines still missing/legacy (need-first order)"
$T python benchmarks/record_baselines.py --missing

note "2. per-op profile of the MFU-gap config (resnet50)"
$T python benchmarks/profile_step.py --config resnet50_imagenet

note "3. resnet50 geometry probes: batch 128/512 + remat (HBM-pressure hypothesis)"
$T python bench.py --config resnet50_imagenet --batch_size 128
$T python bench.py --config resnet50_imagenet --batch_size 512
$T python bench.py --config resnet50_imagenet --remat

note "4. MFU flag sweep (short: the profile + probes above pick the lever)"
$T python benchmarks/mfu_tune.py --config resnet50_imagenet \
    --batches 0,128 --flag_sets baseline,lhs

note "4b. gpt_lm streamed-CE probe (logits never materialize — faster?)"
$T python bench.py --config gpt_lm --vocab_chunks 8

note "5. attention artifact (flash vs XLA, backs COVERAGE.md)"
# temp-then-move: a failed run must not clobber a previous GOOD artifact
tmp=$(mktemp)
if $T python benchmarks/attention_bench.py > "$tmp" 2>&1 \
   && $T python benchmarks/attention_bench.py --causal >> "$tmp" 2>&1; then
  mv "$tmp" benchmarks/attention_bench_tpu.txt
  tail -8 benchmarks/attention_bench_tpu.txt >&2
else
  echo "attention bench failed; keeping prior artifact" >&2
  tail -4 "$tmp" >&2; rm -f "$tmp"
fi

note "6. decode throughput"
tmp=$(mktemp)
if $T python benchmarks/generate_bench.py > "$tmp" 2>&1; then
  mv "$tmp" benchmarks/generate_bench_tpu.txt
  tail -4 benchmarks/generate_bench_tpu.txt >&2
else
  echo "generate bench failed; keeping prior artifact" >&2
  tail -4 "$tmp" >&2; rm -f "$tmp"
fi

note "6b. serving throughput (load sweep + length-bucket sweep)"
tmp=$(mktemp)
if $T python benchmarks/serving_bench.py \
    --json_out benchmarks/serving_bench_tpu.json > "$tmp" 2>&1; then
  mv "$tmp" benchmarks/serving_bench_tpu.txt
  tail -14 benchmarks/serving_bench_tpu.txt >&2
else
  echo "serving bench failed; keeping prior artifact" >&2
  tail -4 "$tmp" >&2; rm -f "$tmp"
fi

note "7. cross-hardware convergence (framework on TPU vs torch on CPU)"
# scaled milestones: the committed convergence_record.json records the
# milestone-stabilized protocol — a no-decay short run must not
# overwrite it with an unstable terminal state
$T python benchmarks/convergence.py --epochs 6 --milestones 4,5 \
    --train_size 1024

note "done — review artifacts, then commit"
