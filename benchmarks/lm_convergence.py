"""LM convergence parity: this framework vs transformers, SAME GPT-2
init, SAME byte-corpus batches (VERDICT r4 #3, LM record).

The image-side counterpart is ``convergence.py``; here the model is a
GPT-2 (built by ``transformers.GPT2LMHeadModel``, imported into the
framework via ``utils.gpt_interop.from_gpt2_state_dict`` — the exact
``--hf_init`` CLI path) and the data is a deterministic byte-level
corpus streamed by the framework's own ``TokenLoader`` on BOTH sides.
Objective on both sides: exact mean next-token CE over positions with
a successor (``train.lm._next_token_targets`` semantics), plain SGD
with identical hyperparameters — any trajectory gap is framework
semantics, nothing else.

Writes ``benchmarks/lm_convergence_record.json`` and prints a one-line
JSON summary (headline: final-epoch mean-loss delta; step-0 loss delta
pins the imported-init forward parity).

Run: ``python benchmarks/lm_convergence.py [--epochs 3]``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import benchmarks._common as _common  # noqa: E402

RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "lm_convergence_record.json")

# GPT-2 small-geometry test double (matches tests/test_lm_cli.py):
# byte-level 257 vocab, 4 layers, 128 wide, 4 heads, no dropout
GPT2_KW = dict(vocab_size=257, n_positions=256, n_embd=128, n_layer=4,
               n_head=4, resid_pdrop=0.0, embd_pdrop=0.0,
               attn_pdrop=0.0, tie_word_embeddings=False)
LR = 0.1


def make_corpus(args):
    from pytorch_multiprocessing_distributed_tpu.data.text import tokenize

    text = ("the quick brown fox jumps over the lazy dog. "
            "pack my box with five dozen liquor jugs! "
            "how vexingly quick daft zebras jump? ") * args.repeats
    return tokenize(text)


def make_loader(args, tokens):
    from pytorch_multiprocessing_distributed_tpu.data.lm import TokenLoader

    return TokenLoader(tokens, batch_size=args.batch_size,
                       seq_len=args.seq_len, world_size=1, seed=0)


def run_framework(args, sd, tokens):
    import jax
    import jax.numpy as jnp

    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh
    from pytorch_multiprocessing_distributed_tpu.train.lm import (
        create_lm_train_state, make_lm_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.utils.gpt_interop import (
        from_gpt2_state_dict)

    model, params = from_gpt2_state_dict(sd, num_heads=GPT2_KW["n_head"],
                                         attn_impl="xla")
    mesh = make_mesh(1, devices=jax.devices()[:1])
    opt = sgd(learning_rate=LR, momentum=0.9, weight_decay=0.0,
              nesterov=False)
    state = create_lm_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((2, args.seq_len), jnp.int32), opt)
    state = state.replace(params=jax.tree.map(jnp.asarray, params))
    step = make_lm_train_step(model, opt, mesh)

    loader = make_loader(args, tokens)
    losses = []
    for epoch in range(1, args.epochs + 1):
        state = state.replace(epoch=jnp.asarray(epoch, jnp.int32))
        loader.set_epoch(epoch)
        ep = []
        for batch in loader:
            tok = jax.device_put(jnp.asarray(batch))
            state, metrics = step(state, tok)
            ep.append(float(np.asarray(metrics["loss"])))
        losses.append(ep)
        print(f"[framework] epoch {epoch}: loss {np.mean(ep):.4f}",
              file=sys.stderr, flush=True)
    return losses


def run_torch(args, sd, tokens):
    import torch
    import torch.nn.functional as F
    import transformers

    model = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(**GPT2_KW))
    model.load_state_dict(sd)
    model.train()
    optimizer = torch.optim.SGD(model.parameters(), lr=LR, momentum=0.9)

    loader = make_loader(args, tokens)
    losses = []
    for epoch in range(1, args.epochs + 1):
        loader.set_epoch(epoch)
        ep = []
        for batch in loader:
            x = torch.from_numpy(np.ascontiguousarray(batch)).long()
            logits = model(x).logits
            # exact _next_token_targets semantics: position j predicts
            # token j+1; the final position has no successor
            loss = F.cross_entropy(
                logits[:, :-1].reshape(-1, logits.shape[-1]),
                x[:, 1:].reshape(-1))
            optimizer.zero_grad()
            loss.backward()
            optimizer.step()
            ep.append(float(loss.detach()))
        losses.append(ep)
        print(f"[torch]     epoch {epoch}: loss {np.mean(ep):.4f}",
              file=sys.stderr, flush=True)
    return losses


def main():
    _common.apply_platform_env()
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", default=3, type=int)
    p.add_argument("--batch_size", default=8, type=int)
    p.add_argument("--seq_len", default=64, type=int)
    p.add_argument("--repeats", default=120, type=int,
                   help="corpus length knob (~125 bytes per repeat)")
    args = p.parse_args()

    import jax
    import torch
    import transformers

    platform = jax.devices()[0].platform
    torch.manual_seed(0)
    src = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(**GPT2_KW))
    sd = src.state_dict()

    tokens = make_corpus(args)
    t0 = time.time()
    fw = run_framework(args, sd, tokens)
    fw_s = time.time() - t0
    t0 = time.time()
    th = run_torch(args, sd, tokens)
    th_s = time.time() - t0

    fw_ep = [float(np.mean(e)) for e in fw]
    th_ep = [float(np.mean(e)) for e in th]
    record = {
        "platform": platform,
        "model": "GPT2LMHeadModel " + json.dumps(GPT2_KW),
        "optimizer": f"SGD lr={LR} momentum=0.9 (both sides)",
        "epochs": args.epochs,
        "batch_size": args.batch_size,
        "seq_len": args.seq_len,
        "corpus_tokens": int(len(tokens)),
        "identical_init": True,
        "identical_batches": True,
        "framework": {"epoch_loss": fw_ep, "seconds": round(fw_s, 1)},
        "torch_cpu": {"epoch_loss": th_ep, "seconds": round(th_s, 1)},
        # step-0 pins the imported-init forward+loss; the final epoch
        # pins where both optimizers converge to
        "step0_loss_delta": round(fw[0][0] - th[0][0], 6),
        "final_loss_delta": round(fw_ep[-1] - th_ep[-1], 6),
    }
    with open(RECORD, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps({
        "metric": "gpt2_lm_convergence_final_loss_delta_vs_torch",
        "value": record["final_loss_delta"],
        "unit": "nats",
        "extra": {k: record[k] for k in
                  ("platform", "epochs", "corpus_tokens",
                   "step0_loss_delta")},
    }))


if __name__ == "__main__":
    main()
