#!/bin/bash
# Multi-cycle grant supervisor: wait for .tpu_alive (written by
# tpu_watch.sh's patient prober) -> run the priority-ordered capture
# (on_grant.sh) -> commit whatever artifacts it produced -> re-arm the
# watcher for the NEXT window. Detach with:
#   setsid nohup bash benchmarks/grant_cycle.sh >> .on_grant.log 2>&1 &
# Exactly one instance should run (it serializes chip access; a second
# concurrent capture would contend for the single-tenant chip).
cd "$(dirname "$0")/.." || exit 1
while true; do
  while [ ! -f .tpu_alive ]; do sleep 30; done
  echo "[cycle] grant detected $(date -u +%FT%TZ)"
  bash benchmarks/on_grant.sh
  echo "[cycle] capture finished $(date -u +%FT%TZ); committing artifacts"
  # pathspec'd commit: operator-staged files must never be swept into
  # the unattended capture commit. Added one by one — git add is
  # all-or-nothing on missing pathspecs, and a window that produced
  # only SOME artifacts must still commit those
  artifacts="benchmarks/baseline_record.json benchmarks/mfu_tune_results.json
      benchmarks/attention_bench_tpu.txt benchmarks/generate_bench_tpu.txt
      benchmarks/serving_bench_tpu.txt benchmarks/convergence_record.json"
  for a in $artifacts; do git add "$a" 2>/dev/null; done
  # commit only the SUCCESSFULLY staged artifacts: a pathspec naming a
  # file git has never seen aborts the whole commit (nothing lands)
  staged=$(git diff --cached --name-only -- $artifacts)
  [ -z "$staged" ] || git commit -q -m \
      "TPU grant-window capture: baseline/profile/attention/decode artifacts" \
      -- $staged
  rm -f .tpu_alive
  # patient re-probe for the next window (tpu_watch exits on success)
  bash benchmarks/tpu_watch.sh 120
done
