// TCP key-value rendezvous store — the c10d TCPStore analogue.
//
// The reference rendezvouses through torch.distributed's TCPStore (spawned
// by init_process_group behind MASTER_ADDR/MASTER_PORT, reference
// main.py:190-193). JAX pods rendezvous through the jax.distributed
// coordinator for the DEVICE control plane; this store provides the
// remaining HOST control plane the framework needs outside XLA:
// experiment-level barriers, health/heartbeat keys, rank assignment for
// ad-hoc jobs. Exposed to Python via ctypes (runtime/store.py).
//
// Protocol (length-prefixed binary over TCP):
//   request :=  u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   reply   :=  i64 status | u32 vlen | value bytes
//   ops: 1=SET  2=GET  3=ADD(value=i64 ascii delta)  4=WAIT  5=DELETE
// GET on a missing key returns status=-1. WAIT blocks (server side) until
// the key exists. ADD atomically adds to an integer key (creating it),
// returning the new value — barriers are ADD + WAIT loops client-side.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::vector<int> conn_fds;  // open client connections (guarded by mu)
  bool stopping = false;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_reply(int fd, int64_t status, const std::string& value) {
  uint32_t vlen = static_cast<uint32_t>(value.size());
  if (!write_full(fd, &status, sizeof(status))) return false;
  if (!write_full(fd, &vlen, sizeof(vlen))) return false;
  if (vlen && !write_full(fd, value.data(), vlen)) return false;
  return true;
}

void unregister_conn(Store* store, int fd) {
  std::lock_guard<std::mutex> lock(store->mu);
  auto& fds = store->conn_fds;
  fds.erase(std::remove(fds.begin(), fds.end(), fd), fds.end());
}

void serve_conn(Store* store, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    uint32_t klen, vlen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
    if (klen > (1u << 20)) break;
    std::string key(klen, '\0');
    if (klen && !read_full(fd, key.data(), klen)) break;
    if (!read_full(fd, &vlen, 4)) break;
    if (vlen > (1u << 26)) break;  // 64 MiB value cap
    std::string value(vlen, '\0');
    if (vlen && !read_full(fd, value.data(), vlen)) break;

    bool ok = true;
    switch (op) {
      case 1: {  // SET
        {
          std::lock_guard<std::mutex> lock(store->mu);
          store->kv[key] = value;
        }
        store->cv.notify_all();
        ok = send_reply(fd, 0, "");
        break;
      }
      case 2: {  // GET
        std::string out;
        int64_t status = -1;
        {
          std::lock_guard<std::mutex> lock(store->mu);
          auto it = store->kv.find(key);
          if (it != store->kv.end()) {
            out = it->second;
            status = 0;
          }
        }
        ok = send_reply(fd, status, out);
        break;
      }
      case 3: {  // ADD — status 0, new counter value in the reply body
        int64_t delta = std::strtoll(value.c_str(), nullptr, 10);
        int64_t result;
        {
          std::lock_guard<std::mutex> lock(store->mu);
          int64_t cur = 0;
          auto it = store->kv.find(key);
          if (it != store->kv.end())
            cur = std::strtoll(it->second.c_str(), nullptr, 10);
          result = cur + delta;
          store->kv[key] = std::to_string(result);
        }
        store->cv.notify_all();
        ok = send_reply(fd, 0, std::to_string(result));
        break;
      }
      case 4: {  // WAIT (blocks until key exists or server stops)
        std::unique_lock<std::mutex> lock(store->mu);
        store->cv.wait(lock, [&] {
          return store->stopping || store->kv.count(key) > 0;
        });
        bool aborted = store->stopping;
        std::string out = aborted ? "" : store->kv[key];
        lock.unlock();
        ok = send_reply(fd, aborted ? -2 : 0, out);
        if (aborted) ok = false;  // drop the connection on shutdown
        break;
      }
      case 5: {  // DELETE — status 0, "1"/"0" (erased or not) in the body
        int64_t erased;
        {
          std::lock_guard<std::mutex> lock(store->mu);
          erased = static_cast<int64_t>(store->kv.erase(key));
        }
        store->cv.notify_all();
        ok = send_reply(fd, 0, std::to_string(erased));
        break;
      }
      default:
        ok = false;
    }
    if (!ok) break;
  }
  unregister_conn(store, fd);
  ::close(fd);
}

}  // namespace

extern "C" {

// Starts a store server on port (0 = ephemeral). Returns an opaque handle,
// or nullptr on failure. *out_port receives the bound port.
void* pmdt_store_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (out_port) *out_port = ntohs(addr.sin_port);

  auto* store = new Store();
  store->listen_fd = fd;
  store->accept_thread = std::thread([store] {
    for (;;) {
      int cfd = ::accept(store->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen fd closed -> shutdown
      std::lock_guard<std::mutex> lock(store->mu);
      if (store->stopping) {
        ::close(cfd);
        break;
      }
      store->conn_fds.push_back(cfd);
      store->workers.emplace_back(serve_conn, store, cfd);
    }
  });
  return store;
}

void pmdt_store_server_stop(void* handle) {
  auto* store = static_cast<Store*>(handle);
  if (!store) return;
  std::vector<int> fds;
  {
    std::lock_guard<std::mutex> lock(store->mu);
    store->stopping = true;
    fds = store->conn_fds;  // snapshot; workers unregister as they exit
  }
  store->cv.notify_all();
  ::shutdown(store->listen_fd, SHUT_RDWR);
  ::close(store->listen_fd);
  // Unblock every worker stuck in read_full on its client socket, then
  // JOIN them all before freeing the store (no detached threads may
  // outlive the Store they reference).
  for (int cfd : fds) ::shutdown(cfd, SHUT_RDWR);
  if (store->accept_thread.joinable()) store->accept_thread.join();
  for (auto& w : store->workers)
    if (w.joinable()) w.join();
  delete store;
}

// Client: connect/disconnect + ops. Return fd >= 0 or -1.
int pmdt_store_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

void pmdt_store_disconnect(int fd) {
  if (fd >= 0) ::close(fd);
}

static int64_t request(int fd, uint8_t op, const char* key, const void* val,
                       uint32_t vlen, char* out, int64_t out_cap,
                       int64_t* out_len) {
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  if (!write_full(fd, &op, 1) || !write_full(fd, &klen, 4) ||
      !write_full(fd, key, klen) || !write_full(fd, &vlen, 4) ||
      (vlen && !write_full(fd, val, vlen)))
    return -3;
  int64_t status;
  uint32_t rlen;
  if (!read_full(fd, &status, 8) || !read_full(fd, &rlen, 4)) return -3;
  std::string buf(rlen, '\0');
  if (rlen && !read_full(fd, buf.data(), rlen)) return -3;
  if (out && out_cap > 0) {
    int64_t n = std::min<int64_t>(rlen, out_cap);
    std::memcpy(out, buf.data(), static_cast<size_t>(n));
  }
  // *out_len is always the TRUE value length; a caller seeing
  // out_len > cap got a truncated copy and should use the _dyn variant.
  if (out_len) *out_len = rlen;
  return status;
}

int64_t pmdt_store_set(int fd, const char* key, const void* val, int64_t len) {
  return request(fd, 1, key, val, static_cast<uint32_t>(len), nullptr, 0,
                 nullptr);
}

int64_t pmdt_store_get(int fd, const char* key, char* out, int64_t cap,
                       int64_t* out_len) {
  return request(fd, 2, key, nullptr, 0, out, cap, out_len);
}

// Dynamic-allocation variants: the reply value is malloc'd at exact size
// so arbitrarily large values cross the socket exactly once (no probe /
// retry). Caller frees *out with pmdt_store_free.
static int64_t request_dyn(int fd, uint8_t op, const char* key, char** out,
                           int64_t* out_len) {
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  uint32_t vlen = 0;
  *out = nullptr;
  *out_len = 0;
  if (!write_full(fd, &op, 1) || !write_full(fd, &klen, 4) ||
      !write_full(fd, key, klen) || !write_full(fd, &vlen, 4))
    return -3;
  int64_t status;
  uint32_t rlen;
  if (!read_full(fd, &status, 8) || !read_full(fd, &rlen, 4)) return -3;
  if (rlen) {
    char* buf = static_cast<char*>(std::malloc(rlen));
    if (!buf) return -4;
    if (!read_full(fd, buf, rlen)) {
      std::free(buf);
      return -3;
    }
    *out = buf;
    *out_len = rlen;
  }
  return status;
}

int64_t pmdt_store_get_dyn(int fd, const char* key, char** out,
                           int64_t* out_len) {
  return request_dyn(fd, 2, key, out, out_len);
}

int64_t pmdt_store_wait_dyn(int fd, const char* key, char** out,
                            int64_t* out_len) {
  return request_dyn(fd, 4, key, out, out_len);
}

void pmdt_store_free(char* p) { std::free(p); }

int64_t pmdt_store_add(int fd, const char* key, int64_t delta, char* out,
                       int64_t cap, int64_t* out_len) {
  std::string d = std::to_string(delta);
  return request(fd, 3, key, d.data(), static_cast<uint32_t>(d.size()), out,
                 cap, out_len);
}

int64_t pmdt_store_wait(int fd, const char* key, char* out, int64_t cap,
                        int64_t* out_len) {
  return request(fd, 4, key, nullptr, 0, out, cap, out_len);
}

int64_t pmdt_store_delete(int fd, const char* key, char* out, int64_t cap,
                          int64_t* out_len) {
  return request(fd, 5, key, nullptr, 0, out, cap, out_len);
}

}  // extern "C"
