"""CLI entrypoint — same seven flags as the reference (``main.py:21-30``).

What changes underneath (the TPU-native design, SURVEY.md §7): no
``mp.spawn`` — ONE process per host drives all local chips via a named
``(data, model)`` mesh; ``--world_size`` sets the data-axis (DP) degree
the way it set the number of spawned GPU processes in the reference
(``main.py:28,185-188``); the NCCL rendezvous on ``127.0.0.1:20080``
(``main.py:190-193``) becomes ``jax.distributed`` pod init (multi-host)
or nothing (single host).

Extension flags (all optional, defaults reproduce the reference):
``--data_root``, ``--synthetic``, ``--dtype``, ``--model_parallel``,
``--seed``, ``--resume``.

Testing without chips: PMDT_FORCE_CPU_DEVICES=8 virtualizes 8 CPU
devices (same mechanism as the test suite).
"""

import argparse
import os
import shutil

from pytorch_multiprocessing_distributed_tpu.runtime import (
    scope as graftscope)

parser = argparse.ArgumentParser(description="Confidence Aware Learning")
parser.add_argument('--batch_size', default=64, type=int, help='Batch size')
parser.add_argument('--epochs', default=20, type=int, help='Total number of epochs to run')
parser.add_argument('--model', default='res', type=str, help='Models name to use [res, dense, vgg]')
parser.add_argument('--save_path', default='./test/', type=str,
                    help='Savefiles directory: logs, checkpoints, plots AND a\n'
                         'main.py snapshot land here (run_model). The default\n'
                         './test/ is a run artifact, gitignored — not the\n'
                         'tests/ suite')
parser.add_argument('--gpu', default='7', type=str, help='GPU id to use')
parser.add_argument('--print-freq', '-p', default=10, type=int, metavar='N', help='print frequency (default: 10)')
parser.add_argument('--world_size', default=2, type=int, help='Gpu use number')
# --- TPU-native extensions (not in the reference CLI) ---
parser.add_argument('--dataset', default='cifar', choices=['cifar', 'imagenet'],
                    help='dataset family: cifar (reference parity) or imagenet '
                         '(BASELINE configs #2/#3 — ImageFolder tree or --synthetic)')
parser.add_argument('--data_root', default='', type=str,
                    help='dataset root (cifar: cifar-10-batches-py inside; '
                         'imagenet: train/ + val/ ImageFolder tree)')
parser.add_argument('--synthetic', action='store_true',
                    help='use a deterministic synthetic dataset (no files needed)')
parser.add_argument('--num_classes', default=0, type=int,
                    help='label count (0 = auto: 10 cifar / 1000 imagenet)')
parser.add_argument('--image_size', default=0, type=int,
                    help='square input size (0 = auto: 32 cifar / 224 imagenet)')
parser.add_argument('--dtype', default='float32', choices=['float32', 'bfloat16'],
                    help='compute dtype for conv/matmul (params stay f32)')
parser.add_argument('--model_parallel', default=1, type=int,
                    help='model-axis size of the mesh (1 = pure DP, reference mode)')
parser.add_argument('--zero', action='store_true',
                    help='graftzero: sharded weight update on the '
                         'explicit shard_map-DP step — grads reduce-'
                         'scatter into per-rank bucket shards, the '
                         'optimizer updates only the local shard '
                         '(moments sharded from step one, ~1/world '
                         'optimizer HBM per chip), params all-gather '
                         'back. Bit-identical trajectory; checkpoints '
                         'stay mode-portable (gather-on-save). Pure DP '
                         'only — see --zero1/--fsdp for the GSPMD path')
parser.add_argument('--zero1', action='store_true',
                    help='ZeRO-1: shard optimizer moments over the data '
                         'axis (each replica stores 1/world of them; '
                         'GSPMD inserts the reduce-scatter/all-gather)')
parser.add_argument('--fsdp', action='store_true',
                    help='FSDP/ZeRO-3: shard params, BN stats AND '
                         'optimizer moments over the data axis (each '
                         'replica stores ~1/world of the model; GSPMD '
                         'all-gathers params per layer and reduce-'
                         'scatters grads). For models bigger than chip '
                         'HBM; pure DP is faster when the model fits')
parser.add_argument('--grad_accum', default=1, type=int,
                    help='accumulate gradients over N sequential '
                         'microbatches per optimizer step (activation '
                         'memory of one microbatch, one weight update) — '
                         'the per-device batch must divide by N')
parser.add_argument('--clip_grad_norm', default=0.0, type=float,
                    help='clip the global gradient norm to this bound '
                         'before the update (0 = off); applied to the '
                         'already-averaged gradients, torch '
                         'clip_grad_norm_ semantics')
parser.add_argument('--label_smoothing', default=0.0, type=float,
                    help='cross-entropy label smoothing epsilon '
                         '(torch CrossEntropyLoss(label_smoothing=e))')
parser.add_argument('--ema', default=0.0, type=float, metavar='DECAY',
                    help='track an exponential moving average of the '
                         'params with this decay (e.g. 0.999) and use '
                         'it for evaluation; 0 = off')
parser.add_argument('--remat', action='store_true',
                    help='rematerialize activations in the backward '
                         '(jax.checkpoint): ~1.3x step time for a much '
                         'smaller HBM footprint — buys batch sizes the '
                         'chip could not otherwise hold')
parser.add_argument('--seed', default=0, type=int, help='init/seed for params and shuffling')
parser.add_argument('--resume', default='', type=str,
                    help="checkpoint path to resume from, or 'auto' = "
                         "latest model_*.pth in --save_path (reference "
                         "has no resume)")
parser.add_argument('--save_every', default=0, type=int,
                    help='checkpoint every N epochs (0 = reference '
                         'behavior: final epoch only)')
parser.add_argument('--keep_checkpoints', default=0, type=int,
                    help='retain only the K newest periodic checkpoints '
                         '(0 = keep all)')
parser.add_argument('--ckpt_backend', default='msgpack',
                    choices=['msgpack', 'orbax'],
                    help='msgpack = reference-parity model_{epoch}.pth '
                         '(one host-gathered file, torch-interoperable); '
                         'orbax = sharded per-host writes under '
                         '{save_path}/orbax/ — no gather, scales with '
                         'the model; needs shared storage across hosts. '
                         "With orbax, --resume takes 'auto' or an epoch "
                         'number')
parser.add_argument('--ckpt_async', action='store_true',
                    help='overlap checkpoint serialization with training '
                         '(orbax backend only); the final-epoch and '
                         'preemption saves are always durable before '
                         'exit')
parser.add_argument('--lr', default=0.0, type=float,
                    help='base learning rate (0 = optimizer default: '
                         '0.1 sgd / 1e-3 lamb, the reference values)')
parser.add_argument('--lr_schedule', default='multistep',
                    choices=['multistep', 'cosine'],
                    help='multistep = reference MultiStepLR([60,80], 0.1); '
                         'cosine = cosine decay to 0 over --epochs with '
                         '--warmup_epochs linear warmup')
parser.add_argument('--warmup_epochs', default=0, type=int,
                    help='linear LR warmup epochs (cosine schedule only)')
parser.add_argument('--optimizer', default='sgd',
                    choices=['sgd', 'lamb', 'sgd_fused'],
                    help='sgd = reference config (main.py:51-55); lamb = '
                         'large-batch layerwise-adaptive (BASELINE #5); '
                         'sgd_fused = same SGD trajectory via the fused '
                         'single-pass Pallas update kernel')
parser.add_argument('--profile', default='', type=str, metavar='LOGDIR',
                    help='capture a jax.profiler trace of the run into '
                         'LOGDIR (TensorBoard-loadable; off when empty)')
parser.add_argument('--torch_export', action='store_true',
                    help='additionally export the final weights as a '
                         'torch-loadable state_dict '
                         '(model_{epoch}.torch.pth, reference model '
                         'naming; ResNet family only)')
parser.add_argument('--max_restarts', default=0, type=int,
                    help='graftheal supervised restart: catch named-'
                         'fatal errors (GraftFaultError family — lost '
                         'peer, poisoned pool, exhausted retries), '
                         're-run rendezvous, and restart the run '
                         'resuming from the newest digest-valid '
                         'checkpoint (--resume auto semantics) — at '
                         'most N times with exponential backoff '
                         '(0 = die on first fatal, the old behavior)')
parser.add_argument('--restart_backoff', default=1.0, type=float,
                    help='first-restart delay in seconds (doubles per '
                         'restart, capped at 30s)')
graftscope.add_cli_args(parser, stats_port=True)


def main(args):
    if args.torch_export and not (
        args.model == "res" or args.model.startswith("resnet")
    ):
        # Fail BEFORE the training run, not after hours of work: the
        # torch state_dict mapping covers the ResNet family only.
        raise SystemExit(
            f"--torch_export supports the ResNet family only "
            f"(got --model {args.model})"
        )
    # arm before any jax work: compile/placement phases belong on the
    # timeline too (zero cost when no graftscope flag is set; the
    # Trainer's spans and the flight recorder attach automatically)
    graftscope.arm_from_args(args)
    from pytorch_multiprocessing_distributed_tpu.runtime import hbm

    if args.stats_port:
        # graftmeter: arm the HBM ledger before any state is placed so
        # the Trainer's params/opt-state registrations land on it
        hbm.arm()
    # Backend selection must happen before device queries.
    from pytorch_multiprocessing_distributed_tpu.utils.hostenv import (
        force_cpu_devices_from_env)

    force_cpu_devices_from_env()
    from pytorch_multiprocessing_distributed_tpu.utils.compile_cache import (
        enable_compilation_cache)

    enable_compilation_cache()

    import jax
    import jax.numpy as jnp

    from pytorch_multiprocessing_distributed_tpu import data as datamod
    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.parallel import (
        dist, make_mesh)
    from pytorch_multiprocessing_distributed_tpu.train import (
        create_train_state, load_checkpoint)
    from pytorch_multiprocessing_distributed_tpu.train.optim import (
        cosine_lr, multistep_lr, sgd)
    from pytorch_multiprocessing_distributed_tpu.train.trainer import Trainer

    # Every pure-flag validation BEFORE dist/device/data work (the
    # repo-wide convention train_lm.py states explicitly: an invalid
    # combo must not cost a backend bring-up or a dataset read, and
    # must never surface as an unrelated crash later).
    if args.model in models.LM_MODELS:
        raise ValueError(
            f"--model {args.model} is a language model: it trains on "
            "token sequences via pytorch_multiprocessing_distributed_tpu"
            ".train.lm (make_lm_train_step), not through this image-"
            "classification CLI. See MIGRATION.md."
        )
    if args.optimizer == "sgd_fused" and (
        args.zero1 or args.fsdp or args.model_parallel > 1
    ):
        raise ValueError(
            "--optimizer sgd_fused is the explicit shard_map-DP "
            "path's fused kernel; under --zero1/--fsdp/--model_parallel "
            "the GSPMD partitioner cannot shard through the opaque "
            "Pallas call (it would replicate the moment buffers, "
            "defeating the sharding). Use --optimizer sgd there."
        )
    if args.zero and (args.zero1 or args.fsdp or args.model_parallel > 1):
        raise ValueError(
            "--zero is the explicit shard_map-DP sharded update; "
            "--zero1/--fsdp/--model_parallel run the GSPMD path, which "
            "shards state via placement instead — pick one family."
        )
    if args.zero and args.optimizer == "sgd_fused":
        raise ValueError(
            "--zero shards the update through the transform's "
            "update()/shard_update() path; the fused Pallas whole-"
            "update kernel cannot run on shards. Use --optimizer sgd "
            "or lamb with --zero."
        )
    if args.zero and args.ckpt_backend == "orbax":
        raise ValueError(
            "--zero checkpoints via msgpack gather-on-save (the "
            "artifact round-trips between --zero and plain runs); "
            "--ckpt_backend orbax would persist the sharded layout."
        )
    if args.warmup_epochs and args.lr_schedule != "cosine":
        raise ValueError(
            "--warmup_epochs applies to --lr_schedule cosine (the "
            "reference's MultiStepLR has no warmup)"
        )
    # dataset-derived geometry (the reference hardcodes 32x32/10-way,
    # data.py:11 + model/resnet.py:86; here the imagenet route widens it)
    is_imagenet = args.dataset == "imagenet"
    image_size = args.image_size or (224 if is_imagenet else 32)
    if not is_imagenet and image_size != 32:
        raise ValueError(
            "--dataset cifar is fixed at 32x32 (the reference resizes to "
            "32, data.py:11); --image_size applies to --dataset imagenet"
        )

    dist.init_process()

    mesh = make_mesh(args.world_size, args.model_parallel)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32

    if not args.data_root:
        args.data_root = "./imagenet" if is_imagenet else "./cifar10_data"
    args.image_size = image_size

    # loaders first (reference order: main.py:36 -> data.py:6-59), so the
    # model head can size itself from what the dataset actually contains
    # (a FolderImageNet tree derives its own class count).
    train_loader, test_loader = datamod.get_loader(args, mesh)
    num_classes = (
        args.num_classes
        or getattr(getattr(train_loader, "dataset", None), "num_classes", None)
        or (1000 if is_imagenet else 10)
    )
    args.num_classes = num_classes

    # model (reference main.py:39-40 — only 'res' didn't crash there).
    # Pure DP binds the data axis into BN for the explicit pmean stat
    # sync; the TP path (model_parallel > 1) runs under global-semantics
    # GSPMD jit where batch stats are global by construction, so BN must
    # NOT carry an axis name there (train/step.py make_train_step_tp).
    use_gspmd = args.model_parallel > 1 or args.zero1 or args.fsdp
    model = models.get_model(
        args.model, dtype=dtype,
        bn_axis=None if use_gspmd else "data",
        num_classes=num_classes,
        stem="imagenet" if is_imagenet else "cifar",
    )

    # optimizer + schedule — default is the exact reference config
    # (main.py:51-59); the alternatives are the model-layer extension
    # seam BASELINE configs #4/#5 train through
    def make_schedule(base_default):
        base = args.lr or base_default
        if args.lr_schedule == "cosine":
            return cosine_lr(base, args.epochs,
                             warmup_epochs=args.warmup_epochs)
        # warmup x non-cosine is rejected in the flag-validation block
        return multistep_lr(base, milestones=[60, 80], gamma=0.1)

    if args.optimizer == "lamb":
        from pytorch_multiprocessing_distributed_tpu.train.lamb import lamb

        optimizer = lamb(
            learning_rate=make_schedule(1e-3),
            weight_decay=0.0001,
        )
    elif args.optimizer == "sgd_fused":
        # GSPMD combos rejected up in the flag-validation block
        from pytorch_multiprocessing_distributed_tpu.ops.pallas.fused_update import (
            sgd_pallas)

        optimizer = sgd_pallas(
            learning_rate=make_schedule(0.1),
            momentum=0.9,
            weight_decay=0.0001,
            nesterov=True,
        )
    else:
        optimizer = sgd(
            learning_rate=make_schedule(0.1),
            momentum=0.9,
            weight_decay=0.0001,
            nesterov=True,
        )

    state = create_train_state(
        model,
        jax.random.PRNGKey(args.seed),
        jnp.zeros((2, image_size, image_size, 3), jnp.float32),
        optimizer,
        ema=args.ema > 0,
    )
    start_epoch = 1
    if args.ckpt_backend == "orbax" and args.resume:
        from pytorch_multiprocessing_distributed_tpu.train.orbax_ckpt import (
            OrbaxCheckpointer)

        ck = OrbaxCheckpointer(args.save_path)
        if args.resume == "auto":
            # latest_epoch broadcasts the primary's verdict itself
            epoch = ck.latest_epoch()
        else:
            try:
                epoch = int(args.resume)
            except ValueError:
                raise SystemExit(
                    f"--ckpt_backend orbax: --resume must be 'auto' or "
                    f"an epoch number (orbax checkpoints are epoch-keyed "
                    f"directories under {{save_path}}/orbax/), got "
                    f"{args.resume!r}"
                )
        if epoch is None:
            if dist.is_primary():
                print(f"--resume auto: no orbax checkpoint under "
                      f"{args.save_path}; starting fresh")
        else:
            # device_get: the restore lands committed on the template's
            # (single-device, pre-shard_state) placement; committed
            # leaves would then fight the mesh sharding inside the
            # jitted step. Host arrays are placement-free — the trainer
            # re-shards them exactly like a fresh init (shard_state for
            # zero1/fsdp/TP, jit replication for plain DP).
            state = jax.device_get(ck.restore(state, epoch))
            start_epoch = int(state.epoch) + 1
            if dist.is_primary():
                print(f"Resumed from {ck.directory}/{epoch} "
                      f"(continuing at epoch {start_epoch})")
        ck.close()
    auto_resume = False
    if args.ckpt_backend != "orbax" and args.resume == "auto":
        from pytorch_multiprocessing_distributed_tpu.train.checkpoint import (
            resolve_auto_resume)

        args.resume = resolve_auto_resume(args.save_path) or ""
        auto_resume = bool(args.resume)
        if not args.resume and dist.is_primary():
            print(f"--resume auto: no checkpoint under {args.save_path}; "
                  "starting fresh")
    if args.ckpt_backend != "orbax" and args.resume:
        if auto_resume:
            # auto picks the checkpoint, so it also owns the recovery:
            # a corrupt newest checkpoint (digest mismatch) is reported
            # and the previous valid epoch restores instead. An
            # EXPLICIT --resume path still fails loudly — the user
            # named that file.
            from pytorch_multiprocessing_distributed_tpu.train.checkpoint import (
                checkpoint_epoch, load_with_fallback)

            # anchor the fallback walk at the primary-resolved epoch:
            # a stale EXTRA checkpoint on one host (newer than what the
            # primary resolved) must not shift that host's walk and get
            # misdiagnosed as cross-host divergence
            state, args.resume = load_with_fallback(
                args.save_path, state,
                anchor=checkpoint_epoch(args.resume))
        else:
            state = load_checkpoint(args.resume, state)
        # continue the epoch series (LR schedule + log numbering) from
        # where the checkpoint left off
        start_epoch = int(state.epoch) + 1
        if dist.is_primary():
            print(f"Resumed from {args.resume} (continuing at epoch {start_epoch})")

    from pytorch_multiprocessing_distributed_tpu.ops.losses import (
        smooth_cross_entropy_loss)

    loss_fn = smooth_cross_entropy_loss(args.label_smoothing)
    trainer = Trainer(
        model=model,
        optimizer=optimizer,
        mesh=mesh,
        state=state,
        train_loader=train_loader,
        test_loader=test_loader,
        save_path=args.save_path,
        epochs=args.epochs,
        print_freq=args.print_freq,
        start_epoch=start_epoch,
        zero=args.zero,
        zero1=args.zero1,
        fsdp=args.fsdp,
        remat=args.remat,
        grad_accum=args.grad_accum,
        loss_fn=loss_fn,
        clip_grad_norm=args.clip_grad_norm or None,
        ema_decay=args.ema or None,
        save_every=args.save_every,
        keep_checkpoints=args.keep_checkpoints,
        ckpt_backend=args.ckpt_backend,
        ckpt_async=args.ckpt_async,
    )
    stats_server = None
    health = None
    if args.stats_port:
        # live trainer telemetry: hbm_* capacity gauges (graftmeter
        # ledger) + the loop's windowed loss/throughput, on /metrics
        # and /snapshot.json over stdlib http.server — plus /healthz
        # (graftheal): 200 only while the run is up, with last-beat
        # ages when a PMDT_HEARTBEAT monitor is armed
        from pytorch_multiprocessing_distributed_tpu.runtime import (
            fleet, heal)

        health = heal.HealthState()
        # graftfleet: goodput_* gauges classified from the Trainer's
        # own spans (train.window/data/metrics_fetch/checkpoint)
        fleet.arm_goodput()

        def live_snapshot():
            snap = dict(trainer.live)
            ledger = hbm.active_ledger()
            if ledger is not None:
                snap.update(ledger.snapshot())
            snap.update(fleet.goodput_gauges())
            return snap

        stats_server = graftscope.start_stats_server(
            live_snapshot, port=args.stats_port, prefix="pmdt",
            health_fn=lambda: heal.healthz(health,
                                           heal.active_monitor()),
            # /events.json (graftfleet): the armed scope, served
            # live, ?since= cursor for incremental scrapes
            events_fn=graftscope.scope_events_fn)
        print(f"stats: http://127.0.0.1:"
              f"{stats_server.server_address[1]}/metrics "
              f"(+ /healthz)", flush=True)
        # announce this rank's scrape address to the fleet store
        # (no-op unless PMDT_FLEET armed a monitor at rendezvous)
        fleet.publish_endpoint(
            f"127.0.0.1:{stats_server.server_address[1]}")
        health.to_ready("training")

    try:
        if args.profile:
            from pytorch_multiprocessing_distributed_tpu.utils.profiler import trace

            with trace(args.profile):
                trainer.fit()
        else:
            trainer.fit()
    except BaseException:
        # the supervised-restart path (--max_restarts) re-enters
        # main() on the SAME fixed --stats_port: a listener left
        # behind by the dying run would turn every restart into
        # EADDRINUSE — release it before the named fatal propagates
        if stats_server is not None:
            stats_server.shutdown()
        raise

    if args.torch_export:
        from pytorch_multiprocessing_distributed_tpu.train.checkpoint import (
            _gather_for_host)
        from pytorch_multiprocessing_distributed_tpu.utils.torch_interop import (
            save_torch_checkpoint)

        # COLLECTIVE gather first — under --zero1/--fsdp/--model_parallel
        # the state is sharded across hosts, so every host must
        # participate before the primary-only write (same contract as
        # save_checkpoint).
        params, batch_stats = _gather_for_host(
            (trainer.state.params, trainer.state.batch_stats))
        if dist.is_primary():
            out = os.path.join(
                args.save_path, f"model_{args.epochs}.torch.pth")
            save_torch_checkpoint(
                out, jax.device_get(params), jax.device_get(batch_stats))
            print(f"Exported torch state_dict -> {out}")

    if dist.is_primary():
        graftscope.export_from_args(args)
    if stats_server is not None:
        if health is not None:
            health.to_dead("run complete")
        stats_server.shutdown()
    dist.destroy_process_group()


def run_model(args):
    """Experiment bring-up (reference ``run_model``, ``main.py:180-188``):
    create the save dir, snapshot this script into it, run —
    optionally under graftheal's bounded-restart supervisor
    (``--max_restarts``): a named fatal (lost peer, poisoned engine
    state, exhausted retries) tears the pod down, backs off, re-runs
    rendezvous, and restarts the run with ``--resume auto`` — so every
    restart resumes from the newest digest-valid checkpoint through
    ``load_with_fallback``. Restart budget exhaustion fails loudly
    (``RestartBudgetExhausted``)."""
    if not os.path.exists(args.save_path):
        os.makedirs(args.save_path)
    shutil.copy(__file__, os.path.join(args.save_path, 'main.py'))
    if not args.max_restarts:
        main(args)
        return
    from pytorch_multiprocessing_distributed_tpu.runtime import heal

    def target(attempt):
        if attempt:
            # resume from the newest digest-valid checkpoint (auto
            # owns corrupt-artifact fallback; main() re-resolves it)
            args.resume = "auto"
        return main(args)

    def rerendezvous():
        # tear the pod down so the restarted run re-runs bring-up
        # (init_process is idempotent only while initialized)
        from pytorch_multiprocessing_distributed_tpu.parallel import (
            dist)

        dist.destroy_process_group()

    heal.Supervisor(target, max_restarts=args.max_restarts,
                    backoff_s=args.restart_backoff,
                    rendezvous=rerendezvous).run()


if __name__ == "__main__":
    run_model(parser.parse_args())
