"""Benchmark harness — prints ONE JSON line for the driver.

The reference publishes no numbers (BASELINE.md), so this harness IS the
benchmark the framework is judged on: ResNet-18/CIFAR-10 train-step
throughput, images/sec/chip (BASELINE.json config #1 hardware-adjusted:
whatever chips are visible — the driver runs it on one real TPU chip).

Honest timing under async dispatch: warmup compiles + settles caches,
then the timed window blocks on the final step's metrics
(``block_until_ready``), so the measurement covers real device work —
not dispatch (SURVEY.md §5 "Tracing").

``vs_baseline`` is reported vs the recorded number in
``benchmarks/baseline_record.json`` when present (set by earlier rounds),
else 1.0 (the reference has no published number to compare against).
"""

import argparse
import json
import os
import time


def run_bench(dtype_name: str = "bfloat16", batch_size: int = 512,
              steps: int = 30, warmup: int = 5) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh
    from pytorch_multiprocessing_distributed_tpu.train import (
        create_train_state, make_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.train.step import shard_batch

    n_dev = jax.device_count()
    mesh = make_mesh(n_dev)
    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32

    model = models.ResNet18(dtype=dtype, bn_axis="data")
    opt = sgd(learning_rate=0.1)
    state = create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)), opt
    )
    step = make_train_step(model, opt, mesh)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch_size, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (batch_size,)))
    xb, yb = shard_batch((x, y), mesh)

    for _ in range(warmup):
        state, metrics = step(state, xb, yb)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, xb, yb)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = batch_size * steps / dt
    per_chip = images_per_sec / n_dev
    return {
        "metric": "resnet18_cifar10_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "extra": {
            "dtype": dtype_name,
            "global_batch": batch_size,
            "devices": n_dev,
            "steps": steps,
            "step_ms": round(1000 * dt / steps, 3),
            "platform": jax.devices()[0].platform,
        },
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dtype", default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--batch_size", default=512, type=int)
    p.add_argument("--steps", default=30, type=int)
    args = p.parse_args()

    result = run_bench(args.dtype, args.batch_size, args.steps)

    record_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "baseline_record.json",
    )
    vs = 1.0
    if os.path.exists(record_path):
        try:
            with open(record_path) as f:
                rec = json.load(f)
            base = rec.get(result["metric"])
            if base:
                vs = round(result["value"] / base, 4)
        except Exception:
            pass
    result["vs_baseline"] = vs
    print(json.dumps(result))


if __name__ == "__main__":
    main()
