"""Benchmark harness — prints ONE JSON line for the driver.

The reference publishes no numbers (BASELINE.md), so this harness IS the
benchmark the framework is judged on. Configs mirror BASELINE.json:
``resnet18_cifar`` (config #1, the default), ``resnet50_imagenet``
(config #2 — the north star: global batch 256, 224x224, bf16) and
``vit_b16_imagenet`` (config #4).

Robustness contract (round-1 failure was an ``UNAVAILABLE`` at backend
bring-up with rc=1 and no output): backend init is retried with backoff,
falls back to CPU with a note, and NO failure path exits without first
printing a well-formed JSON line (an ``error`` field at worst).

Honest timing under async dispatch: warmup compiles + settles caches,
then the timed window blocks on the final step's metrics
(``block_until_ready``), so the measurement covers real device work —
not dispatch (SURVEY.md §5 "Tracing").

MFU: the compiled step's own XLA cost analysis gives FLOPs per program
(per chip); ``mfu = flops/sec / chip peak`` using a per-generation peak
table (bf16 MXU numbers). Null on CPU or unknown hardware.

``vs_baseline`` is reported vs the recorded number in
``benchmarks/baseline_record.json`` when present (set by earlier rounds),
else 1.0 (the reference has no published number to compare against).
"""

import argparse
import json
import os
import sys
import time
import traceback

# bf16 peak FLOPs/s per chip by device_kind substring (first match wins;
# more specific generations first). Sources: public TPU spec sheets.
PEAK_FLOPS = [
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

CONFIGS = {
    "resnet18_cifar": dict(
        model="res", image_size=32, batch=512, num_classes=10, stem="cifar",
    ),
    "resnet50_imagenet": dict(
        model="resnet50", image_size=224, batch=256, num_classes=1000,
        stem="imagenet",
    ),
    "vit_b16_imagenet": dict(
        model="vit_b16", image_size=224, batch=256, num_classes=1000,
        stem=None,
    ),
}


def _log(msg: str) -> None:
    """Diagnostics go to stderr; stdout carries exactly one JSON line."""
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def init_devices(retries: int = 3, delay: float = 5.0):
    """Bring up the backend, surviving transient TPU-plugin failures AND
    hangs (the round-1 bench died here with rc=1 and no JSON).

    ``jax.devices()`` does not just raise on a sick TPU plugin — it can
    HANG (observed: >500s inside axon bring-up). The init runs in a
    watchdog thread so the healthy path pays exactly one bring-up:

    - completes -> done;
    - raises (e.g. UNAVAILABLE) -> retry with backoff, then in-process
      CPU fallback via ``jax.config.update`` (env vars are too late —
      the plugin initializes even under ``JAX_PLATFORMS=cpu``);
    - times out -> the hung thread holds jax's global backend lock, so
      NOTHING in this process can initialize any platform anymore:
      re-exec ourselves once with ``--platform cpu``.

    Returns (devices, note) where note is None or a fallback explanation.
    """
    import threading

    import jax

    timeout = float(os.environ.get("PMDT_BENCH_PROBE_TIMEOUT", 180))
    last_err = None
    for attempt in range(retries):
        box = {}

        def target():
            try:
                box["devices"] = jax.devices()
            except BaseException as e:  # noqa: BLE001
                box["err"] = e

        t = threading.Thread(target=target, daemon=True,
                             name="pmdt-backend-init")
        t.start()
        t.join(timeout)
        if "devices" in box:
            return box["devices"], None
        if "err" not in box:
            # Hung. This process is unsalvageable for backend init.
            if os.environ.get("PMDT_BENCH_REEXEC"):
                raise RuntimeError(
                    f"backend init hung past {timeout:.0f}s even after "
                    "re-exec onto the host platform"
                )
            _log(f"backend init hung past {timeout:.0f}s; re-executing "
                 "with --platform cpu")
            os.environ["PMDT_BENCH_REEXEC"] = "1"
            sys.stdout.flush()
            sys.stderr.flush()
            os.execv(sys.executable,
                     [sys.executable] + sys.argv + ["--platform", "cpu"])
        last_err = box["err"]
        if attempt + 1 < retries:
            _log(
                f"attempt {attempt + 1}/{retries} failed: {last_err}. "
                f"Retrying in {delay * (attempt + 1):.0f}s. (If this "
                "persists: another process may hold the TPU — check for "
                "stale jobs; or force the host platform with --platform "
                "cpu.)"
            )
            time.sleep(delay * (attempt + 1))
    note = (f"TPU backend unavailable after {retries} attempts "
            f"({last_err}); CPU fallback")
    _log(note)
    jax.config.update("jax_platforms", "cpu")
    return jax.devices(), note


def chip_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        return None
    for key, peak in PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def compile_step(step, *args):
    """AOT-compile the step ONCE; return (callable, per-chip FLOPs).

    The compiled executable drives the warmup/timed loops directly (AOT
    compiles don't populate jit's cache, so lowering for cost analysis
    and then calling the jitted wrapper would compile the same program
    twice — a multi-ten-second tax on the exact harness whose round-1
    failure was a startup timeout). FLOPs come from XLA's own cost model.
    """
    try:
        compiled = step.lower(*args).compile()
    except Exception as e:
        _log(f"AOT compile unavailable ({e}); falling back to jit")
        return step, None
    flops = None
    try:
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        f = ca.get("flops", 0.0)
        flops = float(f) if f and f > 0 else None
    except Exception as e:
        _log(f"cost_analysis unavailable: {e}")
    return compiled, flops


def run_bench(config: str, dtype_name: str, batch_size: int, steps: int,
              warmup: int, devices, note) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh
    from pytorch_multiprocessing_distributed_tpu.train import (
        create_train_state, make_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.train.step import shard_batch

    cfg = CONFIGS[config]
    n_dev = len(devices)
    platform = devices[0].platform
    mesh = make_mesh(n_dev, devices=devices)
    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    batch = batch_size or cfg["batch"]
    if platform != "tpu":
        # CPU fallback is a liveness signal, not a perf number — shrink
        # so the line still appears in bounded time.
        batch = min(batch, 8 * n_dev)
        steps, warmup = min(steps, 5), min(warmup, 2)
    if batch % n_dev:
        batch += n_dev - batch % n_dev  # keep the data axis even
    s = cfg["image_size"]

    model = models.get_model(
        cfg["model"], dtype=dtype, bn_axis="data",
        num_classes=cfg["num_classes"], stem=cfg["stem"],
    )
    opt = sgd(learning_rate=0.1)
    state = create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, s, s, 3)), opt
    )
    step = make_train_step(model, opt, mesh)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, s, s, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, cfg["num_classes"], (batch,)))
    xb, yb = shard_batch((x, y), mesh)

    steps = max(1, steps)
    step, flops = compile_step(step, state, xb, yb)

    for _ in range(warmup):
        state, metrics = step(state, xb, yb)
    if warmup > 0:
        jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, xb, yb)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    per_chip = images_per_sec / n_dev
    peak = chip_peak_flops(devices[0])
    mfu = None
    if flops and peak:
        mfu = round(flops * (steps / dt) / peak, 4)

    result = {
        "metric": f"{config}_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "mfu": mfu,
        "extra": {
            "config": config,
            "dtype": dtype_name,
            "global_batch": batch,
            "devices": n_dev,
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", "unknown"),
            "steps": steps,
            "step_ms": round(1000 * dt / steps, 3),
            "flops_per_step_per_chip": flops,
            "peak_flops_per_chip": peak,
        },
    }
    if note:
        result["extra"]["backend_note"] = note
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet18_cifar",
                   choices=sorted(CONFIGS))
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--batch_size", default=0, type=int,
                   help="global batch (0 = config default)")
    p.add_argument("--steps", default=30, type=int)
    p.add_argument("--warmup", default=5, type=int)
    p.add_argument("--platform", default="auto", choices=["auto", "cpu"],
                   help="cpu = skip the TPU probe and force the host platform")
    args = p.parse_args()

    result = None
    try:
        if args.platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
            devices = jax.devices()
            note = ("TPU init hung; re-exec'd onto CPU"
                    if os.environ.get("PMDT_BENCH_REEXEC") else None)
        else:
            devices, note = init_devices()
        _log(f"devices: {len(devices)} x "
             f"{getattr(devices[0], 'device_kind', devices[0].platform)}")
        result = run_bench(args.config, args.dtype, args.batch_size,
                           args.steps, args.warmup, devices, note)
    except BaseException as e:  # noqa: BLE001 — the JSON line must appear
        _log(traceback.format_exc())
        result = {
            "metric": f"{args.config}_train_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "mfu": None,
            "error": f"{type(e).__name__}: {e}",
        }

    record_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "baseline_record.json",
    )
    vs = 1.0
    if os.path.exists(record_path):
        try:
            with open(record_path) as f:
                rec = json.load(f)
            base = rec.get(result["metric"])
            if base:
                vs = round(result["value"] / base, 4)
        except Exception:
            pass
    result["vs_baseline"] = vs
    print(json.dumps(result))


if __name__ == "__main__":
    main()
