"""Benchmark harness — prints ONE JSON line for the driver.

The reference publishes no numbers (BASELINE.md), so this harness IS the
benchmark the framework is judged on. Configs mirror BASELINE.json:
``resnet50_imagenet`` (config #2, THE NORTH STAR and the default: global
batch 256, 224x224, bf16), ``resnet18_cifar`` (config #1),
``resnet152_imagenet`` (config #3), ``vit_b16_imagenet`` (config #4) and
``convnext_lamb`` (config #5, large-batch LAMB stress); ``gpt_lm``
(beyond BASELINE's five) measures the GPT/flash-attention LM path in
tokens/sec/chip.

Robustness contract (round-1 failure was an ``UNAVAILABLE`` at backend
bring-up with rc=1 and no output): backend init is retried with backoff,
falls back to CPU with a note, and NO failure path exits without first
printing a well-formed JSON line (an ``error`` field at worst).

Measurement discipline (round 2 shipped a physically impossible number —
mfu 11.6 — because ``block_until_ready`` returns EARLY on this
environment's experimental ``axon`` PJRT plugin; measured here: a
workload with a 5.6 ms/step physical floor "completed" in 0.05 ms/step
under ``block_until_ready`` but 5.7 ms/step under a real device->host
readback). The timed protocol is therefore:

1. every window boundary is a REAL D2H readback of a scalar metric
   (``np.asarray``), which demonstrably forces execution on axon;
2. the queue is drained (one step + readback) before each clock start,
   so a window never absorbs previously enqueued async work;
3. the window is grown until it spans >= ``--min_window`` seconds
   (default 1.0 s) of real wall time — never a 9 ms blip;
4. a linearity self-check times N steps and 2N steps; if t(2N)/t(N) is
   not ~2 (within [1.6, 2.6], tolerance for the fixed per-window
   readback latency over the tunnel), the run FAILS with an ``error``
   field instead of emitting a number;
4b. the reported step time is the two-window SLOPE
   ``(t(2N) - t(N)) / N``: each window is ``fixed_readback + n * step``,
   so the difference cancels the fixed device->host readback latency
   (measured ~100-200 ms per window over this environment's tunnel)
   exactly, leaving the steady-state step time the chip actually
   sustains. The conservative whole-window quotient ``t(2N) / 2N``
   (which charges the tunnel round-trip to the workload) is kept in
   ``extra.step_ms_conservative``; both are linearity- and MFU-gated;
5. hard physical sanity gates: computed MFU must be <= 1.0 and the loss
   finite, else ``error`` — this harness can no longer print a number
   that exceeds the hardware's peak.

MFU: the compiled step's own XLA cost analysis gives FLOPs per program
(per chip); ``mfu = flops/sec / chip peak`` using a per-generation peak
table (bf16 MXU numbers). Null on CPU or unknown hardware.

``vs_baseline``: the first VALID TPU run of each metric writes
``benchmarks/baseline_record.json``; later runs report against it.
Before a record exists (or on error / CPU fallback / mismatched
config) it is null — a non-comparison must never read as "on par".
"""

import argparse
import json
import math
import os
import sys
import time
import traceback
from typing import Optional

# bf16 peak FLOPs/s per chip by device_kind substring (first match wins;
# more specific generations first). Sources: public TPU spec sheets.
PEAK_FLOPS = [
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# HBM bandwidth per chip (bytes/s) by the same device_kind substrings —
# the roofline's second axis (graftmeter): a step whose arithmetic
# intensity sits below peak_flops/peak_bw is bandwidth-bound and no
# kernel fusion will reach MXU peak. Sources: public TPU spec sheets.
PEAK_HBM_BW = [
    ("v6e", 1640e9),
    ("v6 lite", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]

CONFIGS = {
    "resnet18_cifar": dict(
        model="res", image_size=32, batch=512, num_classes=10, stem="cifar",
    ),
    "resnet50_imagenet": dict(
        model="resnet50", image_size=224, batch=256, num_classes=1000,
        stem="imagenet",
    ),
    "resnet152_imagenet": dict(
        model="resnet152", image_size=224, batch=128, num_classes=1000,
        stem="imagenet",
    ),
    "vit_b16_imagenet": dict(
        model="vit_b16", image_size=224, batch=256, num_classes=1000,
        stem=None,
    ),
    # BASELINE config #5: large-batch LAMB stress (ConvNeXt, 21k-way head).
    "convnext_lamb": dict(
        model="convnext_t", image_size=224, batch=256, num_classes=21841,
        stem=None, optimizer="lamb",
    ),
    # LM / long-context flagship (beyond BASELINE's five): GPT-2 small
    # through the Pallas causal flash kernel; tokens/sec/chip.
    "gpt_lm": dict(
        lm=True, model="gpt_small", seq_len=1024, batch=8,
    ),
    # long-context variant: 4x the sequence — the [S, S] attention
    # never materializes (flash kernel), so this measures what the
    # long-context stack actually sustains. batch 2 = same tokens/step
    # as gpt_lm ON THE SINGLE-CHIP canonical geometry (build_workload
    # rounds the global batch up to the data-axis size on wider meshes,
    # where per-chip tokens/step then differ).
    "gpt_lm_long": dict(
        lm=True, model="gpt_small", seq_len=4096, batch=2,
    ),
}


def metric_for(config: str):
    """(metric_name, unit) for a config — the ONE place the naming
    lives; the success and error paths must emit the same strings (the
    baseline record is keyed by them)."""
    if CONFIGS.get(config, {}).get("lm"):
        return f"{config}_train_tokens_per_sec_per_chip", "tokens/sec/chip"
    return f"{config}_train_images_per_sec_per_chip", "images/sec/chip"


def _log(msg: str) -> None:
    """Diagnostics go to stderr; stdout carries exactly one JSON line."""
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def probe_backend(timeout: float):
    """Probe backend bring-up in a SHORT-LIVED SUBPROCESS.

    The round-3 failure mode: ``jax.devices()`` HANGS in-process
    (observed: hours, after a killed bring-up wedges the axon tunnel),
    and a hung init thread holds jax's global backend lock forever — one
    wedged probe cost the whole round its TPU evidence. A subprocess
    probe can neither wedge nor poison the parent: the parent only
    initializes a backend the probe just proved healthy.

    Returns (platform_or_None, err_note_or_None, hung): ``hung``
    distinguishes a TIMEOUT (the wedged-tunnel signature — the probe
    process sat on backend init for the whole budget) from a fast
    failure (rc != 0, usually transient), so the retry policy can stop
    burning minutes once the wedge pattern repeats.
    """
    import subprocess

    code = ("import jax, sys; ds = jax.devices(); "
            "sys.stdout.write(ds[0].platform)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"probe hung past {timeout:.0f}s", True
    except Exception as e:  # noqa: BLE001
        return None, f"probe failed to launch: {e}", False
    if proc.returncode == 0 and proc.stdout.strip():
        return proc.stdout.strip().splitlines()[-1], None, False
    tail = (proc.stderr or "").strip().splitlines()
    return None, (f"probe rc={proc.returncode}: "
                  f"{tail[-1] if tail else 'no output'}"), False


def init_devices(retries: int = 3, delay: float = 5.0,
                 probe_timeout: Optional[float] = None,
                 probe_attempts: Optional[int] = None):
    """Bring up the backend, surviving transient TPU-plugin failures AND
    hangs (the round-1 bench died here with rc=1 and no JSON; round 3
    lost its TPU evidence to a single in-process hang; round 5 burned
    3 x 180 s + 2 x 60 s backoff on a tunnel whose every probe hung).

    Protocol:

    1. Probe bring-up in a subprocess (``probe_backend``) over a
       multi-attempt budget (``--probe_timeout``/``--probe_attempts``
       / env knobs: ``PMDT_BENCH_PROBE_TIMEOUT``,
       ``PMDT_BENCH_PROBE_ATTEMPTS``, ``PMDT_BENCH_PROBE_DELAY``; the
       CLI defaults to TWO attempts — see below). A transiently wedged
       tunnel gets minutes to recover instead of one strike; a wedged
       probe dies with its subprocess. Hang policy (the r05 lesson,
       BENCH_r05.json ``backend_note``): a hang is not a transient —
       a hung probe already gave the tunnel its full timeout to
       recover, so the 60 s backoff sleep is SKIPPED after one, and a
       SECOND hung probe fails the run over to CPU immediately
       regardless of remaining budget. Fast failures (probe rc != 0)
       keep the backoff and the full attempt budget: those really are
       transient.
    2. Only after a probe reports a healthy non-CPU platform does the
       PARENT initialize it — still under a watchdog thread with the
       re-exec escape hatch, in case the backend wedges between probe
       and init.
    3. If every probe fails, fall back to CPU in-process via
       ``jax.config.update`` — the parent never touched the sick
       plugin, so this is safe and instant.

    Returns (devices, note) where note is None or a fallback explanation.
    """
    import threading

    import jax

    timeout = float(probe_timeout
                    or os.environ.get("PMDT_BENCH_PROBE_TIMEOUT", 180))
    # `is not None`, not truthiness: an explicit 0 means "as few as
    # possible" and floors to ONE probe below — NOT a fall-through to
    # the 3-attempt legacy default (probing can't be skipped entirely:
    # the platform decision needs one answer; --platform cpu skips)
    attempts = int(probe_attempts if probe_attempts is not None
                   else os.environ.get("PMDT_BENCH_PROBE_ATTEMPTS",
                                       retries))
    attempts = max(1, attempts)
    probe_delay = float(os.environ.get("PMDT_BENCH_PROBE_DELAY", 60))
    platform = None
    probe_note = None
    hung_before = False
    for attempt in range(attempts):
        platform, probe_note, hung = probe_backend(timeout)
        if platform is not None:
            _log(f"backend probe ok (attempt {attempt + 1}): {platform}")
            break
        _log(f"backend probe attempt {attempt + 1}/{attempts} failed: "
             f"{probe_note}")
        if hung and hung_before:
            probe_note += " (second hung probe; failing over early)"
            _log("second hung probe this run — the tunnel is wedged, "
                 "not flaky; skipping the remaining retry budget")
            break
        hung_before = hung_before or hung
        if attempt + 1 < attempts:
            if hung:
                # the probe just sat on the tunnel for the whole
                # timeout — that WAS the recovery window; sleeping
                # another 60 s on top re-creates the r05 burn
                _log("retrying immediately (hung probe already spent "
                     f"{timeout:.0f}s of recovery time)")
            else:
                _log(f"retrying probe in {probe_delay:.0f}s")
                time.sleep(probe_delay)
    if platform is None:
        note = (f"TPU backend unavailable after {attempts} subprocess "
                f"probes x {timeout:.0f}s ({probe_note}); CPU fallback")
        _log(note)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices(), note
    if platform == "cpu":
        # Probe came back healthy but CPU-only (e.g. the plugin errored
        # in the subprocess and jax fell back). PIN cpu before touching
        # the backend: a bare jax.devices() here would re-initialize the
        # possibly-sick accelerator plugin in the parent, unprotected —
        # the exact hang this probe design exists to avoid.
        jax.config.update("jax_platforms", "cpu")
        return jax.devices(), None

    last_err = None
    for attempt in range(retries):
        box = {}

        def target():
            try:
                box["devices"] = jax.devices()
            except BaseException as e:  # noqa: BLE001
                box["err"] = e

        t = threading.Thread(target=target, daemon=True,
                             name="pmdt-backend-init")
        t.start()
        t.join(timeout)
        if "devices" in box:
            return box["devices"], None
        if "err" not in box:
            # Hung. This process is unsalvageable for backend init.
            if os.environ.get("PMDT_BENCH_REEXEC"):
                raise RuntimeError(
                    f"backend init hung past {timeout:.0f}s even after "
                    "re-exec onto the host platform"
                )
            _log(f"backend init hung past {timeout:.0f}s; re-executing "
                 "with --platform cpu")
            os.environ["PMDT_BENCH_REEXEC"] = "1"
            sys.stdout.flush()
            sys.stderr.flush()
            os.execv(sys.executable,
                     [sys.executable] + sys.argv + ["--platform", "cpu"])
        last_err = box["err"]
        if attempt + 1 < retries:
            _log(
                f"attempt {attempt + 1}/{retries} failed: {last_err}. "
                f"Retrying in {delay * (attempt + 1):.0f}s. (If this "
                "persists: another process may hold the TPU — check for "
                "stale jobs; or force the host platform with --platform "
                "cpu.)"
            )
            time.sleep(delay * (attempt + 1))
    note = (f"TPU backend unavailable after {retries} attempts "
            f"({last_err}); CPU fallback")
    _log(note)
    jax.config.update("jax_platforms", "cpu")
    return jax.devices(), note


def _chip_peak(device, table):
    """First device_kind-substring match in an ordered peak table
    (more specific generations first); None off-TPU / unknown chip —
    the ONE lookup both the FLOPs and HBM-bandwidth axes use."""
    if device.platform != "tpu":
        return None
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


def chip_peak_flops(device) -> float:
    return _chip_peak(device, PEAK_FLOPS)


def chip_peak_hbm_bw(device) -> float:
    return _chip_peak(device, PEAK_HBM_BW)


def compile_step(step, *args):
    """AOT-compile the step ONCE; return ``(callable, costs)`` where
    ``costs`` is the graftmeter record for the exact executable
    (``{flops, bytes_accessed, arithmetic_intensity, memory}`` —
    ``analysis.meter.costs_record``) or None when AOT is unavailable.

    The compiled executable drives the warmup/timed loops directly (AOT
    compiles don't populate jit's cache, so lowering for cost analysis
    and then calling the jitted wrapper would compile the same program
    twice — a multi-ten-second tax on the exact harness whose round-1
    failure was a startup timeout). Lowering + cost/memory analysis go
    through the shared ``utils.compile_cache.lowered_program_analysis``
    path (the same one the graftcheck/graftmeter auditors inspect, so
    the benched program, the budgeted program and the audited program
    cannot drift).
    """
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        costs_record)
    from pytorch_multiprocessing_distributed_tpu.utils.compile_cache import (
        lowered_program_analysis)

    try:
        compiled, cost, memory = lowered_program_analysis(step, *args)
    except Exception as e:
        _log(f"AOT compile unavailable ({e}); falling back to jit")
        return step, None
    if cost is None:
        # compat.cost_analysis_dict swallowed the reason; re-probe the
        # raw call (failure path only) so a one-shot grant capture's
        # log still says WHY the MFU column is empty
        try:
            compiled.cost_analysis()
            _log("cost_analysis unavailable (backend returned no "
                 "usable cost model)")
        except Exception as e:  # noqa: BLE001
            _log(f"cost_analysis unavailable: {e}")
    return compiled, costs_record(cost, memory)


def build_workload(config: str, dtype_name: str, batch_size: int,
                   devices, remat: bool = False, vocab_chunks: int = 0,
                   zero: bool = False, zero_overlap: bool = True):
    """Construct the EXACT program a config benches: the jitted train
    step, its initialized state, the resident device batch, and the
    item count per step. The ONE place this lives — ``run_bench`` times
    it and ``benchmarks/profile_step.py`` traces it, so the profiled
    program can never drift from the benched one.

    Returns ``(step, state, batch_args, items_per_step, batch)`` with
    ``batch`` after the data-axis divisibility rounding.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_multiprocessing_distributed_tpu import models
    from pytorch_multiprocessing_distributed_tpu.parallel import make_mesh
    from pytorch_multiprocessing_distributed_tpu.train import (
        create_train_state, make_train_step)
    from pytorch_multiprocessing_distributed_tpu.train.lamb import lamb
    from pytorch_multiprocessing_distributed_tpu.train.optim import sgd
    from pytorch_multiprocessing_distributed_tpu.train.step import shard_batch

    cfg = CONFIGS[config]
    n_dev = len(devices)
    is_tpu = devices[0].platform == "tpu"
    mesh = make_mesh(n_dev, devices=devices)
    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    batch = batch_size or cfg["batch"]
    is_lm = bool(cfg.get("lm"))
    if vocab_chunks and not is_lm:
        raise ValueError(
            f"--vocab_chunks streams the LM head; {config} is not an "
            "LM config"
        )
    if not is_tpu:
        # CPU fallback is a liveness signal, not a perf number — shrink
        # so a line still appears in bounded time.
        batch = min(batch, (1 if is_lm else 4) * n_dev)
    if batch % n_dev:
        batch += n_dev - batch % n_dev  # keep the data axis even
    rng = np.random.default_rng(0)

    if is_lm:
        from pytorch_multiprocessing_distributed_tpu.train.lm import (
            create_lm_train_state, make_lm_train_step)

        s = cfg["seq_len"]
        if not is_tpu:
            s = min(s, 64)  # interpret-mode flash kernel: liveness only
        model = models.get_model(cfg["model"], dtype=dtype,
                                 max_seq_len=max(s, 1024))
        opt = sgd(learning_rate=0.1)
        tokens = jnp.asarray(
            rng.integers(0, model.vocab_size, (batch, s))
        )
        state = create_lm_train_state(
            model, jax.random.PRNGKey(0), tokens[:2], opt
        )
        step = make_lm_train_step(model, opt, mesh, remat=remat,
                                  vocab_chunks=vocab_chunks, zero=zero,
                                  zero_overlap=zero_overlap)
        batch_args = shard_batch((tokens,), mesh)
        items_per_step = batch * s  # tokens
    else:
        s = cfg["image_size"]
        model = models.get_model(
            cfg["model"], dtype=dtype, bn_axis="data",
            num_classes=cfg["num_classes"], stem=cfg["stem"],
        )
        opt = (lamb(learning_rate=1e-3) if cfg.get("optimizer") == "lamb"
               else sgd(learning_rate=0.1))
        state = create_train_state(
            model, jax.random.PRNGKey(0), jnp.zeros((2, s, s, 3)), opt
        )
        step = make_train_step(model, opt, mesh, remat=remat, zero=zero,
                               zero_overlap=zero_overlap)
        x = jnp.asarray(rng.normal(size=(batch, s, s, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, cfg["num_classes"], (batch,)))
        batch_args = shard_batch((x, y), mesh)
        items_per_step = batch  # images

    if zero:
        # graftzero: moments sharded from step one (the replicated
        # tree never materializes); the step binds on this structure
        from pytorch_multiprocessing_distributed_tpu.parallel.zero import (
            zeroify_state)

        state = zeroify_state(state, mesh)
    return step, state, batch_args, items_per_step, batch


def run_bench(config: str, dtype_name: str, batch_size: int,
              min_window: float, warmup: int, devices, note,
              remat: bool = False, vocab_chunks: int = 0,
              zero: bool = False) -> dict:
    import numpy as np

    n_dev = len(devices)
    platform = devices[0].platform
    is_tpu = platform == "tpu"
    if not is_tpu:
        min_window, warmup = min(min_window, 0.2), min(warmup, 1)
    step, state, batch_args, items_per_step, batch = build_workload(
        config, dtype_name, batch_size, devices, remat=remat,
        vocab_chunks=vocab_chunks, zero=zero,
    )
    zero_plan = state.opt_state.plan if zero else None
    if zero:
        # the lazy zero wrapper has no .lower — hand the AOT path the
        # bound jit program for this state structure (the exact
        # program the loop runs)
        step = step.jit_program(state)
    # graftfleet goodput accounting for the bench run itself: compile
    # seconds vs measured-window seconds vs everything else (warmup,
    # queue drains, window growth) over the run's wall clock
    t_run0 = time.perf_counter()
    step, costs = compile_step(step, state, *batch_args)
    compile_s = time.perf_counter() - t_run0
    timed_windows = []  # seconds of MEASURED stepping (the goodput)
    flops = float(costs["flops"]) if costs and costs["flops"] else None
    bytes_accessed = (float(costs["bytes_accessed"])
                      if costs and costs["bytes_accessed"] else None)

    from pytorch_multiprocessing_distributed_tpu.utils.profiler import sync

    def readback(metrics) -> float:
        # The window boundary: profiler.sync is the framework's single
        # D2H-forcing sync (block_until_ready ALONE returns early on the
        # experimental axon plugin — round 2's 11.6-"MFU" artifact).
        sync(metrics)
        return float(np.asarray(metrics["loss"]))

    def window(state, n: int):
        """Drain the queue, then time n steps ending in a D2H readback."""
        state, m = step(state, *batch_args)
        readback(m)  # queue now empty: the clock can't absorb old work
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = step(state, *batch_args)
        loss = readback(m)
        t = time.perf_counter() - t0
        timed_windows.append(t)
        return t, state, loss

    _log(f"warmup x{warmup}")
    for _ in range(max(1, warmup)):
        state, metrics = step(state, *batch_args)
    readback(metrics)

    # Grow the window until it spans >= min_window seconds of real wall
    # time (round 2's fatal mistake was a 9 ms total window). Growth is
    # capped at 10x per iteration and the whole measurement at a wall
    # deadline, so a broken readback (windows reading ~0) degrades to an
    # error line in bounded time, never an hours-long queue drain.
    deadline = time.monotonic() + float(
        os.environ.get("PMDT_BENCH_DEADLINE", 420))
    n1 = 4 if is_tpu else 2
    max_steps = 20_000
    for _ in range(8):
        t1, state, loss = window(state, n1)
        _log(f"window n={n1}: {t1 * 1000:.1f} ms ({1000 * t1 / n1:.3f} ms/step)")
        if t1 >= min_window or n1 >= max_steps:
            break
        if time.monotonic() + 3 * max(t1, 0.001) > deadline:
            raise RuntimeError(
                f"bench deadline exceeded while growing the timed window "
                f"(n={n1} still only {t1 * 1000:.0f} ms) — timing is not "
                "converging; refusing to emit a number"
            )
        n1 = min(max_steps, 10 * n1,
                 max(n1 + 1, math.ceil(n1 * 1.25 * min_window / t1)))

    # Linearity self-check: 2N steps must take ~2x the time of N steps.
    # A fixed ~70 ms per-window readback latency (tunnel round-trip) plus
    # timing jitter keeps the honest ratio just under 2; anything far
    # from 2 means some async/caching artifact ate the measurement.
    if time.monotonic() + 2.5 * t1 > deadline:
        raise RuntimeError(
            "bench deadline would be exceeded by the linearity window — "
            "refusing to emit an unverified number"
        )
    t2, state, loss2 = window(state, 2 * n1)
    ratio = t2 / t1
    _log(f"window n={2 * n1}: {t2 * 1000:.1f} ms (linearity ratio {ratio:.3f})")

    # Two-window slope: t(n) = fixed_readback + n*step, so the difference
    # cancels the fixed D2H/tunnel latency exactly. Guarded below: the
    # linearity gate already bounds ratio in [1.6, 2.6], which bounds the
    # slope within a sane band of the conservative quotient; the MFU gate
    # applies to the slope (the number actually reported).
    step_s_conservative = t2 / (2 * n1)
    step_s = (t2 - t1) / n1
    if step_s <= 0 or not is_tpu:
        # slope <= 0: the linear model collapsed (and on TPU the
        # linearity gate below rejects the run). Off TPU the gates that
        # guard the slope (linearity, MFU) are inactive and the windows
        # are deliberately short liveness probes, so the conservative
        # whole-window quotient — which can only OVERstate step time —
        # is the only safe estimate there.
        step_s = step_s_conservative
    # NOTE: when slope > conservative (steps DEcelerating, e.g. thermal
    # throttling — fixed_readback would be negative) the slope is the
    # PESSIMISTIC estimate and is kept; the fallback never swaps in the
    # smaller number.
    per_chip = items_per_step / step_s / n_dev
    peak = chip_peak_flops(devices[0])
    peak_bw = chip_peak_hbm_bw(devices[0])
    # measured-vs-roofline join (graftmeter): achieved FLOP/s, bytes/s
    # and the intensity-limited ceiling, from the SAME static model the
    # committed cost budgets pin. Null-safe on CPU/unknown chips.
    from pytorch_multiprocessing_distributed_tpu.analysis.meter import (
        roofline)

    eff = roofline(flops, bytes_accessed, step_s, peak, peak_bw)
    mfu = eff["mfu"]

    # ---- graftzero comparison sweep (--zero): the replicated twin,
    # the serialized (overlap-off) twin and a comm-only probe, each a
    # short drained window — honest syncs, never a dispatch stopwatch.
    # overlap_frac = (t_serialized - t_zero) / t_comm: the fraction of
    # the standalone grad-comm wall the bucketed dependency chain
    # hides under compute. hbm_opt_state_bytes is the measured
    # per-chip ledger delta (sharded vs replicated moments).
    zero_extra = {}
    if zero:
        import jax.numpy as _jnp

        from pytorch_multiprocessing_distributed_tpu.parallel import (
            zero as zero_mod)
        from pytorch_multiprocessing_distributed_tpu.runtime import hbm
        from pytorch_multiprocessing_distributed_tpu.runtime import (
            scope as graftscope)
        from pytorch_multiprocessing_distributed_tpu.train.step import (
            register_state_hbm)

        def timed_steps(fn, st, bargs, n):
            st, m = fn(st, *bargs)
            sync(m)  # drain: the clock cannot absorb queued work
            t0 = time.perf_counter()
            for _ in range(n):
                st, m = fn(st, *bargs)
            sync(m)
            return (time.perf_counter() - t0) / n

        n_cmp = max(2, n1 // 2) if is_tpu else 2
        rep_step, rep_state, rep_args, _, _ = build_workload(
            config, dtype_name, batch, devices, remat=remat,
            vocab_chunks=vocab_chunks, zero=False)
        with hbm.scoped_ledger() as rep_ledger:
            register_state_hbm(rep_state)
            rep_opt_bytes = rep_ledger.snapshot().get(
                "hbm_opt_state_bytes", 0)
        rep_s = timed_steps(rep_step, rep_state, rep_args, n_cmp)

        ser_step, ser_state, ser_args, _, _ = build_workload(
            config, dtype_name, batch, devices, remat=remat,
            vocab_chunks=vocab_chunks, zero=True, zero_overlap=False)
        with hbm.scoped_ledger() as z_ledger:
            register_state_hbm(ser_state)
            zero_opt_bytes = z_ledger.snapshot().get(
                "hbm_opt_state_bytes", 0)
        ser_s = timed_steps(ser_step, ser_state, ser_args, n_cmp)

        mesh = rep_args[0].sharding.mesh
        comm_fn = zero_mod.comm_probe(zero_plan, mesh)
        dummies = [_jnp.zeros((b.padded,), _jnp.dtype(b.dtype))
                   for b in zero_plan.buckets]

        def comm_once(_st, *a):
            out = comm_fn(list(a))
            return _st, out

        comm_s = timed_steps(comm_once, None, tuple(dummies), n_cmp)
        comm_bytes = zero_mod.static_comm_bytes(zero_plan)
        total_comm_bytes = (comm_bytes["reduce_scatter"]
                            + comm_bytes["all_gather"])
        # the measured grad-comm span on the bus (static bytes rider —
        # the fleet.static_collective_bytes discipline), feeding the
        # goodput ledger below like every other bench span
        graftscope.emit_span("train.grad_comm", comm_s, cat="train",
                             nbytes=total_comm_bytes,
                             buckets=len(zero_plan.buckets))
        overlap_frac = None
        if comm_s > 0:
            overlap_frac = max(0.0, min(1.0, (ser_s - step_s) / comm_s))
        zero_extra = {
            "zero": True,
            "zero_shards": zero_plan.num_shards,
            "zero_buckets": len(zero_plan.buckets),
            "replicated_step_ms": round(1000 * rep_s, 3),
            "serialized_step_ms": round(1000 * ser_s, 3),
            "grad_comm_ms": round(1000 * comm_s, 3),
            "grad_comm_bytes": total_comm_bytes,
            "grad_comm_frac_of_step": (round(comm_s / step_s, 4)
                                       if step_s > 0 else None),
            "overlap_frac": (round(overlap_frac, 4)
                             if overlap_frac is not None else None),
            "hbm_opt_state_bytes": zero_opt_bytes,
            "hbm_opt_state_bytes_replicated": rep_opt_bytes,
        }
        del rep_step, rep_state, ser_step, ser_state

    # graftfleet: goodput over this bench run (classified through the
    # same ledger the CLIs serve) + collective skew when a fleet
    # monitor is armed — None-safe on a single host, never a fake 0
    from pytorch_multiprocessing_distributed_tpu.runtime import fleet

    run_wall = time.perf_counter() - t_run0
    gp_events = [
        {"name": "bench.run", "ph": "X", "ts": t_run0,
         "dur": run_wall, "seq": 0},
        {"name": "compile.lower", "ph": "X", "cat": "compile",
         "ts": t_run0, "dur": compile_s, "seq": 1},
    ]
    gp_events += [
        {"name": "train.window", "ph": "X", "ts": t_run0, "dur": t,
         "seq": 2 + i} for i, t in enumerate(timed_windows)]
    goodput = fleet.GoodputLedger.from_events(gp_events).gauges()
    collective_skew_p95_s = None
    collective_straggler_rank = None
    monitor = fleet.active_fleet()
    if monitor is not None:
        report = fleet.FleetCollector(
            monitor.store, run_uid=monitor.run_uid,
            prefix=monitor.prefix).straggler_report()
        if report["collectives"]:
            collective_skew_p95_s = report["skew_p95_s"]
            collective_straggler_rank = report["straggler_rank"]

    result = {
        "metric": metric_for(config)[0],
        "value": round(per_chip, 2),
        "unit": metric_for(config)[1],
        "mfu": mfu,
        "extra": {
            "config": config,
            "dtype": dtype_name,
            "global_batch": batch,
            "devices": n_dev,
            "platform": platform,
            "device_kind": getattr(devices[0], "device_kind", "unknown"),
            "steps_timed": 2 * n1,
            "step_ms": round(1000 * step_s, 3),
            "step_ms_conservative": round(1000 * step_s_conservative, 3),
            "window1_s": round(t1, 4),
            "window2_s": round(t2, 4),
            "linearity_ratio": round(ratio, 4),
            # NaN/Inf are not legal JSON; stringify so the output line
            # always parses even when training diverged
            "final_loss": loss2 if math.isfinite(loss2) else repr(loss2),
            # canonical = the config's own batch/dtype (what the baseline
            # record may be written from; ad-hoc flag runs never claim
            # it). Keyed on the REQUEST (batch_size==0), not the final
            # batch: mesh-alignment rounding of the config's own batch
            # must not bar a config from ever recording a baseline.
            "canonical": (batch_size == 0 and dtype_name == "bfloat16"
                          and is_tpu and not remat
                          and vocab_chunks == 0 and not zero),
            "remat": remat,
            "vocab_chunks": vocab_chunks,
            **zero_extra,
            "flops_per_step_per_chip": flops,
            "peak_flops_per_chip": peak,
            # ---- graftmeter efficiency attribution: every record
            # carries WHERE the time went, not just how much of it
            "bytes_accessed_per_step_per_chip": bytes_accessed,
            "peak_hbm_bw_per_chip": peak_bw,
            "arithmetic_intensity": eff["arithmetic_intensity"],
            "achieved_flops_per_sec": eff["achieved_flops_per_sec"],
            "achieved_bytes_per_sec": eff["achieved_bytes_per_sec"],
            "roofline_flops_per_sec": eff["roofline_flops_per_sec"],
            "roofline_bound": eff["roofline_bound"],
            "roofline_frac": eff["roofline_frac"],
            "hbm_memory": (costs or {}).get("memory"),
            # ---- graftfleet: where the RUN's wall went (compile vs
            # measured stepping vs overhead) + cross-rank skew
            "goodput_frac": round(goodput["goodput_frac"], 4),
            "goodput_compile_s": round(goodput["goodput_compile_s"], 3),
            "goodput_wall_s": round(goodput["goodput_wall_s"], 3),
            "collective_skew_p95_s": collective_skew_p95_s,
            "collective_straggler_rank": collective_straggler_rank,
        },
    }
    if note:
        result["extra"]["backend_note"] = note

    # ---- hard sanity gates: never print a physically impossible number.
    errors = []
    if not math.isfinite(loss2):
        errors.append(f"non-finite loss {loss2}")
    fastest = min(step_s, step_s_conservative)
    if flops and peak and flops / fastest > peak:
        # BOTH estimators must be physically possible (equivalently:
        # per-chip images/sec above the ceiling peak*(batch/n_dev)/flops)
        errors.append(
            f"implied {flops / fastest / 1e12:.1f} TFLOP/s "
            f"({'conservative' if fastest < step_s else 'slope'} estimator)"
            f" exceeds the chip's {peak / 1e12:.0f} TFLOP/s peak "
            f"(worst-case mfu {flops / fastest / peak:.3f}) — "
            "measurement invalid"
        )
    if is_tpu:
        if t2 < min_window:
            errors.append(
                f"timed window {t2 * 1000:.0f} ms < required "
                f"{min_window * 1000:.0f} ms even at n={2 * n1} steps"
            )
        if not (1.6 <= ratio <= 2.6):
            errors.append(
                f"non-linear timing: t(2N)/t(N) = {ratio:.3f}, expected ~2 "
                "— async artifact, number rejected"
            )
    if errors:
        result["error"] = "; ".join(errors)
        result["value"] = 0.0
        result["mfu"] = None
    return result


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="resnet50_imagenet",
                   choices=sorted(CONFIGS),
                   help="default = the BASELINE.md north-star workload")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--batch_size", default=0, type=int,
                   help="global batch (0 = config default)")
    p.add_argument("--min_window", default=1.0, type=float,
                   help="minimum timed-window span in seconds")
    p.add_argument("--warmup", default=5, type=int)
    p.add_argument("--platform", default="auto", choices=["auto", "cpu"],
                   help="cpu = skip the TPU probe and force the host platform")
    p.add_argument("--probe_timeout", default=0.0, type=float,
                   help="per-attempt backend-probe timeout in seconds "
                        "(0 = $PMDT_BENCH_PROBE_TIMEOUT or 180); a "
                        "second HUNG probe fails over to CPU "
                        "immediately regardless of remaining attempts")
    p.add_argument("--probe_attempts",
                   default=int(os.environ.get(
                       "PMDT_BENCH_PROBE_ATTEMPTS", 2)),
                   type=int,
                   help="backend-probe attempts before CPU fallback, "
                        "floored at 1 "
                        "(default $PMDT_BENCH_PROBE_ATTEMPTS or 2 — "
                        "r05 showed a wedged tunnel hangs EVERY probe, "
                        "so a long schedule only burns the window; "
                        "hung probes also skip the 60s backoff)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize activations (jax.checkpoint) — "
                        "trades ~1.3x step time for the activation HBM")
    p.add_argument("--vocab_chunks", default=0, type=int,
                   help="LM configs: stream the head+CE over N vocab "
                        "slices (logits never materialize); 0 = dense. "
                        "Non-canonical probe knob like --remat")
    p.add_argument("--zero", action="store_true",
                   help="graftzero sweep: bench the sharded-update "
                        "step AND its replicated/serialized twins + a "
                        "comm-only probe — records replicated vs "
                        "sharded step time, grad-comm bytes/wall, "
                        "overlap_frac and the per-chip "
                        "hbm_opt_state_bytes delta (~1/N). "
                        "Non-canonical probe knob like --remat")
    return p


def main():
    args = build_parser().parse_args()

    result = None
    try:
        if args.platform == "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")
            devices = jax.devices()
            note = ("TPU init hung; re-exec'd onto CPU"
                    if os.environ.get("PMDT_BENCH_REEXEC") else None)
        else:
            devices, note = init_devices(
                probe_timeout=args.probe_timeout or None,
                probe_attempts=args.probe_attempts)
        _log(f"devices: {len(devices)} x "
             f"{getattr(devices[0], 'device_kind', devices[0].platform)}")
        # post-probe: the cache is for (slow, tunnel-bound) TPU
        # compiles; enable_compilation_cache itself skips CPU
        from pytorch_multiprocessing_distributed_tpu.utils.compile_cache import (  # noqa: E501
            enable_compilation_cache)

        cache_dir = enable_compilation_cache(
            platform_hint=devices[0].platform)
        if cache_dir:
            _log(f"compilation cache: {cache_dir}")
        result = run_bench(args.config, args.dtype, args.batch_size,
                           args.min_window, args.warmup, devices, note,
                           remat=args.remat,
                           vocab_chunks=args.vocab_chunks,
                           zero=args.zero)
    except BaseException as e:  # noqa: BLE001 — the JSON line must appear
        _log(traceback.format_exc())
        result = {
            "metric": metric_for(args.config)[0],
            "value": 0.0,
            "unit": metric_for(args.config)[1],
            "mfu": None,
            "error": f"{type(e).__name__}: {e}",
        }

    # Baseline record read/compare/write. Fully fenced: nothing in here
    # may prevent the JSON line from printing.
    try:
        record_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "baseline_record.json",
        )
        rec = {}
        if os.path.exists(record_path):
            try:
                with open(record_path) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    rec = loaded
            except Exception:
                rec = {}
        # null (not 1.0) when no valid comparison happened: an error or
        # CPU-fallback line must never read as "on par with baseline".
        vs = None
        base = rec.get(result["metric"])
        if isinstance(base, (int, float)):  # legacy scalar format
            base = {"value": base}
        extra = result.get("extra", {})
        comparable = (
            isinstance(base, dict)
            and base.get("value")
            and "error" not in result
            and result["value"] > 0
            # apples-to-apples only: a different batch/dtype/chip is a
            # different experiment, not a regression/speedup
            and all(
                base.get(k) is None or base.get(k) == extra.get(k)
                for k in ("global_batch", "dtype", "device_kind")
            )
            # legacy records lack the remat key; treat them as non-remat
            and bool(base.get("remat", False)) == bool(extra.get("remat"))
            # same rationale for the streamed-CE knob: a chunked probe
            # is a different experiment than the dense canonical run
            and int(base.get("vocab_chunks", 0) or 0)
            == int(extra.get("vocab_chunks", 0) or 0)
            # a record written under a different step-time estimator is a
            # different measurement, not a baseline (the slope estimator
            # reads 10-30% faster than the whole-window quotient purely
            # because it cancels the fixed tunnel-readback latency)
            and base.get("estimator", "whole_window") == "two_window_slope"
        )
        if comparable:
            vs = round(result["value"] / base["value"], 4)
        result["vs_baseline"] = vs
        # A CPU-fallback/error line must not BURY real evidence: point
        # at the last canonical TPU record for this metric so a reader
        # of the JSON line alone can find the chip number that exists
        # on disk (clearly labeled; vs_baseline stays null).
        if ((extra.get("platform") != "tpu" or "error" in result)
                and isinstance(base, dict) and base.get("value")):
            result["last_tpu_record"] = {
                "value": base["value"],
                "unit": base.get("unit", result["unit"]),
                "mfu": base.get("mfu"),
                "estimator": base.get("estimator", "whole_window"),
                "note": "most recent canonical TPU baseline on disk "
                        "(benchmarks/baseline_record.json); THIS line "
                        "is not a valid TPU measurement — see its "
                        "error/backend_note for why",
            }

        # The first VALID TPU number for each metric becomes the baseline
        # record future rounds compare against (gated so an error or a
        # CPU fallback can never pollute it).
        valid_tpu = (
            "error" not in result
            and result["value"] > 0
            and extra.get("platform") == "tpu"
            # only a canonical-config run (config's own batch, bf16) may
            # claim the slot — an ad-hoc --batch_size smoke test must not
            # pin the baseline forever
            and extra.get("canonical")
        )
        prior = rec.get(result["metric"])
        prior_legacy = (
            isinstance(prior, dict)
            and prior.get("estimator", "whole_window") != "two_window_slope"
        )
        if valid_tpu and (result["metric"] not in rec or prior_legacy):
            rec[result["metric"]] = {
                "value": result["value"],
                "unit": result["unit"],
                "mfu": result["mfu"],
                "device_kind": extra["device_kind"],
                "global_batch": extra["global_batch"],
                "dtype": extra["dtype"],
                "remat": bool(extra.get("remat")),
                "estimator": "two_window_slope",
            }
            os.makedirs(os.path.dirname(record_path), exist_ok=True)
            with open(record_path, "w") as f:
                json.dump(rec, f, indent=2, sort_keys=True)
            _log(f"recorded baseline for {result['metric']} -> {record_path}")
    except Exception as e:
        _log(f"baseline record handling failed (non-fatal): {e}")
        result.setdefault("vs_baseline", None)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
