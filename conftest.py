"""Repo-level pytest bootstrap.

Tests exercise the multi-chip code paths on a virtualized 8-device CPU
"mesh" (the TPU-native answer to testing multi-node without a pod, see
SURVEY.md §4): XLA is forced onto the host platform and told to expose 8
devices BEFORE any backend is initialized. Set PMDT_TEST_ON_TPU=1 to run
the suite against real chips instead (note: multi-device tests assume 8
devices; on smaller real topologies they will skip/fail by design).

Note: this environment pre-imports jax at interpreter startup (axon
sitecustomize), so env vars alone are too late — jax.config must be
updated directly.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if not os.environ.get("PMDT_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(__file__))

# version-skew shim: tests call jax.shard_map directly (current-jax
# idiom); on a 0.4.x container the alias resolves to
# jax.experimental.shard_map.shard_map with check_vma -> check_rep
# (utils/compat.py). Additive only — a real jax.shard_map wins.
from pytorch_multiprocessing_distributed_tpu.utils.compat import (  # noqa: E402
    install_shard_map_alias)

install_shard_map_alias()

# runtime jit-hygiene sentinels as suite-wide fixtures
# (transfer_sentinel / recompile_sentinel — tests/test_sentinels.py
# pins them on the train step, generate() and the serving engine)
pytest_plugins = (
    "pytorch_multiprocessing_distributed_tpu.analysis.sentinels",
)
