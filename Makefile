# Convenience targets for the canonical workflows. Each one is the
# exact invocation the docs/tests/driver use — no hidden flags.

PYTEST_ENV = XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu

.PHONY: test test-fast lint check check-update chaos soak scope meter \
        fleet spec zero route wire scale quant dryrun bench bench-cpu \
        store trace life clean

# graftlint: AST-only jit-hygiene gate (no jax import, milliseconds).
# Exit 1 on any non-baselined finding; the tier-1 suite and
# benchmarks/on_grant.sh enforce the same gate.
lint:
	python -m pytorch_multiprocessing_distributed_tpu.analysis.lint

# graftcheck + graftmeter: jaxpr-level program auditor — collective
# budgets, donation/resharding/dtype audits, golden fingerprints —
# plus the committed cost/memory budgets (analysis/costs.json:
# FLOPs, bytes accessed, argument/output/temp HBM per canonical
# program), all in ONE pass (traces/compiles on the 8-device CPU
# mesh; never executes). Exit 1 on any drift; enforced in tier-1
# (tests/test_graftcheck.py) and on_grant.sh step 0.
check:
	$(PYTEST_ENV) python -m pytorch_multiprocessing_distributed_tpu.analysis.check

# refresh analysis/fingerprints.json AND analysis/costs.json after a
# DELIBERATE program change (review the JSON diffs in the PR; inline
# invariants still enforce)
check-update:
	$(PYTEST_ENV) python -m pytorch_multiprocessing_distributed_tpu.analysis.check --update

# graftfault: the deterministic fault matrix — every registered
# injection site swept (recover or fail fast, unaffected requests
# token-exact), plus checkpoint-corruption recovery and the SIGTERM
# preemption path. Seeded FaultPlans: the same faults hit the same
# operations on every run. Part of tier-1; this target runs it alone.
chaos:
	$(PYTEST_ENV) python -m pytest tests/test_graftfault.py tests/test_runtime_store.py -q

# graftrace: the concurrency gate alone — the GL119/120/121 static
# pass over the package (part of `make lint`, split out here) plus
# the deterministic-interleaving suite: pinned adversarial schedules
# over the real runtime objects (the PR-15 stale-worker canary,
# kill-vs-drain, journal close-vs-fsync), exhaustive small-schedule
# enumeration, and the realized-vs-static lock-graph audit.
trace:
	python -m pytorch_multiprocessing_distributed_tpu.analysis.lint
	$(PYTEST_ENV) python -m pytest tests/test_graftrace.py -q

# graftheal: the elastic-supervision suite (liveness gate, coordinated
# abort, supervised restart, graceful drain + redelivery journal) PLUS
# the slow-marked chaos soak — N requests under a background fault
# rate with one injected mid-run restart; every request completes
# token-exact or fails named, journal replay accounted.
soak:
	$(PYTEST_ENV) python -m pytest tests/test_graftheal.py -q

# graftscope: observability smoke — a synthetic engine run must emit a
# Perfetto-loadable Chrome trace, a JSONL event log with COMPLETE
# per-request lifecycles, and a parseable Prometheus text exposition
# (plus one live scrape of the /metrics endpoint). Schema drift fails
# here, not during an incident. Same body runs in tier-1
# (test_scope_smoke_end_to_end in tests/test_graftscope.py).
scope:
	$(PYTEST_ENV) python benchmarks/scope_smoke.py

# graftmeter: capacity/efficiency smoke — a registry canary must
# re-measure clean against the committed analysis/costs.json budgets,
# plan_capacity's slot prediction must match a real CPU-backend
# SlotPool allocation within 0.5%, a served engine with the HBM
# ledger armed must expose pmdt_hbm_* gauges on a live /metrics
# scrape, and the ledger must render to a breakdown PNG. Same body
# runs in tier-1 (test_meter_smoke_end_to_end in
# tests/test_graftmeter.py); the full 15-program budget gate is
# `make check`.
meter:
	$(PYTEST_ENV) python benchmarks/meter_smoke.py

# graftfleet: cross-host observability smoke — a synthetic 2-rank run
# over an in-process store must produce ONE merged per-rank timeline
# (a Chrome-trace lane per rank, clock-aligned), a straggler report
# NAMING the injected-slow rank with its arrival-skew percentiles,
# and a goodput fraction on a live /snapshot.json scrape. Same body
# runs in tier-1 (test_fleet_smoke_end_to_end in
# tests/test_graftfleet.py).
fleet:
	$(PYTEST_ENV) python benchmarks/fleet_smoke.py

# graftspec: speculative-decode smoke — the spec engine's greedy
# streams must be byte-identical to the non-speculative engine AND
# generate(), a repetitive stream must clear >1.0 accepted tokens per
# target-model step in FEWER dispatches, k=0 must run zero spec
# passes, and acceptance telemetry + goodput_spec_waste_s must ride
# the bus. Same body runs in tier-1 (test_spec_smoke_end_to_end in
# tests/test_graftspec.py).
spec:
	$(PYTEST_ENV) python benchmarks/spec_smoke.py

# graftquant: int8-KV smoke — the quantized engine's greedy streams
# (dense AND paged) must be byte-identical to the model-dtype engine
# at the head_dim=64 geometry, per_slot_kv_bytes must match a real
# int8 pool byte-for-byte with the bf16 ratio clearing 1.8x, the
# teacher-forced logit delta must be NONZERO and < 5e-3, and a
# quantized detached prefill must splice transcript-equal at < 0.6x
# the model-dtype payload. Same body runs in tier-1
# (test_quant_smoke_end_to_end in tests/test_graftquant.py).
quant:
	$(PYTEST_ENV) python benchmarks/quant_smoke.py

# graftzero: sharded-weight-update smoke — on a 2-shard CPU mesh the
# traced zero DP step must move grads as exactly ONE reduce-scatter +
# ONE all-gather with ZERO grad-sized psums (budget flip), the armed
# HBM ledger must show hbm_opt_state_bytes == the plan's per-chip
# shard bytes (~1/N, byte-exact vs plan_capacity(zero_shards=N)), a
# 3-step sharded trajectory must be BIT-identical to the replicated
# one, and a gather-on-save checkpoint must round-trip into a
# replicated run. Same body runs in tier-1
# (test_zero_smoke_end_to_end in tests/test_graftzero.py).
zero:
	$(PYTEST_ENV) python benchmarks/zero_smoke.py

# graftroute: disaggregated-fleet smoke — 2 paged replicas behind the
# router over an in-process MemStore must serve byte-identically to
# the single-engine baseline, survive one injected replica death by
# journal redelivery to the peer (fleet token count dedup-verified),
# route an identical prompt to the replica holding its cached pages
# (engine-level FULL hit, warm TTFT < cold), and publish the replica
# directory to the store. Same body runs in tier-1
# (test_route_smoke_end_to_end in tests/test_graftroute.py).
route:
	$(PYTEST_ENV) python benchmarks/route_smoke.py

# graftwire: socket-transport smoke — a router in THIS process drives
# 2 replica-server SUBPROCESSES over localhost: prefill->decode
# PageTransfer as raw framed numpy (bytes metered, clean drain, both
# children exit 0), then a SIGKILL -9 of the busiest replica process
# mid-run -> its WAL redelivers to the peer under original uids,
# every stream byte-identical to the in-process fleet, fleet token
# count dedup-verified. Same body runs in tier-1 (slow-marked
# test_wire_smoke_end_to_end in tests/test_graftwire.py).
wire:
	$(PYTEST_ENV) python benchmarks/wire_smoke.py

# graftscale: elastic-fleet smoke — spawn-from-zero, a traffic burst
# scaling REAL --listen replica subprocesses UP (sustained sheds ->
# supervised spawn + prefix prewarm before admission), an idle
# plateau draining them back DOWN (hysteresis + cooldown, children
# exit on their own), then a rolling v1->v2 weight rollout under
# continuous load: zero failed requests, every stream byte-exact to
# ONE version, every child pid reaped loudly at exit. Same body runs
# in tier-1 (slow-marked test_scale_smoke_script_end_to_end in
# tests/test_graftscale.py).
scale:
	$(PYTEST_ENV) python benchmarks/scale_smoke.py

# graftlife: the resource-lifecycle gate — the GL123-125 static pass
# over the package (part of `make lint`, split out here) plus the
# churny ownership-ledger soak: an autoscaled fleet under deadlines,
# withdraws, work stealing and one injected replica death must
# drain to an EMPTY ledger for every resource class (slots, pages,
# buffers, journal admits, transfers, sockets, threads, files), and
# every realized acquire site must be one the static model admits.
life:
	python -m pytorch_multiprocessing_distributed_tpu.analysis.lint
	$(PYTEST_ENV) python benchmarks/life_smoke.py

# full suite on the virtual 8-device CPU mesh (incl. slow e2e CLI runs)
test:
	$(PYTEST_ENV) python -m pytest tests/ -q

# fast suite (slow-marked e2e runs excluded)
test-fast:
	$(PYTEST_ENV) python -m pytest tests/ -q -m "not slow"

# the driver's multi-chip dry-run: full sharded train steps
# (dp/tp/zero1/fsdp/sp/zigzag/ulysses/moe/pp/1f1b/chunked-CE) on 8
# virtual devices
dryrun:
	python -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

# one-JSON-line benchmark (probes the TPU, falls back to CPU liveness)
bench:
	python bench.py

# bench without touching the TPU plugin at all
bench-cpu:
	python bench.py --platform cpu

# the C++ TCP rendezvous store (ctypes-loaded on demand at runtime)
store:
	$(MAKE) -C csrc

clean:
	rm -rf csrc/build .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
