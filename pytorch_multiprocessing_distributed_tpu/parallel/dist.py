"""Process bring-up and host-level rendezvous.

TPU-native replacement for the reference's launcher + init
(``main.py:180-193``): no ``mp.spawn`` — TPU runs ONE Python process per
host controlling all local chips, and multi-host pods rendezvous through
the JAX coordinator over DCN (``jax.distributed.initialize``), not a
hand-rolled env-var TCP store on ``127.0.0.1:20080``.

``rank`` in the reference is a per-GPU process index; here the analogous
host-level notion is ``jax.process_index()`` and the per-shard notion is
``lax.axis_index`` inside the step. "rank 0 does the logging" becomes
``is_primary()``.

A C++ TCP key-value store (the c10d ``TCPStore`` analogue) is provided in
:mod:`..runtime.store` for control-plane coordination outside of JAX —
experiment-level barriers, health keys — with the same ``set/get/wait/
add`` surface.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


_initialized = False


def init_process(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    local_device_ids=None,
) -> None:
    """Join the multi-host pod (or no-op on a single host).

    Mirrors ``init_process`` (reference ``main.py:190-193``) at the host
    level. With no arguments, auto-detects: if JAX's standard cluster env
    vars are present (``JAX_COORDINATOR_ADDRESS`` etc.) or explicit args
    are given, calls ``jax.distributed.initialize``; otherwise single-host
    mode. Safe to call twice (idempotent), unlike the reference which
    would deadlock re-joining NCCL.
    """
    global _initialized
    if _initialized:
        return
    want_distributed = (
        coordinator_address is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    if want_distributed:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    _initialized = True


def destroy_process_group() -> None:
    """Leave the pod (reference ``main.py:84``). No-op on a single host."""
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False


def get_rank() -> int:
    """Host-level rank: ``jax.process_index()`` (reference ``dist.get_rank()``)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of participating hosts (NOT chips)."""
    return jax.process_count()


def is_primary() -> bool:
    """True on the host that owns logging/checkpoint/plot side effects.

    The reference gates these on ``dist.get_rank() == 0`` (``main.py:69,
    75, 81, 119, 129, 162, 169``).
    """
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every host arrives (control-plane sync)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
