"""Process bring-up and host-level rendezvous.

TPU-native replacement for the reference's launcher + init
(``main.py:180-193``): no ``mp.spawn`` — TPU runs ONE Python process per
host controlling all local chips, and multi-host pods rendezvous through
the JAX coordinator over DCN (``jax.distributed.initialize``), not a
hand-rolled env-var TCP store on ``127.0.0.1:20080``.

``rank`` in the reference is a per-GPU process index; here the analogous
host-level notion is ``jax.process_index()`` and the per-shard notion is
``lax.axis_index`` inside the step. "rank 0 does the logging" becomes
``is_primary()``.

A C++ TCP key-value store (the c10d ``TCPStore`` analogue) is provided in
:mod:`..runtime.store` for control-plane coordination outside of JAX —
experiment-level barriers, health keys — with the same ``set/get/wait/
add`` surface.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional

import jax

from ..runtime import fleet as graftfleet
from ..runtime import scope as graftscope
from ..runtime.faults import (maybe_fault, register_site,
                              run_with_timeout)

_initialized = False
_store = None         # TCPStore client kept for control-plane use
_store_server = None  # TCPStoreServer handle when this process hosts it

# the bring-up hazard point: a pod whose rendezvous/barrier faults
# must fail FAST and NAMED (the reference inherits NCCL's silent hang)
_SITE_RENDEZVOUS = register_site(
    "runtime.rendezvous",
    "multihost rendezvous/barrier on the control plane (store "
    "bring-up, coordinator publish, experiment barriers)")

# graftheal's pre-collective liveness gate (runtime.heal): consulted
# before every host-level collective boundary this module (and the
# trainers' windowed-fetch boundaries) own, so a DEAD peer raises a
# named PeerLostError on every SURVIVOR instead of hanging it at the
# next psum. Uninstalled cost: one module-global read + None check —
# the graftfault/graftscope arming discipline.
_collective_gate = None


def install_collective_gate(fn) -> None:
    """Install ``fn`` (raises :class:`~..runtime.faults.PeerLostError`
    on a lost peer / poison key) as the pre-collective gate —
    ``runtime.heal.arm`` does this for its monitor."""
    global _collective_gate
    _collective_gate = fn


def clear_collective_gate() -> None:
    global _collective_gate
    _collective_gate = None


def gate_collectives() -> None:
    """Run the liveness gate if one is armed (no-op otherwise). Call
    at any host boundary that is about to enter (or dispatch work
    containing) a collective a dead peer would wedge — the step
    loops' windowed-fetch boundaries do.

    graftfleet: the arrival stamp lands FIRST (one module-global read
    when no fleet is armed) — this rank's arrival at the boundary is
    the straggler report's raw datum, and it must record even when
    the gate then raises a named PeerLostError (the stamp is exactly
    how the collector sees who was alive and when)."""
    graftfleet.note_arrival("dist.gate")
    gate = _collective_gate
    if gate is not None:
        gate()


def _run_with_watchdog(fn, timeout: float, what: str, hint: str):
    """Bounded bring-up: ``jax.distributed.initialize`` (and backend
    bring-up generally) can HANG rather than raise when a peer never
    shows up — the reference inherits the same failure mode from NCCL
    and just sits there. graftfault's shared watchdog applies the
    bench.py probing discipline here: complete, raise, or fail fast
    with an ACTIONABLE :class:`~..runtime.faults.FaultTimeout`
    (SURVEY.md §5 failure detection: "fail-fast pod init with clear
    coordinator-timeout errors")."""
    return run_with_timeout(fn, timeout, what, hint)


def _is_local_host(host: str) -> bool:
    if host in ("127.0.0.1", "localhost", "0.0.0.0"):
        return True
    try:
        return host in (socket.gethostname(), socket.getfqdn(),
                        socket.gethostbyname(socket.gethostname()))
    except OSError:
        return False


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _store_rendezvous(timeout: float):
    """Rendezvous rank/world/coordinator through the C++ TCP store.

    The TPU-native analogue of the env-var TCPStore rendezvous behind the
    reference's ``init_process_group`` (``main.py:190-193``): the rank-0
    process hosts the store at ``PMDT_MASTER_ADDR`` (csrc/tcp_store.cpp),
    every process checks in, rank 0 publishes the JAX coordinator
    address, and everyone returns ``(coordinator, world, rank)`` ready to
    feed ``jax.distributed.initialize``. Unlike the reference's hardcoded
    ``127.0.0.1:20080``, the address comes from the environment and every
    wait is bounded with an error naming what was being waited for.

    Env contract: ``PMDT_MASTER_ADDR=host:port`` (required),
    ``PMDT_WORLD_SIZE=N`` (required), ``PMDT_RANK`` (optional — without
    it ranks are assigned first-come via the store's atomic counter, and
    only a process local to the master host will try to host the store).
    """
    from ..runtime.store import TCPStore, TCPStoreServer

    maybe_fault(_SITE_RENDEZVOUS)
    master = os.environ["PMDT_MASTER_ADDR"]
    try:
        host, port_s = master.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        raise RuntimeError(
            f"PMDT_MASTER_ADDR={master!r} is not host:port"
        ) from None
    world_s = os.environ.get("PMDT_WORLD_SIZE")
    if not world_s:
        raise RuntimeError(
            "PMDT_MASTER_ADDR is set but PMDT_WORLD_SIZE is not; "
            "store-mediated bring-up needs the world size (export "
            "PMDT_WORLD_SIZE=<number of processes>)"
        )
    world = int(world_s)
    rank_env = os.environ.get("PMDT_RANK")
    deadline = time.monotonic() + timeout

    # Host the store when this process is (or may be) rank 0. An
    # EXPLICIT rank 0 hosts unconditionally (like torch TCPStore's
    # is_master flag): hostname heuristics must not be able to produce a
    # false negative on a multi-NIC/aliased master — a failed bind just
    # falls through to connecting. In first-come mode, only a process
    # that looks local to the master host tries.
    global _store_server
    if rank_env == "0" or (rank_env is None and _is_local_host(host)):
        try:
            _store_server = TCPStoreServer(port)
        except OSError:
            _store_server = None

    store = None
    last_err = None
    while time.monotonic() < deadline:
        try:
            store = TCPStore(host, port)
            break
        except ConnectionError as e:
            last_err = e
            time.sleep(0.2)
    if store is None:
        raise RuntimeError(
            f"could not reach the rendezvous store at {master} within "
            f"{timeout:.0f}s ({last_err}). Is the rank-0 process up, is "
            "PMDT_MASTER_ADDR identical on every process, and is the "
            "port reachable (firewall)?"
        )

    rank = int(rank_env) if rank_env is not None else store.add("rendezvous/next_rank", 1) - 1
    if rank >= world:
        store.close()
        raise RuntimeError(
            f"rank {rank} >= PMDT_WORLD_SIZE {world}: more processes "
            "checked in than the declared world size"
        )

    coord_key = "rendezvous/jax_coordinator"
    if rank == 0:
        # Publish an address that resolves to THIS machine — in
        # first-come mode rank 0 may not be on the master host, and the
        # free port was probed here, so "master_host:port" would point
        # at a machine where nothing will listen. The outbound IP toward
        # the store is reachable by every peer that can reach the store.
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
            probe.connect((host, port))  # no traffic; just routes
            my_ip = probe.getsockname()[0]
        coordinator = f"{my_ip}:{_free_port()}"
        store.set(coord_key, coordinator.encode())
    else:
        # bounded poll (store.wait blocks unboundedly by design)
        coordinator = None
        while time.monotonic() < deadline:
            v = store.get(coord_key)
            if v:
                coordinator = v.decode()
                break
            time.sleep(0.1)
        if coordinator is None:
            store.close()
            raise RuntimeError(
                f"rank {rank}: rank 0 did not publish the JAX coordinator "
                f"address at the store within {timeout:.0f}s — it likely "
                "crashed before or during bring-up; check its logs first"
            )

    global _store
    _store = store
    # graftheal env hook: PMDT_HEARTBEAT="soft:hard[:interval]" (s)
    # arms a liveness monitor over THIS rendezvous store — every host
    # beats, and the pre-collective gate turns a silent peer into a
    # named PeerLostError on every survivor (no-op when unset)
    from ..runtime import heal

    heal.monitor_from_env(store, str(rank),
                          [str(i) for i in range(world)])
    # graftfleet env hook: PMDT_FLEET=<run_uid> arms the fleet
    # monitor over the SAME store — rank-tagged events, clock
    # handshake, endpoint publication, collective arrival stamps
    # (no-op when unset)
    graftfleet.monitor_from_env(store, socket.gethostname(), rank,
                                world)
    return coordinator, world, rank


def init_process(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    local_device_ids=None,
    timeout: Optional[float] = None,
) -> None:
    """Join the multi-host pod (or no-op on a single host).

    Mirrors ``init_process`` (reference ``main.py:190-193``) at the host
    level. Resolution order:

    1. explicit args or JAX's standard cluster env vars
       (``JAX_COORDINATOR_ADDRESS`` etc.) -> ``jax.distributed.initialize``;
    2. ``PMDT_MASTER_ADDR`` (+ ``PMDT_WORLD_SIZE``) -> rendezvous through
       the C++ TCP store first (:func:`_store_rendezvous`), then
       ``jax.distributed.initialize`` with the agreed coordinates;
    3. neither -> single-host mode, no-op.

    Every distributed path runs under a bounded watchdog
    (``PMDT_INIT_TIMEOUT`` seconds, default 180) that fails fast with an
    actionable message instead of hanging forever on a missing peer.
    Safe to call twice (idempotent), unlike the reference which would
    deadlock re-joining NCCL.
    """
    global _initialized
    if _initialized:
        return
    if timeout is None:
        timeout = float(os.environ.get("PMDT_INIT_TIMEOUT", 180))

    want_distributed = (
        coordinator_address is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    use_store = (
        not want_distributed
        and os.environ.get("PMDT_MASTER_ADDR")
        and not os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if use_store:
        t0 = time.monotonic()
        coordinator_address, num_processes, process_id = _store_rendezvous(
            timeout
        )
        timeout = max(10.0, timeout - (time.monotonic() - t0))
        want_distributed = True

    if want_distributed:
        where = coordinator_address or os.environ.get(
            "JAX_COORDINATOR_ADDRESS", "<env-provided>"
        )
        _run_with_watchdog(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            ),
            timeout,
            what=f"jax.distributed.initialize (coordinator {where})",
            hint=(
                "Not all processes reached the coordinator. Check that "
                "every process was started with the same world size and "
                "coordinator address, that none crashed earlier (inspect "
                "their logs), and that the port is reachable. Set "
                "PMDT_INIT_TIMEOUT to adjust this deadline."
            ),
        )
    _initialized = True


def destroy_process_group() -> None:
    """Leave the pod (reference ``main.py:84``). No-op on a single host."""
    global _initialized, _store, _store_server
    # a monitor gating over the store about to close must go first
    from ..runtime import heal

    heal.disarm()
    graftfleet.disarm()
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    if _store is not None:
        _store.close()
        _store = None
    if _store_server is not None:
        _store_server.stop()
        _store_server = None
    _initialized = False


def get_rank() -> int:
    """Host-level rank: ``jax.process_index()`` (reference ``dist.get_rank()``)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of participating hosts (NOT chips)."""
    return jax.process_count()


def is_primary() -> bool:
    """True on the host that owns logging/checkpoint/plot side effects.

    The reference gates these on ``dist.get_rank() == 0`` (``main.py:69,
    75, 81, 119, 129, 162, 169``).
    """
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every host arrives (control-plane sync). An
    injected fault here surfaces named (fail fast) — a half-synced
    fleet must never proceed silently, and with graftheal armed a
    DEAD peer fails this barrier named BEFORE anyone blocks in it.

    graftfleet: this rank's arrival is stamped to the store and the
    blocking sync itself is a ``collective.barrier`` span — the wait
    INSIDE the span is precisely this rank's lead over the last
    arriver, so barrier spans and the straggler report cross-check."""
    gate_collectives()
    maybe_fault(_SITE_RENDEZVOUS)
    graftfleet.note_arrival(f"barrier:{name}")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # attr key must not be "name" — span() binds that to the
        # event name (and the attr would clobber it in to_dict)
        with graftscope.span("collective.barrier", cat="collective",
                             barrier=name):
            multihost_utils.sync_global_devices(name)
