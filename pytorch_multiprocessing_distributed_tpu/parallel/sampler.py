"""Deterministic per-replica dataset sharding.

Semantic parity target: ``torch.utils.data.distributed.DistributedSampler``
as the reference uses it on both splits (``data.py:31-37``, ``shuffle=True``):

- epoch-seeded permutation: generator seeded with ``seed + epoch``;
- wraparound padding so every replica gets ``ceil(N / world)`` samples
  (eval therefore sees duplicated samples when ``N % world != 0`` — the
  reference behavior of record, SURVEY.md §3.5.3);
- rank r takes the strided slice ``indices[r::world]``.

When torch is importable the permutation is drawn from ``torch.randperm``
with a ``torch.Generator`` — making the shard contents **index-exact**
with the reference sampler for the same (seed, epoch, rank, world). The
numpy fallback keeps identical sharding semantics with a different
permutation stream.
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np

try:  # torch-cpu is an optional, test/parity-time dependency only
    import torch as _torch
except Exception:  # pragma: no cover
    _torch = None


def padded_epoch_indices(
    dataset_size: int,
    num_replicas: int,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = False,
) -> List[int]:
    """The full padded (or truncated) epoch index list, shared by all
    replicas — replica ``r``'s shard is the strided slice ``[r::world]``.

    Computed ONCE per epoch by the loader and sliced per replica (the
    permutation is identical across replicas by construction, so there is
    no reason to redraw it world_size times).
    """
    if shuffle:
        if _torch is not None:
            g = _torch.Generator()
            g.manual_seed(seed + epoch)
            indices = _torch.randperm(dataset_size, generator=g).tolist()
        else:
            rng = np.random.default_rng(seed + epoch)
            indices = rng.permutation(dataset_size).tolist()
    else:
        indices = list(range(dataset_size))

    if drop_last and dataset_size % num_replicas:
        num_samples = dataset_size // num_replicas
    else:
        num_samples = math.ceil(dataset_size / num_replicas)
    total_size = num_samples * num_replicas

    if not drop_last:
        padding = total_size - len(indices)
        if padding > 0:
            if padding <= len(indices):
                indices += indices[:padding]
            else:  # tiny dataset: repeat whole list (torch semantics)
                reps = math.ceil(padding / len(indices))
                indices += (indices * reps)[:padding]
    else:
        indices = indices[:total_size]
    assert len(indices) == total_size
    return indices


class DistributedShardSampler:
    """Index sampler for one replica of a sharded dataset.

    Args:
      dataset_size: total number of samples.
      rank: this replica's index on the data axis.
      num_replicas: data-axis size (the reference's ``world_size``).
      shuffle: epoch-seeded shuffle (the reference passes True for BOTH
        train and test, ``data.py:31-37``).
      seed: base seed (torch's default 0).
      drop_last: drop the tail instead of padding (torch semantics; the
        reference uses the default False).
    """

    def __init__(
        self,
        dataset_size: int,
        rank: int,
        num_replicas: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_size = dataset_size
        self.rank = rank
        self.num_replicas = num_replicas
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_size % num_replicas:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = math.ceil(dataset_size / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle for a new epoch (torch's ``set_epoch``)."""
        self.epoch = epoch

    def indices(self) -> List[int]:
        """This replica's index list for the current epoch."""
        padded = padded_epoch_indices(
            self.dataset_size,
            self.num_replicas,
            shuffle=self.shuffle,
            seed=self.seed,
            epoch=self.epoch,
            drop_last=self.drop_last,
        )
        shard = padded[self.rank : self.total_size : self.num_replicas]
        assert len(shard) == self.num_samples
        return shard

    def valid_mask(self) -> np.ndarray:
        """True where the shard position holds a REAL sample, False where
        it holds a wraparound-padding duplicate.

        Padding positions in the flat epoch list are exactly positions
        ``>= dataset_size`` (the appended wraparound tail); shard ``r``
        holds flat positions ``r, r+world, r+2*world, ...``. This is what
        makes eval accuracy exact when ``N % world != 0`` — the reference
        cannot express it (DistributedSampler hides which samples are
        duplicates), and its eval double-counts them (SURVEY.md §3.5.3).
        """
        positions = self.rank + self.num_replicas * np.arange(self.num_samples)
        return positions < self.dataset_size

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples
