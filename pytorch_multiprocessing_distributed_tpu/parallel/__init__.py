"""Distributed runtime: mesh, collectives, sharding, process bring-up.

The TPU-native replacement for the reference's distributed stack
(SURVEY.md §2.2): ``mp.spawn`` + ``dist.init_process_group('nccl')``
(reference ``main.py:180-193``) becomes :func:`init_process` over a named
:class:`jax.sharding.Mesh`; NCCL collectives become XLA collectives over
ICI/DCN (:mod:`.collectives`); ``DistributedSampler`` (reference
``data.py:31-37``) becomes :class:`DistributedShardSampler`.
"""

from .mesh import make_mesh, data_axis_size, DATA_AXIS, MODEL_AXIS
from .collectives import (
    all_gather,
    all_reduce,
    pmean,
    ppermute,
    psum,
    reduce_scatter,
    reduce_tensor,
)
from .sampler import DistributedShardSampler
from .ring_attention import ring_attention, zigzag_indices
from .ulysses import ulysses_attention
from .pipeline import pipeline_1f1b, pipeline_apply
from .gpt_pipeline import (
    PIPE_AXIS,
    create_pipelined_lm_state,
    make_pipelined_lm_eval_step,
    make_pipelined_lm_train_step,
    stack_pipeline_params,
    unstack_pipeline_params,
)
from .dist import (
    barrier,
    destroy_process_group,
    get_rank,
    get_world_size,
    init_process,
    is_primary,
)

__all__ = [
    "make_mesh",
    "data_axis_size",
    "DATA_AXIS",
    "MODEL_AXIS",
    "psum",
    "pmean",
    "all_gather",
    "all_reduce",
    "reduce_scatter",
    "ppermute",
    "reduce_tensor",
    "DistributedShardSampler",
    "ring_attention",
    "ulysses_attention",
    "pipeline_1f1b",
    "pipeline_apply",
    "init_process",
    "destroy_process_group",
    "get_rank",
    "get_world_size",
    "is_primary",
    "barrier",
]
