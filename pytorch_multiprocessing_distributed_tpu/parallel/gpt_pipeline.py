"""Pipelined GPT training: heterogeneous stages over a ``pipe`` mesh axis.

:mod:`.pipeline` provides the homogeneous GPipe primitive; a real LM is
NOT homogeneous — it is embed -> N blocks -> head, and the embedding /
head tables are among the largest tensors in the model. The torch way
to pipeline this is per-stage ``nn.Module``\\ s with different code on
different ranks. The TPU-native way, used here, keeps ONE SPMD program
and makes every stage-heterogeneous tensor *sharded* over the pipe axis
instead:

- **embedding**: the vocab dimension is sharded over ``pipe``
  (Megatron-style vocab-parallel lookup: each shard gathers the rows it
  owns, one ``psum`` materializes the activation);
- **blocks**: stacked ``[n_stages, layers_per_stage, ...]`` and sharded
  over ``pipe`` — stage *s* holds only its own layers; microbatches flow
  through :func:`.pipeline.pipeline_apply` (``ppermute`` ring, GPipe
  schedule, differentiable scan);
- **head**: output-vocab sharded over ``pipe``; under the default
  ``schedule="gpipe"`` the next-token loss is computed vocab-parallel
  (local partial logits, ``pmax``/``psum`` log-sum-exp) so the full
  ``[B, S, V]`` logits tensor never materializes anywhere. The
  ``"1f1b"`` schedule instead weight-GATHERS the head for the step and
  evaluates a dense per-microbatch CE where the last stage's output
  lands (``[mb, s, V]`` only — params stay vocab-sharded at rest; the
  trade buys O(n_stages) activation residency, see ``body_1f1b``).

Every parameter therefore has exactly one resident shard per pipe
stage (embed/head rows live where their slice lives), composing with
data parallelism over the ``data`` axis — all in one jitted
``shard_map`` with ``check_vma=True`` (required for correct collective
AD transposes, see :mod:`.pipeline`).

No reference counterpart (the reference is single-stage DDP,
SURVEY.md §2.3); geometry validation mirrors :func:`.mesh.make_mesh`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import shard_map
from .mesh import DATA_AXIS

# NB: ..train imports stay function-local — parallel/__init__ re-exports
# this module and ..train imports ..parallel, so a top-level import here
# would cycle.

PIPE_AXIS = "pipe"
_LN_EPS = 1e-6  # flax nn.LayerNorm default, as used by the GPT family


def _num_layers(params) -> int:
    n = 0
    while f"block_{n}" in params:
        n += 1
    return n


def stack_pipeline_params(params, n_stages: int):
    """GPT ``init`` params -> the pipe-shardable tree.

    Returns a dict whose pipe-sharded leaves carry a leading
    ``n_stages`` dim: ``embed`` ``[S, ceil(V/S), D]`` (vocab
    row-sharded, zero-padded), ``blocks`` ``[S, L/S, ...]``, ``head_k``
    ``[S, D, ceil(V/S)]`` / ``head_b`` ``[S, ceil(V/S)]`` (vocab
    col-sharded, zero-padded). ``head_b`` is present only when the GPT
    has a head bias — a ``head_bias=False`` model (the HF-GPT-2 interop
    configuration) simply has no such leaf. Padded vocab slots are NOT
    masked here: the forward passes mask them explicitly from the true
    ``vocab_size`` (``slot_id >= vocab_size -> -1e9``), so masking
    never depends on a bias slot existing. ``pos`` and ``ln_f`` are
    small and replicated.
    """
    num_layers = _num_layers(params)
    if num_layers == 0:
        raise ValueError("params has no block_<i> entries — not a GPT tree")
    if num_layers % n_stages:
        raise ValueError(
            f"{num_layers} layers not divisible by n_stages={n_stages}"
        )
    per = num_layers // n_stages
    blocks = jax.tree.map(
        lambda *ls: jnp.stack(ls),
        *[params[f"block_{i}"] for i in range(num_layers)],
    )
    blocks = jax.tree.map(
        lambda l: l.reshape(n_stages, per, *l.shape[1:]), blocks
    )

    embed = params["embed"]  # [V, D]
    vocab, d = embed.shape
    vs = -(-vocab // n_stages)  # ceil
    pad = n_stages * vs - vocab
    embed = jnp.pad(embed, ((0, pad), (0, 0))).reshape(n_stages, vs, d)
    head_k = params["head"]["kernel"]  # [D, V]
    head_k = jnp.pad(head_k, ((0, 0), (0, pad)))
    head_k = head_k.reshape(d, n_stages, vs).transpose(1, 0, 2)

    out = {
        "embed": embed,
        # copy pass-through leaves: sharing buffers with the source tree
        # would let a donating step on the SOURCE state delete them
        "pos": jnp.array(params["pos_embed"], copy=True),
        "blocks": blocks,
        "ln_f": jax.tree.map(lambda l: jnp.array(l, copy=True),
                             params["ln_final"]),
        "head_k": head_k,
    }
    if "bias" in params["head"]:
        out["head_b"] = jnp.pad(
            params["head"]["bias"], (0, pad)).reshape(n_stages, vs)
    return out


def unstack_pipeline_params(pipe_params, vocab_size: int):
    """Inverse of :func:`stack_pipeline_params` (checkpoint interop)."""
    n_stages, vs, d = pipe_params["embed"].shape
    blocks = pipe_params["blocks"]
    any_leaf = jax.tree_util.tree_leaves(blocks)[0]
    per = any_leaf.shape[1]
    head = {
        "kernel": pipe_params["head_k"].transpose(1, 0, 2).reshape(
            d, n_stages * vs)[:, :vocab_size],
    }
    if "head_b" in pipe_params:
        head["bias"] = pipe_params["head_b"].reshape(
            n_stages * vs)[:vocab_size]
    out = {
        "embed": pipe_params["embed"].reshape(n_stages * vs, d)[:vocab_size],
        "pos_embed": pipe_params["pos"],
        "ln_final": pipe_params["ln_f"],
        "head": head,
    }
    for s in range(n_stages):
        for j in range(per):
            out[f"block_{s * per + j}"] = jax.tree.map(
                lambda l: l[s, j], blocks
            )
    return out


def pipeline_specs(pipe_params, pipe_axis: str = PIPE_AXIS):
    """PartitionSpec tree matching :func:`stack_pipeline_params` output."""
    specs = {
        "embed": P(pipe_axis),
        "pos": P(),
        "blocks": jax.tree.map(lambda _: P(pipe_axis),
                               pipe_params["blocks"]),
        "ln_f": jax.tree.map(lambda _: P(), pipe_params["ln_f"]),
        "head_k": P(pipe_axis),
    }
    if "head_b" in pipe_params:
        specs["head_b"] = P(pipe_axis)
    return specs


def create_pipelined_lm_state(model, rng, sample_tokens,
                              optimizer: "Transform",
                              n_stages: int,
                              params=None) -> "TrainState":
    """Init the GPT normally, restack for the pipe axis, init optimizer
    buffers on the stacked tree (so they shard identically). Pass
    ``params`` (a dense GPT param tree, e.g. imported HF-GPT-2 weights
    from :func:`..utils.gpt_interop.from_gpt2_state_dict`) to stack
    those instead of a fresh init."""
    from ..train.state import TrainState

    if getattr(model, "seq_axis", None) is not None:
        model = model.clone(seq_axis=None)
    if params is None:
        params = model.init(rng, sample_tokens, train=False)["params"]
    params = stack_pipeline_params(
        jax.tree.map(jnp.asarray, params), n_stages)
    return TrainState(
        params=params,
        batch_stats={},
        opt_state=optimizer.init(params),
        epoch=jnp.ones((), jnp.int32),
    )


def _shared_parts(model, pipe_axis):
    """Closures shared by every pipelined body (gpipe train, 1f1b
    train, eval) — ONE copy so the execution paths cannot drift
    numerically."""
    from ..models.gpt import Block
    from ..train.lm import _collect_moe_losses
    from .pipeline import _zeros_vma

    # attn_impl="xla": the Pallas flash kernel cannot declare vma for
    # the check_vma=True shard_map these steps REQUIRE (collective AD
    # correctness, see .pipeline); plain masked attention is the same
    # exact math.
    ln_eps = getattr(model, "ln_eps", _LN_EPS)
    is_moe = getattr(model, "n_experts", 0) > 0
    block = Block(model.num_heads, model.mlp_dim, model.dtype,
                  attn_impl="xla", ln_eps=ln_eps,
                  n_experts=getattr(model, "n_experts", 0),
                  moe_top_k=getattr(model, "moe_top_k", 1),
                  moe_capacity_factor=getattr(
                      model, "moe_capacity_factor", 1.0))

    if is_moe:
        def stage_fn(stage_params, x):
            # MoE contract: (y, [aux_sum, z_sum]) — this stage's LAYER
            # SUM of the sown balance/z losses (the bodies normalize to
            # the layer-mean the dense step uses)
            def layer(carry, lp):
                h, acc = carry
                y, mut = block.apply({"params": lp}, h,
                                     mutable=["losses"])
                a, zl = _collect_moe_losses(mut)
                return (y, acc + jnp.stack([a, zl])), None

            acc0 = _zeros_vma((2,), jnp.float32, x)
            (y, acc), _ = jax.lax.scan(layer, (x, acc0), stage_params)
            return y, acc
    else:
        def stage_fn(stage_params, x):
            # stage_params leaves [L/S, ...]: scan this stage's layers
            def layer(carry, lp):
                return block.apply({"params": lp}, carry), None

            y, _ = jax.lax.scan(layer, x, stage_params)
            return y

    def vocab_parallel_embed(emb, pos, tokens, i):
        """Gather the locally-owned rows, psum to materialize [B, S, D]."""
        emb0 = emb[0]  # [Vs, D]
        vs = emb0.shape[0]
        start = i * vs
        idx = tokens - start
        mine = jnp.logical_and(idx >= 0, idx < vs)
        h = emb0[jnp.clip(idx, 0, vs - 1)] * mine[..., None]
        h = jax.lax.psum(h, pipe_axis)
        return (h + pos[: tokens.shape[1]]).astype(model.dtype)

    def final_ln(h, lnf):
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        h = (h - mu) * jax.lax.rsqrt(var + ln_eps)
        return h * lnf["scale"] + lnf["bias"]

    return stage_fn, vocab_parallel_embed, final_ln


def _make_forward_ce(model, axis_name, pipe_axis, m,
                     moe_aux_weight=0.01, moe_z_weight=1e-3):
    """The GPipe forward objective shared by the gpipe train body and
    the eval step: vocab-parallel embed -> pipelined blocks -> final LN
    -> vocab-parallel log-sum-exp CE (the [B, S, V] logits never
    materialize). For MoE models the pipelined stages also accumulate
    the sown balance/z losses (valid ticks only) and the objective adds
    them layer-mean-normalized, mirroring the dense step. Returns
    ``forward_ce(p, tokens) -> (obj, (ce_sum, count, moe_aux))`` with
    ``obj`` normalized for differentiation."""
    from ..train.lm import _next_token_targets
    from .pipeline import pipeline_apply

    stage_fn, vocab_parallel_embed, final_ln = _shared_parts(
        model, pipe_axis
    )
    is_moe = getattr(model, "n_experts", 0) > 0
    n_layers = model.num_layers

    def forward_ce(p, tokens):
        targets, valid = _next_token_targets(tokens, None)
        w = valid.astype(jnp.float32)
        count = jax.lax.psum(jnp.sum(w), axis_name)
        b, s = tokens.shape
        if b % m:
            raise ValueError(
                f"per-replica batch {b} is not divisible by "
                f"n_microbatches={m}"
            )
        i = jax.lax.axis_index(pipe_axis)

        vs = p["embed"].shape[1]
        start = i * vs
        h = vocab_parallel_embed(p["embed"], p["pos"], tokens, i)

        micro = h.reshape(m, b // m, s, h.shape[-1])
        out = pipeline_apply(
            stage_fn, p["blocks"], micro, axis_name=pipe_axis,
            with_stage_aux=is_moe
        )
        if is_moe:
            out, aux_local = out
            # layer-mean x microbatch-mean, matching the dense step's
            # _collect_moe_losses normalization
            aux_vec = jax.lax.psum(aux_local, pipe_axis) / (
                n_layers * m)
        else:
            aux_vec = jnp.zeros((2,), jnp.float32)
        h = out.reshape(b, s, -1).astype(jnp.float32)
        h = final_ln(h, p["ln_f"])

        # ---- vocab-parallel head + log-sum-exp CE: each stage scores
        # its vocab slice; padded slots are masked to -1e9 (zero softmax
        # mass) from the TRUE vocab size — explicit, so it works with or
        # without a head bias (head_bias=False is the HF-GPT-2 interop
        # configuration). The matmul stays f32: the plain GPT head is
        # f32-pinned (models/gpt.py nn.Dense(dtype=f32)) and trajectory
        # parity must hold for bf16 models too.
        logits = h @ p["head_k"][0]
        if "head_b" in p:
            logits = logits + p["head_b"][0]
        slot_valid = start + jnp.arange(vs) < model.vocab_size
        logits = jnp.where(slot_valid, logits, -1e9)
        # stop_gradient BEFORE pmax: the max-shift is numerical
        # stabilization only (lse is shift-invariant) and pmax has
        # no AD rule — its input must already carry a zero tangent
        gmax = jax.lax.pmax(
            jax.lax.stop_gradient(jnp.max(logits, -1)), pipe_axis
        )
        lse = jnp.log(jax.lax.psum(
            jnp.sum(jnp.exp(logits - gmax[..., None]), -1), pipe_axis
        )) + gmax
        tidx = targets - start
        tmine = jnp.logical_and(tidx >= 0, tidx < vs)
        tlogit = jnp.take_along_axis(
            logits, jnp.clip(tidx, 0, vs - 1)[..., None], -1
        )[..., 0] * tmine
        tlogit = jax.lax.psum(tlogit, pipe_axis)
        ce_sum = jnp.sum((lse - tlogit) * w)
        # /dp_world: grads come back data-summed under check_vma AD,
        # so the local aux objective pre-divides (dense-step convention)
        dp_world = jax.lax.psum(1, axis_name)
        obj = ce_sum / count + (
            moe_aux_weight * aux_vec[0] + moe_z_weight * aux_vec[1]
        ) / dp_world
        return obj, (ce_sum, count, aux_vec[0])

    return forward_ce


def _state_specs(state, pipe_axis):
    """ONE source of truth for the pipelined state layout
    (pipeline_specs), mirrored onto the full TrainState pytree."""
    from ..train.optim import OptState
    from ..train.state import TrainState

    ps = pipeline_specs(state.params, pipe_axis)
    return TrainState(
        params=ps,
        batch_stats={},
        opt_state=OptState(momentum=ps, count=P(), initialized=P()),
        epoch=P(),
    )


def make_pipelined_lm_train_step(
    model,
    optimizer: "Transform",
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    n_microbatches: Optional[int] = None,
    schedule: str = "gpipe",
    moe_aux_weight: float = 0.01,
    moe_z_weight: float = 1e-3,
):
    """Build the jitted DP x PP LM train step.

    Args:
      model: a ``GPT`` (provides block geometry and dtype) — dense or
        MoE (``n_experts > 0``: the pipelined stages accumulate the
        sown balance/z losses on valid ticks and both schedules train
        against them with the dense step's layer-mean normalization;
        the reported ``moe_aux`` is a per-microbatch estimator of the
        same statistic, like every sharded batch view).
      mesh: 2-D ``(data, pipe)`` mesh (either axis may be 1).
      n_microbatches: microbatches per step (default: the pipe axis
        size — the minimum that keeps every stage busy; more shrinks
        the bubble fraction further).
      schedule: ``"gpipe"`` (autodiff through the forward schedule —
        simplest, but the reversed scan stashes residuals for all M
        microbatches) or ``"1f1b"`` (:func:`.pipeline.pipeline_1f1b` —
        each microbatch's backward starts as soon as its forward leaves
        the last stage, O(n_stages) activation residency independent of
        M, rematerialized stage backward). Same math either way — the
        trajectory-parity test pins gpipe == 1f1b == plain DP.

    Returns ``step(state, tokens) -> (state, metrics)`` with ``state``
    from :func:`create_pipelined_lm_state`; ``tokens`` is the global
    ``[B, S]`` int array and ``metrics = {loss, count}`` matches
    :func:`..train.lm.make_lm_train_step` (exact mean next-token CE).
    """
    from ..train.lm import _next_token_targets
    from ..train.optim import apply_updates
    from ..train.state import TrainState
    from .pipeline import pipeline_1f1b

    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(
            f"schedule must be 'gpipe' or '1f1b', got {schedule!r}"
        )
    n_stages = int(mesh.shape[pipe_axis])
    dp = int(mesh.shape[axis_name])
    m = n_microbatches or n_stages
    is_moe = getattr(model, "n_experts", 0) > 0
    n_layers = model.num_layers
    stage_fn, vocab_parallel_embed, final_ln = _shared_parts(
        model, pipe_axis
    )
    forward_ce = _make_forward_ce(model, axis_name, pipe_axis, m,
                                  moe_aux_weight, moe_z_weight)

    def _metrics(loss, count, moe_aux):
        out = {"loss": loss, "count": count}
        if is_moe:
            out["moe_aux"] = jax.lax.pmean(moe_aux, axis_name)
        return out

    def body(state: TrainState, tokens):
        (_, (ce_sum, count, moe_aux)), grads = jax.value_and_grad(
            forward_ce, has_aux=True
        )(state.params, tokens)
        # NO explicit grad psums here. Under check_vma=True the vma-aware
        # AD transposes already reduce each cotangent over every mesh
        # axis its parameter is INVARIANT along: pipe-sharded leaves come
        # back data-summed, replicated leaves (pos, ln_f) come back
        # summed over BOTH axes. An explicit psum on top multiplies the
        # gradient by the axis size (verified empirically: 2x/8x updates
        # on a (2, 4) mesh). This is the opposite convention from the
        # check_vma=False steps elsewhere in train/, which must psum
        # their local grads themselves.

        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params, lr_step=state.epoch
        )
        new_state = state.replace(
            params=apply_updates(state.params, updates), opt_state=new_opt
        )
        loss = jax.lax.psum(ce_sum, axis_name) / count
        return new_state, _metrics(loss, count, moe_aux)

    def body_1f1b(state: TrainState, tokens):
        """Manual-VJP twin of ``body`` built on :func:`pipeline_1f1b`.

        Differences from the GPipe body, both standard 1F1B structure:
        the per-microbatch loss must be computable where the last
        stage's output lands, so (a) the head is weight-GATHERED over
        ``pipe`` for the step (Megatron-style: gather the [D, V/S]
        slices, grads return through the all_gather transpose as a
        psum_scatter — params stay vocab-sharded at rest), and (b) the
        final-LN + CE run per-microbatch inside the schedule rather
        than once over the full batch.
        """
        targets, valid = _next_token_targets(tokens, None)
        w = valid.astype(jnp.float32)
        count = jax.lax.psum(jnp.sum(w), axis_name)
        b, s = tokens.shape
        if b % m:
            raise ValueError(
                f"per-replica batch {b} is not divisible by "
                f"n_microbatches={m}"
            )
        i = jax.lax.axis_index(pipe_axis)
        p = state.params
        mb = b // m

        # ---- vocab-parallel embedding, differentiated via vjp so the
        # schedule's input cotangent flows back to the embed rows
        def embed_fn(emb, pos):
            h = vocab_parallel_embed(emb, pos, tokens, i)
            return h.reshape(m, mb, s, h.shape[-1])

        micro, embed_vjp = jax.vjp(embed_fn, p["embed"], p["pos"])

        # ---- gather the vocab-sharded head for the last-stage loss.
        # Padded vocab slots are masked inside mb_loss from the true
        # vocab size — no bias slot needed to carry the mask, so a
        # biasless (head_bias=False, HF-interop) head gathers only its
        # kernel.
        has_bias = "head_b" in p
        head_leaves = (
            (p["head_k"], p["head_b"]) if has_bias else (p["head_k"],)
        )

        def gather_fn(*hs):
            full_k = jax.lax.all_gather(
                hs[0][0], pipe_axis, axis=1, tiled=True
            )  # [D, S*Vs]
            full_b = (jax.lax.all_gather(
                hs[1][0], pipe_axis, axis=0, tiled=True
            ) if has_bias else None)  # [S*Vs]
            return full_k, full_b

        (full_k, full_b), gather_vjp = jax.vjp(gather_fn, *head_leaves)
        loss_params = (full_k, full_b, p["ln_f"])
        aux = (
            targets.reshape(m, mb, s),
            w.reshape(m, mb, s),
        )

        def mb_loss(lp, y, aux_j):
            fk, fb, lnf = lp
            tj, wj = aux_j
            h = final_ln(y.astype(jnp.float32), lnf)
            logits = h @ fk  # [mb, s, Vpad] f32
            if fb is not None:
                logits = logits + fb
            logits = jnp.where(
                jnp.arange(fk.shape[1]) < model.vocab_size, logits, -1e9
            )
            gmax = jax.lax.stop_gradient(jnp.max(logits, -1))
            lse = jnp.log(jnp.sum(
                jnp.exp(logits - gmax[..., None]), -1
            )) + gmax
            tlogit = jnp.take_along_axis(
                logits, tj[..., None], -1
            )[..., 0]
            return jnp.sum((lse - tlogit) * wj) / count

        dp_world = jax.lax.psum(1, axis_name)
        if is_moe:
            # objective adds (w_aux*A + w_z*Z) / (L*M*dp): the constant
            # aux cotangent the schedule seeds on every backward tick
            aux_ct = jnp.asarray(
                [moe_aux_weight, moe_z_weight], jnp.float32
            ) / (n_layers * m * dp_world)
            (loss_local, d_blocks, d_lp, d_micro,
             aux_local) = pipeline_1f1b(
                stage_fn, p["blocks"], micro, mb_loss, loss_params,
                aux, axis_name=pipe_axis, with_stage_aux=True,
                stage_aux_cotangent=aux_ct,
            )
            moe_aux = jax.lax.psum(aux_local, pipe_axis)[0] / (
                n_layers * m)
        else:
            loss_local, d_blocks, d_lp, d_micro = pipeline_1f1b(
                stage_fn, p["blocks"], micro, mb_loss, loss_params,
                aux, axis_name=pipe_axis,
            )
            moe_aux = jnp.zeros((), jnp.float32)
        d_fk, d_fb, d_lnf = d_lp
        # gather_vjp's psum_scatter SUMS the per-shard partials itself —
        # feed them unreduced (a pre-psum would overcount by n_stages)
        d_head = gather_vjp((d_fk, d_fb))
        d_emb, d_pos = embed_vjp(d_micro)
        grads = {
            "embed": d_emb,
            "pos": d_pos,
            "blocks": d_blocks,
            # ln_f is replicated over pipe; its partials need the psum
            "ln_f": jax.tree.map(
                lambda g: jax.lax.psum(g, pipe_axis), d_lnf
            ),
            "head_k": d_head[0],
        }
        if has_bias:
            grads["head_b"] = d_head[1]
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params, lr_step=state.epoch
        )
        new_state = state.replace(
            params=apply_updates(state.params, updates), opt_state=new_opt
        )
        loss = jax.lax.psum(loss_local, axis_name)
        return new_state, _metrics(loss, count, moe_aux)

    def step(state, tokens):
        if state.params["embed"].shape[0] != n_stages:
            raise ValueError(
                f"state was stacked for "
                f"{state.params['embed'].shape[0]} stages but the mesh "
                f"{pipe_axis!r} axis has {n_stages} — create the state "
                f"with n_stages matching the mesh"
            )
        if tokens.shape[0] % (dp * m):
            raise ValueError(
                f"global batch {tokens.shape[0]} must divide by "
                f"data axis x n_microbatches = {dp} x {m}"
            )
        sspec = _state_specs(state, pipe_axis)
        mspec = {"loss": P(), "count": P()}
        if is_moe:
            mspec["moe_aux"] = P()
        sharded = shard_map(
            body_1f1b if schedule == "1f1b" else body,
            mesh=mesh,
            in_specs=(sspec, P(axis_name)),
            out_specs=(sspec, mspec),
        )
        return sharded(state, tokens)

    return jax.jit(step, donate_argnums=(0,))


def make_pipelined_lm_eval_step(
    model,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
    n_microbatches: Optional[int] = None,
):
    """Forward-only pipelined eval: exact mean next-token CE through the
    same GPipe forward (vocab-parallel embed/head, per-stage blocks) as
    the train step — `eval(state, tokens) -> {loss, count}` matching
    :func:`..train.lm.make_lm_eval_step`'s contract. ``state`` is the
    full pipelined TrainState (opt buffers ride along untouched)."""
    n_stages = int(mesh.shape[pipe_axis])
    dp = int(mesh.shape[axis_name])
    m = n_microbatches or n_stages
    forward_ce = _make_forward_ce(model, axis_name, pipe_axis, m)

    def body(state, tokens):
        _, (ce_sum, count, _aux) = forward_ce(state.params, tokens)
        loss = jax.lax.psum(ce_sum, axis_name) / count
        return {"loss": loss, "count": count}

    def step(state, tokens):
        if state.params["embed"].shape[0] != n_stages:
            raise ValueError(
                f"state was stacked for "
                f"{state.params['embed'].shape[0]} stages but the mesh "
                f"{pipe_axis!r} axis has {n_stages} — create the state "
                f"with n_stages matching the mesh"
            )
        if tokens.shape[0] % (dp * m):
            raise ValueError(
                f"global batch {tokens.shape[0]} must divide by "
                f"data axis x n_microbatches = {dp} x {m}"
            )
        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=(_state_specs(state, pipe_axis), P(axis_name)),
            out_specs={"loss": P(), "count": P()},
        )
        return sharded(state, tokens)

    return jax.jit(step)
