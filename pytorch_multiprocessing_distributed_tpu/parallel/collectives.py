"""Collective communication over the device mesh.

The NCCL analogue (SURVEY.md §5 "Distributed communication backend").
Two API levels:

1. **In-context primitives** (``psum``/``pmean``/``all_gather``/
   ``reduce_scatter``/``ppermute``) — used inside a ``shard_map``/``pmap``
   body where a mesh axis is bound. These are thin, typed wrappers over
   ``jax.lax`` collectives; XLA lowers them to ICI all-reduce rings
   (intra-slice) or DCN transfers (cross-slice) depending on where the
   axis lives — there is no hand-written transport layer to get wrong,
   which is the point of the TPU-native design.

2. **Host-level ops** (``all_reduce``, ``reduce_tensor``) — take a mesh
   and an array and run the collective as a standalone jitted program, the
   moral equivalent of calling ``dist.all_reduce`` outside any step
   function. ``reduce_tensor`` is the live, tested version of the
   reference's dead helper (``main.py:173-177``: clone → all_reduce(SUM)
   → /world_size).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..runtime import fleet as graftfleet
from ..runtime import scope as graftscope
from ..utils.compat import shard_map
from .mesh import DATA_AXIS

AxisName = Union[str, Sequence[str]]


# ---------------------------------------------------------------- in-context

def psum(x, axis_name: AxisName = DATA_AXIS):
    """Sum over the mesh axis (DDP's gradient all-reduce, ref main.py:109)."""
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: AxisName = DATA_AXIS):
    """Mean over the mesh axis (all_reduce(SUM)/world_size, ref main.py:173-177)."""
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name: AxisName = DATA_AXIS, *, axis: int = 0,
               tiled: bool = False):
    """Gather shards from every member of the axis."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: AxisName = DATA_AXIS, *, scatter_axis: int = 0,
                   tiled: bool = True):
    """Sum-reduce then scatter shards along ``scatter_axis``."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                                tiled=tiled)


def ppermute(x, perm, axis_name: AxisName = DATA_AXIS):
    """Point-to-point ring permutation (building block of ring attention)."""
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: AxisName = DATA_AXIS):
    """This shard's coordinate along the axis (the reference's ``rank``)."""
    return jax.lax.axis_index(axis_name)


# ---------------------------------------------------------------- host-level

_REDUCERS = {
    "sum": jax.lax.psum,
    "mean": jax.lax.pmean,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


@partial(jax.jit, static_argnums=(1, 2, 3))
def _all_reduce_program(x, mesh: Mesh, axis_name: str, op: str):
    def body(v):  # v: [1, ...] — this member's value
        return _REDUCERS[op](v[0], axis_name)

    shard = shard_map(
        body, mesh=mesh, in_specs=P(axis_name), out_specs=P(), check_vma=False
    )
    return shard(x)


def all_reduce(x, mesh: Mesh, axis_name: str = DATA_AXIS, op: str = "sum"):
    """Standalone all-reduce of stacked per-member values over a mesh axis.

    ``x`` has shape ``[axis_size, ...]`` — element ``i`` is member ``i``'s
    value, mirroring "each rank holds its own tensor" in
    ``dist.all_reduce``. Returns the reduced ``[...]`` value (replicated).
    ``op``: ``sum`` | ``mean`` | ``max`` | ``min``.

    The compiled program is cached (jit with static mesh/axis/op), so
    per-iteration calls don't re-trace.
    """
    if op not in _REDUCERS:
        raise ValueError(f"unknown reduce op {op!r}; one of {sorted(_REDUCERS)}")
    x = jnp.asarray(x)
    if x.shape[0] != mesh.shape[axis_name]:
        raise ValueError(
            f"leading dim {x.shape[0]} != size of mesh axis "
            f"{axis_name!r} ({mesh.shape[axis_name]})"
        )
    # graftfleet: stamp this rank's arrival at the boundary with the
    # STATIC per-member payload bytes — host metadata (.nbytes), never
    # a device read (it matches the psum bytes the graftcheck budget
    # commits for this program). The emitted event is an INSTANT, not
    # a span: the jitted call below is dispatch-only, and timing it
    # here would be exactly the async-dispatch lie GL115 flags.
    per_member_bytes = int(x.nbytes // x.shape[0]) if x.shape[0] else 0
    graftfleet.note_arrival(f"all_reduce@{axis_name}", axis=axis_name,
                            nbytes=per_member_bytes)
    graftscope.emit("collective.all_reduce", cat="collective",
                    axis=axis_name, op=op, nbytes=per_member_bytes)
    return _all_reduce_program(x, mesh, axis_name, op)


def reduce_tensor(tensor, mesh: Mesh, axis_name: str = DATA_AXIS):
    """all_reduce(SUM) / world_size — the reference's ``reduce_tensor``.

    In the reference this helper exists but is never called (``main.py:
    173-177``), which is why its reported eval accuracy is divided by
    world_size. Here it is live and tested — the canonical way to average
    stacked per-member metrics outside a step (the trainer itself reduces
    metrics in-step via ``psum``, which is cheaper).
    """
    return all_reduce(tensor, mesh, axis_name, op="mean")


# ------------------------------------------------------------- graftcheck

def audit_programs():
    """graftcheck registration hook (``analysis/programs.py``): the
    host-level ``all_reduce`` program — the simplest budget in the
    registry, pinned inline to exactly one payload-sized ``psum``. If
    this ever reads 2, someone double-reduced the moral equivalent of
    ``dist.all_reduce``."""
    def build():
        import jax.numpy as jnp

        from .mesh import audit_mesh

        mesh = audit_mesh(data=4, model=2)
        stacked = jax.ShapeDtypeStruct((4, 16), jnp.float32)

        def fn(x):
            return _all_reduce_program(x, mesh, DATA_AXIS, "sum")

        return {
            "fn": fn,
            "args": (stacked,),
            # one psum of the per-member [16] f32 payload = 64 bytes
            "expect_collectives": {
                "psum@data": {"count": 1, "bytes": 64}},
        }

    return [{"name": "collectives_all_reduce", "min_devices": 8,
             "build": build}]
