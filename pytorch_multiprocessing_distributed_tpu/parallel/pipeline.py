"""Pipeline parallelism: GPipe-style microbatched execution over a mesh
axis, expressed as a shard_map collective pipeline.

The reference is single-stage (SURVEY.md §2.3 marks PP absent); this is
the framework's PP primitive. Stage s of a homogeneous S-stage network
lives on mesh shard s of the ``pipe`` axis. Microbatches enter stage 0,
activations hop to the next stage each tick via ``lax.ppermute`` (ICI
neighbor exchange, overlapped with the current tick's compute by XLA),
and after ``M + S - 1`` ticks every microbatch has flowed through every
stage — the classic GPipe schedule with its (S-1)-tick bubble.

Differentiable by construction: the schedule is a ``lax.scan`` over
ticks and autodiff reverses it (backward microbatches flow the ring the
other way), so ``jax.grad`` of a loss on the pipeline output yields
per-stage parameter gradients on the shard that owns the stage — a
pipelined training step with no hand-written backward schedule.

Use INSIDE ``shard_map`` with the stage-stacked params sharded over the
pipe axis (leading dim S -> per-shard 1, see tests):

    jax.shard_map(
        lambda p, x: pipeline_apply(stage_fn, p, x, axis_name="pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P(),
    )

Keep ``check_vma`` at its default (True): the replication checker is
what makes the AD transpose of the final ``psum`` correct — under
``check_vma=False`` gradients through the pipeline silently come back
scaled by the number of stages.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    *,
    axis_name: str,
):
    """Run the S-stage pipeline on ``M`` microbatches.

    Args:
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``
        (homogeneous stages — the standard PP regime).
      stage_params: THIS shard's stage parameters (pytree; leaves carry
        a leading stage dim of 1 from the ``P(axis_name)`` in_spec,
        squeezed here).
      microbatches: ``[M, mb, ...]`` replicated input microbatches.
      axis_name: the bound pipe mesh axis.

    Returns:
      ``[M, mb, ...]`` pipeline outputs, replicated across the axis.
    """
    n = jax.lax.psum(1, axis_name)  # static python int under shard_map
    i = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params = jax.tree.map(lambda l: jnp.squeeze(l, axis=0), stage_params)
    perm = [(j, (j + 1) % n) for j in range(n)]
    # Run under check_vma=True (shard_map's default): correct psum/
    # ppermute AD transposes REQUIRE the replication checker — with
    # check_vma=False the transpose of the final psum over-counts
    # gradients by the axis size. Mark the device-varying values
    # explicitly so the checker accepts the scan carries.
    def vary(x):
        if axis_name in getattr(jax.typeof(x), "vma", frozenset()):
            return x  # caller already passed a varying value
        return jax.lax.pcast(x, axis_name, to="varying")

    microbatches = vary(microbatches)

    def tick(carry, t):
        act, out = carry
        # stage 0 injects microbatch t (clipped reads feed the bubble
        # ticks; their results are masked out of `out` below)
        inj = microbatches[jnp.clip(t, 0, m - 1)]
        x = jnp.where(i == 0, inj, act)
        y = stage_fn(params, x)
        # the last stage banks finished microbatch t - (n - 1)
        slot = t - (n - 1)
        valid = jnp.logical_and(
            i == n - 1, jnp.logical_and(slot >= 0, slot < m)
        )
        sc = jnp.clip(slot, 0, m - 1)
        out = out.at[sc].set(jnp.where(valid, y, out[sc]))
        # rotate activations one stage forward around the ring
        act = jax.lax.ppermute(y, axis_name, perm)
        return (act, out), None

    act0 = jnp.zeros_like(microbatches[0])  # inherits varying-ness
    out0 = jnp.zeros_like(microbatches)
    (act, out), _ = jax.lax.scan(
        tick, (act0, out0), jnp.arange(m + n - 1)
    )
    # `out` is populated only on the last shard; replicate it
    mask = (i == n - 1).astype(out.dtype)
    return jax.lax.psum(out * mask, axis_name)
