"""Pipeline parallelism: GPipe-style microbatched execution over a mesh
axis, expressed as a shard_map collective pipeline.

The reference is single-stage (SURVEY.md §2.3 marks PP absent); this is
the framework's PP primitive. Stage s of a homogeneous S-stage network
lives on mesh shard s of the ``pipe`` axis. Microbatches enter stage 0,
activations hop to the next stage each tick via ``lax.ppermute`` (ICI
neighbor exchange, overlapped with the current tick's compute by XLA),
and after ``M + S - 1`` ticks every microbatch has flowed through every
stage — the classic GPipe schedule with its (S-1)-tick bubble.

Differentiable by construction: the schedule is a ``lax.scan`` over
ticks and autodiff reverses it (backward microbatches flow the ring the
other way), so ``jax.grad`` of a loss on the pipeline output yields
per-stage parameter gradients on the shard that owns the stage — a
pipelined training step with no hand-written backward schedule.

Use INSIDE ``shard_map`` with the stage-stacked params sharded over the
pipe axis (leading dim S -> per-shard 1, see tests):

    jax.shard_map(
        lambda p, x: pipeline_apply(stage_fn, p, x, axis_name="pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P(),
    )

Keep ``check_vma`` at its default (True): the replication checker is
what makes the AD transpose of the final ``psum`` correct — under
``check_vma=False`` gradients through the pipeline silently come back
scaled by the number of stages.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..utils.compat import pcast, typeof


def _vary(x, axis_name):
    """Mark ``x`` device-varying over ``axis_name`` if it isn't already
    (check_vma bookkeeping for values entering the per-shard schedule)."""
    if axis_name in getattr(typeof(x), "vma", frozenset()):
        return x
    return pcast(x, axis_name, to="varying")


def _match_vma(x, vma_of):
    """Widen ``x``'s device-varying axes to ``vma_of``'s (cotangents
    must carry the exact vma of the output they seed)."""
    want = getattr(typeof(vma_of), "vma", frozenset())
    have = getattr(typeof(x), "vma", frozenset())
    for ax in want - have:
        x = pcast(x, ax, to="varying")
    return x


def _zeros_vma(shape, dtype, vma_of):
    """Zeros carrying ``vma_of``'s device-varying axes — fresh constants
    are replication-invariant, which would make a scan carry's vma
    narrower than the values written into it (jax.vjp then rejects the
    cotangents as type-mismatched)."""
    return _match_vma(jnp.zeros(shape, dtype), vma_of)


def _zeros_like_tree_vma(tree):
    return jax.tree.map(
        lambda l: _zeros_vma(jnp.shape(l), jnp.result_type(l), l), tree
    )


def _stage_aux_zeros(stage_fn, params, x, vma_of):
    """Zero accumulator matching ``stage_fn``'s aux output structure
    (shared by both schedules so their aux bookkeeping cannot drift)."""
    aux_shapes = jax.eval_shape(lambda p, xx: stage_fn(p, xx)[1],
                                params, x)
    return jax.tree.map(
        lambda s: _zeros_vma(s.shape, s.dtype, vma_of), aux_shapes)


def _masked_aux_add(acc, aux_t, valid):
    """Accumulate a stage-aux pytree for VALID (non-bubble) ticks only."""
    return jax.tree.map(
        lambda a, g: a + jnp.where(valid, g, 0.0), acc, aux_t)


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    *,
    axis_name: str,
    with_stage_aux: bool = False,
):
    """Run the S-stage pipeline on ``M`` microbatches.

    Args:
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``
        (homogeneous stages — the standard PP regime). With
        ``with_stage_aux=True`` the contract is ``stage_fn(params, x) ->
        (y, aux)`` where ``aux`` is a pytree of per-invocation scalars
        (e.g. MoE balance losses).
      stage_params: THIS shard's stage parameters (pytree; leaves carry
        a leading stage dim of 1 from the ``P(axis_name)`` in_spec,
        squeezed here).
      microbatches: ``[M, mb, ...]`` replicated input microbatches.
      axis_name: the bound pipe mesh axis.
      with_stage_aux: accumulate the aux outputs of VALID (non-bubble) stage
        invocations. The schedule is a plain scan, so differentiating
        the caller's objective through the accumulated aux flows
        gradients into routing params (and upstream activations)
        automatically.

    Returns:
      ``[M, mb, ...]`` pipeline outputs, replicated across the axis.
      With ``with_stage_aux``: ``(outputs, aux_sum)`` where ``aux_sum`` is
      THIS shard's sum over its valid invocations (device-varying —
      ``psum`` over the axis for the global sum).
    """
    n = jax.lax.psum(1, axis_name)  # static python int under shard_map
    i = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    params = jax.tree.map(lambda l: jnp.squeeze(l, axis=0), stage_params)
    perm = [(j, (j + 1) % n) for j in range(n)]
    # Run under check_vma=True (shard_map's default): correct psum/
    # ppermute AD transposes REQUIRE the replication checker — with
    # check_vma=False the transpose of the final psum over-counts
    # gradients by the axis size. Mark the device-varying values
    # explicitly so the checker accepts the scan carries.
    microbatches = _vary(microbatches, axis_name)

    def tick(carry, t):
        act, out, aux_acc = carry
        # stage 0 injects microbatch t (clipped reads feed the bubble
        # ticks; their results are masked out of `out` below)
        inj = microbatches[jnp.clip(t, 0, m - 1)]
        x = jnp.where(i == 0, inj, act)
        if with_stage_aux:
            y, aux_t = stage_fn(params, x)
            # this stage computes microbatch t - i at tick t; bubble
            # ticks process clipped garbage whose aux must not count
            f_valid = jnp.logical_and(t - i >= 0, t - i < m)
            aux_acc = _masked_aux_add(aux_acc, aux_t, f_valid)
        else:
            y = stage_fn(params, x)
        # the last stage banks finished microbatch t - (n - 1)
        slot = t - (n - 1)
        valid = jnp.logical_and(
            i == n - 1, jnp.logical_and(slot >= 0, slot < m)
        )
        sc = jnp.clip(slot, 0, m - 1)
        out = out.at[sc].set(jnp.where(valid, y, out[sc]))
        # rotate activations one stage forward around the ring
        act = jax.lax.ppermute(y, axis_name, perm)
        return (act, out, aux_acc), None

    act0 = jnp.zeros_like(microbatches[0])  # inherits varying-ness
    out0 = jnp.zeros_like(microbatches)
    if with_stage_aux:
        aux0 = _stage_aux_zeros(stage_fn, params, microbatches[0],
                                microbatches)
    else:
        aux0 = ()
    (act, out, aux_acc), _ = jax.lax.scan(
        tick, (act0, out0, aux0), jnp.arange(m + n - 1)
    )
    # `out` is populated only on the last shard; replicate it
    mask = (i == n - 1).astype(out.dtype)
    out = jax.lax.psum(out * mask, axis_name)
    return (out, aux_acc) if with_stage_aux else out


def pipeline_1f1b(
    stage_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    loss_fn: Callable,
    loss_params,
    aux,
    *,
    axis_name: str,
    with_stage_aux: bool = False,
    stage_aux_cotangent=None,
):
    """1F1B pipelined training pass: loss + grads in one schedule.

    :func:`pipeline_apply` + autodiff is GPipe: ALL forwards run before
    any backward, so the reversed scan stashes per-tick residuals for
    every one of the ``M`` microbatches — activation memory grows with
    ``M``, which defeats the point of microbatching. 1F1B starts each
    microbatch's backward as soon as its forward leaves the last stage;
    at any instant a stage holds at most ``2S - 1`` stage-INPUTS (a
    rolling buffer, independent of ``M``) and rematerializes the stage
    forward inside the backward tick (the classic remat trade: one extra
    stage-forward per backward buys O(S) instead of O(M) residency).

    Schedule (tick ``t``, stage ``s`` of ``S``, microbatch ``j``):
    forward of ``j`` runs at ``t = j + s``; the last stage computes the
    per-microbatch loss and its output cotangent immediately; backward
    of ``j`` runs at ``t = j + 2S - 1 - s``. Every steady-state tick is
    exactly one-forward-one-backward per stage. Activations hop +1 on
    the ``ppermute`` ring, cotangents hop -1, both overlapped with
    compute by XLA. Total ``M + 2S - 1`` ticks.

    Args:
      stage_fn: ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``
        (pure local compute — no collectives; it runs under ``jax.vjp``
        inside the schedule).
      stage_params: THIS shard's stage parameters (leaves carry the
        leading stage dim of 1 from a ``P(axis_name)`` in_spec).
      microbatches: ``[M, mb, ...]`` input microbatches.
      loss_fn: ``loss_fn(loss_params, y, aux_j) -> scalar`` per-
        microbatch loss, evaluated where the LAST stage's output lands.
        Local ops only — it executes on every stage every tick (masked),
        so a collective inside it would change meaning.
      loss_params: parameters of the loss head (e.g. final-LN / head
        weights). Grads come back as per-shard PARTIAL sums (nonzero
        only where the last stage contributed): ``psum`` them for
        replicated params, or feed them raw to the transpose of the
        collective that built them (e.g. an ``all_gather``'s vjp).
      aux: pytree of ``[M, ...]`` per-microbatch loss inputs (targets,
        weights); no gradients flow to it.
      axis_name: the bound pipe mesh axis.
      with_stage_aux: ``stage_fn(params, x) -> (y, stage_aux)`` where
        ``stage_aux`` is a pytree of scalars (e.g. MoE balance losses).
        The schedule then optimizes ``sum_j loss_j + <stage_aux_cotangent,
        sum_valid stage_aux>``: on each backward tick the aux
        cotangent is seeded alongside the activation cotangent, so its
        gradient reaches this stage's params AND flows upstream
        through the cotangent ring (routing depends on the stage
        input).
      stage_aux_cotangent: pytree matching ``stage_aux`` — the constant
        d(objective)/d(stage_aux) weights (required iff ``with_stage_aux``).

    Returns:
      ``(loss_sum, dstage_params, dloss_params, dmicrobatches)``:
      summed loss over microbatches (replicated over the axis), grads
      for this shard's stage params (same leading-1 shape), UNREDUCED
      per-shard loss-param grads (see above), and the ``[M, mb, ...]``
      input cotangent (replicated over the axis). With ``with_stage_aux`` a
      fifth element: THIS shard's valid-invocation aux sum
      (device-varying — ``psum`` over the axis for the global sum).
    """
    if with_stage_aux and stage_aux_cotangent is None:
        raise ValueError("with_stage_aux=True requires stage_aux_cotangent")
    n = jax.lax.psum(1, axis_name)  # static python int under shard_map
    i = jax.lax.axis_index(axis_name)
    m = microbatches.shape[0]
    buf = 2 * n - 1  # max in-flight stage-inputs (stage 0's lifetime)
    params = jax.tree.map(lambda l: jnp.squeeze(l, axis=0), stage_params)
    perm_fwd = [(j, (j + 1) % n) for j in range(n)]
    perm_bwd = [(j, (j - 1) % n) for j in range(n)]

    microbatches = _vary(microbatches, axis_name)
    aux = jax.tree.map(lambda l: _vary(l, axis_name), aux)
    loss_params = jax.tree.map(lambda l: _vary(l, axis_name), loss_params)
    if with_stage_aux:
        # the stage-aux outputs inherit the microbatches' full vma (the
        # activations they are computed from); the constant cotangent
        # seeded into their vjp must carry the same
        stage_aux_cotangent = jax.tree.map(
            lambda l: _match_vma(l, microbatches), stage_aux_cotangent)

    def masked_add(acc, g, mask):
        return jax.tree.map(
            lambda a, gg: a + gg * mask.astype(gg.dtype), acc, g
        )

    def tick(carry, t):
        (act_in, cot_in, resid, dy_buf, dps, dlps, dmb, loss_acc,
         aux_acc) = carry

        # ---- forward: microbatch j_f = t - i flows through this stage
        j_f = t - i
        f_valid = jnp.logical_and(j_f >= 0, j_f < m)
        inj = microbatches[jnp.clip(t, 0, m - 1)]
        x_in = jnp.where(i == 0, inj, act_in)
        if with_stage_aux:
            y, aux_t = stage_fn(params, x_in)
            aux_acc = _masked_aux_add(aux_acc, aux_t, f_valid)
        else:
            y = stage_fn(params, x_in)

        # last stage: per-microbatch loss + output cotangent for j_f,
        # banked one tick (its backward runs at t + 1)
        aux_j = jax.tree.map(lambda l: l[jnp.clip(j_f, 0, m - 1)], aux)
        loss_j, loss_vjp = jax.vjp(
            lambda lp, yy: loss_fn(lp, yy, aux_j), loss_params, y
        )
        dlp_j, dy_j = loss_vjp(jnp.ones_like(loss_j))
        l_valid = jnp.logical_and(f_valid, i == n - 1)
        loss_acc = loss_acc + jnp.where(l_valid, loss_j, 0.0)
        dlps = masked_add(dlps, dlp_j, l_valid)
        new_dy = jnp.where(l_valid, dy_j, jnp.zeros_like(dy_j))

        # ---- backward: microbatch j_b = t - (2S-1) + i, rematerialized
        # from the stored stage input. Residual READ happens before the
        # forward WRITE below: at stage 0 the two share a slot on the
        # very tick j_b's storage is retired (j_f - j_b == buf).
        j_b = t - (2 * n - 1) + i
        b_valid = jnp.logical_and(j_b >= 0, j_b < m)
        x_saved = resid[jnp.mod(j_b, buf)]
        g_in = jnp.where(i == n - 1, dy_buf, cot_in)
        _, stage_vjp = jax.vjp(stage_fn, params, x_saved)
        if with_stage_aux:
            # seed the constant aux cotangent with the activation one:
            # the vjp routes it into this stage's params (dp_j) and
            # upstream through dx_j. Invalid-tick contributions follow
            # the same masking as everything else (dp masked here, dx
            # masked at the j_b chain's accumulation points).
            dp_j, dx_j = stage_vjp((g_in, stage_aux_cotangent))
        else:
            dp_j, dx_j = stage_vjp(g_in)
        dps = masked_add(dps, dp_j, b_valid)
        sb = jnp.clip(j_b, 0, m - 1)
        take = jnp.logical_and(b_valid, i == 0)
        dmb = dmb.at[sb].set(jnp.where(take, dx_j, dmb[sb]))

        # now bank this tick's forward input
        sf = jnp.mod(j_f, buf)
        resid = resid.at[sf].set(jnp.where(f_valid, x_in, resid[sf]))

        act_out = jax.lax.ppermute(y, axis_name, perm_fwd)
        cot_out = jax.lax.ppermute(dx_j, axis_name, perm_bwd)
        return (
            act_out, cot_out, resid, new_dy, dps, dlps, dmb, loss_acc,
            aux_acc
        ), None

    mb0 = microbatches[0]
    z = _zeros_vma(mb0.shape, mb0.dtype, mb0)
    if with_stage_aux:
        aux0 = _stage_aux_zeros(stage_fn, params, mb0, mb0)
    else:
        aux0 = ()
    carry0 = (
        z,                                                # fwd ring
        z,                                                # bwd ring
        _zeros_vma((buf,) + z.shape, z.dtype, mb0),       # input residuals
        z,                                        # banked loss cotangent
        _zeros_like_tree_vma(params),             # stage-param grads
        _zeros_like_tree_vma(loss_params),
        _zeros_vma(microbatches.shape, microbatches.dtype, mb0),
        _zeros_vma((), jnp.float32, mb0),         # loss accumulator
        aux0,                                     # stage-aux accumulator
    )
    (_, _, _, _, dps, dlps, dmb, loss_acc, aux_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(m + 2 * n - 1)
    )

    loss_sum = jax.lax.psum(loss_acc, axis_name)  # last stage holds it
    dmb = jax.lax.psum(dmb, axis_name)            # stage 0 holds it
    dstage = jax.tree.map(lambda g: jnp.expand_dims(g, 0), dps)
    if with_stage_aux:
        return loss_sum, dstage, dlps, dmb, aux_acc
    return loss_sum, dstage, dlps, dmb
