"""Ulysses-style sequence parallelism: all-to-all head re-partition.

The second of the two canonical long-context strategies (the first,
K/V-rotation ring attention, is ``ring_attention.py``). Where the ring
keeps sequence shards resident and pays ``axis_size - 1`` neighbor hops
of K/V, Ulysses (DeepSpeed-Ulysses, arXiv:2309.14509 — public method,
re-implemented here from the idea) pays exactly TWO all-to-alls per
attention call:

1. inputs arrive ``[b, s_local, h, d]`` (sequence sharded); an
   all-to-all re-partitions to ``[b, s_global, h_local, d]`` — each
   device now owns a subset of HEADS over the FULL sequence;
2. attention runs entirely locally (the Pallas flash kernel, causal or
   not — no per-hop masking cases, no ring imbalance);
3. a second all-to-all restores ``[b, s_local, h, d]``.

Trade-offs vs the ring: all-to-all moves the same O(s*h*d) bytes but as
one dense exchange (XLA lowers to ICI all-to-all) instead of a pipeline
of neighbor hops, and the causal-work imbalance of the contiguous ring
disappears (every device computes the same full-sequence triangle over
its heads). The constraint is ``heads % axis_size == 0``; the ring has
no such requirement. Exact-parity with dense attention and with the
ring is test-pinned.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.pallas.flash_attention import flash_attention


def _check_heads(h: int, axis_size: int) -> None:
    if h % axis_size:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"sequence-axis size ({axis_size}); use ring_attention for "
            "head counts that do not divide"
        )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Exact attention with the sequence dim sharded over ``axis_name``.

    Args:
      q, k, v: per-shard ``[batch, seq_local, heads, head_dim]``; the
        global sequence is sharded contiguously over ``axis_name``
        (same layout contract as :func:`.ring_attention`).
      axis_name: bound mesh axis (inside ``shard_map``).
      causal: causal masking over GLOBAL positions (exact — each device
        sees the full sequence for its heads).

    Returns:
      ``[batch, seq_local, heads, head_dim]`` — this shard's slice of
      the full-attention output, differentiable (all_to_all transposes
      to all_to_all under autodiff; the flash kernel carries its own
      custom VJP).
    """
    axis_size = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    _check_heads(h, axis_size)

    def seq_to_heads(x):
        # [3, b, s_local, h, d] -> [3, b, s_global, h_local, d]; q/k/v
        # travel STACKED so the exchange is ONE collective, not three
        # (same trick as the ring's tupled ppermute)
        return jax.lax.all_to_all(
            x, axis_name, split_axis=3, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        # [b, s_global, h_local, d] -> [b, s_local, h, d]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = seq_to_heads(jnp.stack((q, k, v)))
    out = flash_attention(
        qh, kh, vh, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return heads_to_seq(out)
