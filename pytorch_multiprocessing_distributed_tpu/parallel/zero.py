"""graftzero: cross-replica sharded weight update (ZeRO-1) with
bucketed, overlapped gradient communication.

The DP train steps' reference semantics move gradients as ONE
grad-sized ``psum`` and then run a fully replicated optimizer update on
every rank: optimizer moments are N-way redundant in HBM and the
all-reduce serializes behind the backward pass. arXiv:2004.13336
(PAPERS.md) replaces that schedule with

    reduce-scatter(grads) -> sharded optimizer update -> all-gather

so each DP rank stores and updates only ``1/N`` of every moment buffer
and the two collectives move the same total bytes as the one all-reduce
(ring cost: ``2 (N-1)/N P`` either way) — the freed ``(N-1)/N`` of the
optimizer state is what ``plan_capacity(zero_shards=N)`` re-spends.

Mechanics (all under ``shard_map``, the explicit-collective DP path):

- the grad tree is flattened into **dtype-homogeneous flat buckets**
  (:func:`plan_buckets`): shard boundaries land in flat index space, so
  they never have to split a leaf across ragged shapes, and elementwise
  optimizer math runs on bare 1-D shards;
- each bucket is ``lax.psum_scatter``-ed along the DP axis as its own
  collective, chained bucket-to-bucket through
  ``lax.optimization_barrier`` — a pure dependency chain that fixes the
  ISSUE order (bucket 0's scatter can start while later buckets' grads
  are still being computed) without adding ops;
- the optimizer update runs on the local shard only. BOTH shipped
  transforms (:func:`..train.optim.sgd`, :func:`..train.lamb.lamb`)
  provide the ``Transform.shard_update`` / ``Transform.shard_finish``
  split: the elementwise phase runs on the flat shards, the update
  direction is all-gathered, and the finish phase (LR scale; LAMB's
  per-leaf trust ratio) is applied on full leaves with the exact
  replicated math — bit-identical to the replicated baseline by
  construction. A custom transform without the seam falls back to its
  unmodified ``update`` on the shard pytrees, which is only correct
  (and only bitwise-stable) if that update is purely elementwise —
  the seam is the supported path;
- updated params are all-gathered back (per bucket, same chaining), so
  params stay replicated (the ZeRO-1 point: moments shard, params
  don't) and donation still aliases the full state.

Optimizer moments are allocated sharded FROM STEP ONE: a
:class:`ZeroOptState` holds per-bucket flat arrays of GLOBAL shape
``[padded]`` placed ``P(data)`` on the mesh — each rank materializes
only its ``padded/N`` slice, and the replicated tree never exists.
Checkpoints stay portable: ``save_checkpoint`` gathers a
:class:`ZeroOptState` back to the inner (replicated-format) state, so
``--resume auto`` round-trips between ``--zero`` and plain runs.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS

# Default bucket granularity. Big enough that the tiny audit/test
# models land in ONE bucket per dtype (the committed budget's "exactly
# one reduce-scatter + one all-gather"); small enough that real models
# split into several buckets whose scatters overlap the backward.
DEFAULT_BUCKET_MB = 32.0


@dataclass(frozen=True)
class Bucket:
    """One dtype-homogeneous flat bucket: which param-tree leaves it
    holds (indices into the flattened leaf list), where each starts in
    flat space, and the pad/shard geometry over ``num_shards``."""

    dtype: str
    leaf_idx: Tuple[int, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    padded: int
    shard: int


@dataclass(frozen=True)
class ZeroPlan:
    """The static bucket layout for one (param tree, num_shards) pair.

    Hashable/frozen by construction: it rides the jit cache key as a
    ``ZeroOptState`` static field, and two states built from the same
    params + shard count compare equal. ``leaf_shapes``/``leaf_dtypes``
    record the flattened param-leaf geometry so gather-on-save can
    unflatten without the original tree."""

    num_shards: int
    buckets: Tuple[Bucket, ...]
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_dtypes: Tuple[str, ...]

    @property
    def padded_bytes(self) -> int:
        """Total flat bytes across buckets (incl. padding) — the
        reduce-scatter operand volume per step."""
        return sum(b.padded * jnp.dtype(b.dtype).itemsize
                   for b in self.buckets)

    @property
    def shard_bytes(self) -> int:
        """Per-rank flat bytes across buckets — what ONE moment buffer
        costs per chip under zero (= padded_bytes / num_shards), and
        the all-gather operand volume per step."""
        return sum(b.shard * jnp.dtype(b.dtype).itemsize
                   for b in self.buckets)


def plan_buckets(params, num_shards: int, *,
                 bucket_bytes: Optional[int] = None) -> ZeroPlan:
    """Lay the param tree's leaves into dtype-homogeneous flat buckets.

    Leaves keep tree-flattening order within their dtype group; a group
    splits into multiple buckets once it exceeds ``bucket_bytes`` (a
    single oversized leaf gets its own bucket — leaves are never split
    ACROSS buckets; shard boundaries inside one bucket land in flat
    index space instead). Every bucket pads to a multiple of
    ``num_shards`` so ``psum_scatter`` tiles evenly.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if bucket_bytes is None:
        bucket_bytes = int(DEFAULT_BUCKET_MB * 2 ** 20)
    leaves = jax.tree.leaves(params)
    if not leaves:
        raise ValueError("plan_buckets: empty parameter tree")
    by_dtype: Dict[str, List[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(str(jnp.dtype(leaf.dtype)), []).append(i)

    buckets: List[Bucket] = []
    for dtype, idxs in by_dtype.items():
        itemsize = jnp.dtype(dtype).itemsize
        cur: List[int] = []
        cur_bytes = 0
        groups: List[List[int]] = []
        for i in idxs:
            n = int(math.prod(leaves[i].shape)) * itemsize
            if cur and cur_bytes + n > bucket_bytes:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += n
        if cur:
            groups.append(cur)
        for group in groups:
            sizes = tuple(int(math.prod(leaves[i].shape))
                          for i in group)
            offsets, off = [], 0
            for s in sizes:
                offsets.append(off)
                off += s
            total = off
            padded = -(-total // num_shards) * num_shards
            buckets.append(Bucket(
                dtype=dtype, leaf_idx=tuple(group), sizes=sizes,
                offsets=tuple(offsets), total=total, padded=padded,
                shard=padded // num_shards))
    covered = sorted(i for b in buckets for i in b.leaf_idx)
    assert covered == list(range(len(leaves)))
    return ZeroPlan(
        num_shards=num_shards,
        buckets=tuple(buckets),
        leaf_shapes=tuple(tuple(int(d) for d in leaf.shape)
                          for leaf in leaves),
        leaf_dtypes=tuple(str(jnp.dtype(leaf.dtype))
                          for leaf in leaves),
    )


def static_comm_bytes(plan: ZeroPlan) -> Dict[str, int]:
    """Per-step collective byte volumes as the committed jaxpr budget
    counts them (operand avals): the reduce-scatter sees the full
    padded bucket, the all-gather sees the per-rank shard. These are
    the static bytes the ``train.grad_comm`` events carry — the same
    discipline as ``fleet.static_collective_bytes``."""
    return {"reduce_scatter": plan.padded_bytes,
            "all_gather": plan.shard_bytes}


# ------------------------------------------------- flat (un)bucketing

def _flatten_bucket(leaves: Sequence[jax.Array], bucket: Bucket):
    """Concat the bucket's leaves (tree order) into one flat
    ``[padded]`` array; padding is zeros (sum-neutral under the
    scatter, sliced off at unflatten)."""
    parts = [leaves[i].reshape(-1) for i in bucket.leaf_idx]
    if bucket.padded > bucket.total:
        parts.append(jnp.zeros((bucket.padded - bucket.total,),
                               jnp.dtype(bucket.dtype)))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def _unflatten_buckets(flats: Sequence[jax.Array], plan: ZeroPlan,
                       like_tree):
    """Inverse of per-bucket flattening: flat ``[padded]`` arrays back
    to a tree shaped like ``like_tree``."""
    n_leaves = len(plan.leaf_shapes)
    leaves: List[Any] = [None] * n_leaves
    for flat, bucket in zip(flats, plan.buckets):
        for i, off, size in zip(bucket.leaf_idx, bucket.offsets,
                                bucket.sizes):
            leaves[i] = flat[off:off + size].reshape(
                plan.leaf_shapes[i])
    return jax.tree.unflatten(jax.tree.structure(like_tree), leaves)


def _chained(x, chain, overlap: bool):
    """Thread the bucket-order dependency chain: ``x`` gains a data
    dependency on the previous bucket's collective result, so the
    scheduler issues collectives in bucket order (early scatters
    overlap late buckets' computation) without materializing anything
    — ``optimization_barrier`` is the identity."""
    if chain is None or not overlap:
        return x
    return jax.lax.optimization_barrier((x, chain))[0]


def reduce_scatter_grads(grads, plan: ZeroPlan, axis_name: str, *,
                         mean: bool, overlap: bool = True):
    """Bucketed reduce-scatter of a local grad tree along ``axis_name``.

    Returns one ``[shard]`` array per bucket: this rank's slice of the
    cross-replica SUM (``mean=True`` divides by the axis size — the
    ``pmean`` twin). ``overlap=False`` joins every grad leaf before the
    first scatter (the serialized schedule — the bench's baseline for
    the overlap-fraction measurement)."""
    leaves = jax.tree.leaves(grads)
    if not overlap:
        leaves = list(jax.lax.optimization_barrier(tuple(leaves)))
    shards = []
    chain = None
    for bucket in plan.buckets:
        flat = _chained(_flatten_bucket(leaves, bucket), chain, overlap)
        shard = jax.lax.psum_scatter(
            flat, axis_name, scatter_dimension=0, tiled=True)
        chain = shard
        if mean:
            shard = shard / plan.num_shards
        shards.append(shard)
    return shards


def all_gather_buckets(shards: Sequence[jax.Array], plan: ZeroPlan,
                       axis_name: str, *, overlap: bool = True):
    """Per-bucket tiled all-gather (the params-return half), chained
    like the scatters so early gathers overlap late buckets' update
    math."""
    full = []
    chain = None
    for bucket, shard in zip(plan.buckets, shards):
        g = jax.lax.all_gather(_chained(shard, chain, overlap),
                               axis_name, axis=0, tiled=True)
        chain = g
        full.append(g)
    return full


def shard_params(params, plan: ZeroPlan, axis_name: str):
    """This rank's ``[shard]`` slice of each flat param bucket (params
    are replicated under ZeRO-1; the slice is local, no collective)."""
    leaves = jax.tree.leaves(params)
    idx = jax.lax.axis_index(axis_name)
    out = []
    for bucket in plan.buckets:
        flat = _flatten_bucket(leaves, bucket)
        out.append(jax.lax.dynamic_slice_in_dim(
            flat, idx * bucket.shard, bucket.shard))
    return out


def finite_shards(shards: Sequence[jax.Array], axis_name: str):
    """The NaN/inf guard predicate off the SCATTERED grad shards: each
    rank counts non-finite elements in its slices, ONE summed scalar
    psum agrees the verdict — same count-and-sum shape as
    ``step.finite_grads`` (ADD-combines fold under XLA's
    AllReduceReassociate; see that docstring), just computed where the
    reduced grads now live."""
    bad = jnp.asarray(0, jnp.int32)
    for s in shards:
        bad = bad + jnp.sum(
            jnp.logical_not(jnp.isfinite(s)).astype(jnp.int32))
    return jax.lax.psum(bad, axis_name) == 0


def clip_shards_by_global_norm(shards: Sequence[jax.Array],
                               axis_name: str, max_norm: float):
    """Global-norm clipping on scattered shards: partial sum of
    squares per rank + one scalar psum = the full-tree norm; the scale
    is replicated so every rank clips identically.

    NOTE: this is the ONE zero-path piece that is not bit-identical to
    the replicated baseline — the norm sums per-shard partials in rank
    order instead of the replicated path's single leafwise sum, so
    clipped trajectories agree to float-reassociation tolerance only
    (the scale itself differs by ulps when the reassociated sums
    round differently). Unavoidable without gathering the grads the
    schedule exists not to gather; documented at every claim site."""
    sq = sum(jnp.sum(jnp.square(s.astype(jnp.float32))) for s in shards)
    gnorm = jnp.sqrt(jax.lax.psum(sq, axis_name))
    scale = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
    return [s * scale for s in shards]


def comm_probe(plan: ZeroPlan, mesh: Mesh,
               axis_name: str = DATA_AXIS):
    """Jitted comm-only program: the step's exact bucketed
    reduce-scatter + all-gather dependency chain on dummy grad-sized
    buffers. The bench times it solo (drained, synced) to measure the
    standalone grad-comm wall — the denominator of the overlap
    fraction. Takes a list of ``[padded]`` arrays (one per bucket,
    replicated) and returns the gathered buckets."""
    from ..utils.compat import shard_map

    def body(flats):
        shards = []
        chain = None
        for flat in flats:
            flat = _chained(flat, chain, True)
            s = jax.lax.psum_scatter(
                flat, axis_name, scatter_dimension=0, tiled=True)
            chain = s
            shards.append(s)
        return all_gather_buckets(shards, plan, axis_name)

    n = len(plan.buckets)
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=([P()] * n,), out_specs=[P()] * n,
        check_vma=False))


# ------------------------------------------------------ sharded state

@flax.struct.dataclass
class ZeroOptState:
    """Optimizer state with moment buffers stored as per-bucket flat
    arrays of GLOBAL shape ``[padded]``, placed ``P(data)`` on the mesh
    — each rank holds ``padded/N``. ``inner`` keeps the wrapped
    transform's own structure (``OptState``/``LambState``) with each
    moment tree replaced by the bucket list, so the transform's
    ``update`` runs on it unchanged; scalar leaves (step counts, init
    flags) stay replicated."""

    inner: Any
    plan: ZeroPlan = flax.struct.field(pytree_node=False)
    moment_fields: Tuple[str, ...] = flax.struct.field(
        pytree_node=False, default=())

    def specs(self, axis_name: str):
        """The shard_map spec tree: ``P(axis)`` on every bucket array,
        ``P()`` on scalars — mirrors this state's structure."""
        spec = jax.tree.map(lambda _: P(), self.inner)
        spec = spec._replace(**{
            f: [P(axis_name)] * len(self.plan.buckets)
            for f in self.moment_fields})
        return ZeroOptState(inner=spec, plan=self.plan,
                            moment_fields=self.moment_fields)


def _moment_fields(inner, params) -> Tuple[str, ...]:
    """Fields of a NamedTuple-style transform state whose value
    mirrors the param-tree structure (the moment buffers to shard);
    everything else must be scalar-leaved (kept replicated)."""
    fields = getattr(inner, "_fields", None)
    if fields is None:
        raise ValueError(
            "zero mode needs a NamedTuple-style optimizer state "
            f"(OptState/LambState), got {type(inner).__name__}")
    p_struct = jax.tree.structure(params)
    moments = []
    for f in fields:
        val = getattr(inner, f)
        if jax.tree.structure(val) == p_struct and jax.tree.leaves(val):
            moments.append(f)
        else:
            for leaf in jax.tree.leaves(val):
                if getattr(leaf, "ndim", 0) != 0:
                    raise ValueError(
                        f"optimizer state field {f!r} is neither a "
                        "param-shaped moment tree nor scalar-leaved — "
                        "zero mode cannot shard it")
    return tuple(moments)


def _is_abstract(tree) -> bool:
    return any(not hasattr(leaf, "dtype") or isinstance(
        leaf, jax.ShapeDtypeStruct) for leaf in jax.tree.leaves(tree))


def zeroify_state(state, mesh: Mesh, *, axis_name: str = DATA_AXIS,
                  bucket_bytes: Optional[int] = None):
    """Replace a replicated-format ``opt_state`` with a sharded
    :class:`ZeroOptState`: moments flattened into the plan's buckets
    and device_put ``P(axis_name)`` so each rank materializes only its
    slice. Abstract states (``ShapeDtypeStruct`` leaves — the audit
    path) produce abstract bucket leaves, no placement. Values carry
    over exactly, so a resumed inner state round-trips."""
    if isinstance(state.opt_state, ZeroOptState):
        raise ValueError("state is already zero-sharded")
    num = int(mesh.shape[axis_name])
    plan = plan_buckets(state.params, num, bucket_bytes=bucket_bytes)
    inner = state.opt_state
    moments = _moment_fields(inner, state.params)
    if not moments:
        raise ValueError(
            f"{type(inner).__name__} has no param-shaped moment "
            "buffers to shard — zero mode would change nothing")
    abstract = _is_abstract(inner)
    sharding = (None if abstract
                else NamedSharding(mesh, P(axis_name)))

    def bucketize(tree):
        leaves = jax.tree.leaves(tree)
        shapes = tuple(tuple(int(d) for d in leaf.shape)
                       for leaf in leaves)
        if shapes != plan.leaf_shapes:
            raise ValueError(
                "optimizer moment tree does not mirror the param "
                "tree's leaf shapes — cannot bucket it")
        dtypes = tuple(str(jnp.dtype(leaf.dtype)) for leaf in leaves)
        if dtypes != plan.leaf_dtypes:
            raise ValueError(
                "optimizer moment dtypes do not mirror the param "
                "tree's — the dtype-homogeneous buckets would "
                "silently promote; shard such a transform explicitly")
        out = []
        for b in plan.buckets:
            if abstract:
                out.append(jax.ShapeDtypeStruct((b.padded,),
                                                jnp.dtype(b.dtype)))
            else:
                flat = _flatten_bucket([jnp.asarray(x) for x in leaves],
                                       b)
                out.append(jax.device_put(flat, sharding))
        return out

    new_inner = inner._replace(
        **{f: bucketize(getattr(inner, f)) for f in moments})
    return state.replace(opt_state=ZeroOptState(
        inner=new_inner, plan=plan, moment_fields=moments))


def gather_opt_state(zstate: ZeroOptState, params):
    """Inverse of :func:`zeroify_state`'s bucketing: the inner
    (replicated-format) state, moments unflattened to the param tree.
    Host-side (``np.asarray`` reads each global bucket — the
    gather-on-save moment); callers with non-addressable shards gather
    first (``checkpoint._gather_for_host``)."""
    import numpy as np

    plan = zstate.plan

    def unbucket(flats):
        host = [np.asarray(f) for f in flats]
        return _unflatten_buckets(host, plan, params)

    return zstate.inner._replace(
        **{f: unbucket(getattr(zstate.inner, f))
           for f in zstate.moment_fields})


def train_state_specs(state, axis_name: str = DATA_AXIS):
    """Per-leaf shard_map spec tree for a ``TrainState`` carrying a
    :class:`ZeroOptState`: everything replicated (``P()``) except the
    moment buckets (``P(axis)``)."""
    if not isinstance(state.opt_state, ZeroOptState):
        raise ValueError(
            "train_state_specs wants a zero-sharded state (build it "
            "with zeroify_state)")
    return state.replace(
        params=jax.tree.map(lambda _: P(), state.params),
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=state.opt_state.specs(axis_name),
        epoch=P(),
        ema_params=jax.tree.map(lambda _: P(), state.ema_params),
    )


# ------------------------------------------------------ sharded update

def apply_sharded_update(optimizer, zstate: ZeroOptState,
                         grad_shards: Sequence[jax.Array], params,
                         axis_name: str, *, lr_step=None,
                         overlap: bool = True):
    """The ZeRO-1 update: optimizer math on local shards, ONE bucketed
    all-gather back to full params.

    Transforms with the ``shard_update``/``shard_finish`` pair (both
    shipped optimizers) compute the elementwise direction sharded,
    gather it, and apply the finish phase (LR scale, LAMB's per-leaf
    trust ratio) on FULL leaves — the exact replicated math, so the
    trajectory is bit-identical to the baseline. A custom transform
    without the seam falls back to its unmodified ``update`` on the
    flat shard pytrees (lists of ``[shard]`` arrays stand in for the
    param tree) — correct only for purely elementwise updates.

    Returns ``(new_params, new_zstate)``.
    """
    if getattr(optimizer, "apply", None) is not None:
        raise ValueError(
            "zero mode shards the update through the transform's "
            "update()/shard_update() path; a fused whole-update "
            "optimizer (apply=...) cannot run on shards — use the "
            "unfused transform")
    plan = zstate.plan
    p_shards = shard_params(params, plan, axis_name)
    shard_update = getattr(optimizer, "shard_update", None)
    if shard_update is not None:
        u_shards, new_inner = shard_update(
            list(grad_shards), zstate.inner, p_shards, lr_step=lr_step)
    else:
        u_shards, new_inner = optimizer.update(
            list(grad_shards), zstate.inner, p_shards, lr_step=lr_step)
    full = all_gather_buckets(u_shards, plan, axis_name,
                              overlap=overlap)
    updates = _unflatten_buckets(full, plan, params)
    shard_finish = getattr(optimizer, "shard_finish", None)
    if shard_finish is not None:
        updates = shard_finish(updates, params, lr_step=lr_step)
    from ..train.optim import apply_updates

    new_params = apply_updates(params, updates)
    return new_params, ZeroOptState(inner=new_inner, plan=plan,
                                    moment_fields=zstate.moment_fields)
