"""Named device-mesh construction.

The reference's notion of topology is an integer ``world_size`` mapped to
one CUDA device per spawned process (``main.py:185-193``). Here topology
is a named :class:`jax.sharding.Mesh` with a ``data`` axis (the DP axis —
DDP's replica dimension) and a ``model`` axis (left open for tensor
parallelism; size 1 for parity workloads). XLA lays collectives over ICI
within a slice and DCN across slices according to this mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    world_size: Optional[int] = None,
    model_parallel: int = 1,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = (DATA_AXIS, MODEL_AXIS),
) -> Mesh:
    """Build a ``(data, model)`` mesh.

    Args:
      world_size: size of the data axis (the reference's ``--world_size``,
        ``main.py:28``). Defaults to ``len(devices) // model_parallel``.
      model_parallel: size of the model axis (1 = pure DP, the reference's
        only mode).
      devices: devices to lay out; defaults to ``jax.devices()``.

    Unlike the reference — which trusts ``--world_size`` and deadlocks or
    crashes in NCCL if it exceeds the GPU count — mesh construction
    validates the factorization eagerly.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if world_size is None:
        if n % model_parallel:
            raise ValueError(
                f"{n} devices not divisible by model_parallel={model_parallel}"
            )
        world_size = n // model_parallel
    need = world_size * model_parallel
    if need > n:
        raise ValueError(
            f"mesh needs {need} devices (data={world_size} x "
            f"model={model_parallel}) but only {n} are available"
        )
    grid = np.asarray(devices[:need]).reshape(world_size, model_parallel)
    return Mesh(grid, axis_names)


def data_axis_size(mesh: Mesh) -> int:
    """The DP degree — the reference's ``world_size``."""
    return mesh.shape[DATA_AXIS]


def audit_mesh(data: int = 1, model: int = 1) -> Mesh:
    """The mesh graftcheck's canonical programs are audited on.

    One place so every registered program (``analysis/programs.py``
    hooks) agrees on geometry — committed collective budgets are
    per-shard byte counts and must not drift with ad-hoc mesh choices.
    Built over host devices (the audits trace/lower/compile, never
    execute); raises with the fix spelled out when the process exposes
    too few devices (``make check`` sets the flag).
    """
    need = data * model
    devices = jax.devices()
    if len(devices) < need:
        raise RuntimeError(
            f"graftcheck mesh needs {need} devices (data={data} x "
            f"model={model}) but this process exposes {len(devices)} — "
            "run under XLA_FLAGS=--xla_force_host_platform_device_count"
            "=8 (the `make check` environment)"
        )
    return make_mesh(data, model, devices=devices[:need])
