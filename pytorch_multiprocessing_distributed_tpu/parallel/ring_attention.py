"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support (first-class per the framework goals; the reference
family has no attention at all — SURVEY.md §5 marks SP "absent by
construction", this is the forward-looking half of the mesh design whose
``sequence`` axis slot it reserves).

Mechanism: Q stays resident per shard; K/V blocks rotate around the ring
(``lax.ppermute`` — XLA lowers to ICI neighbor exchanges that overlap
with the block matmuls). Each hop computes a partial attention block and
folds it into a numerically-stable streaming softmax (running max ``m``,
denominator ``l``, unnormalized output ``o`` — the flash-attention
recurrence), so the result is EXACT full attention over the global
sequence while no shard ever materializes more than its local block.

Memory per shard: O(S_local^2) logits instead of O(S_global^2); ICI
traffic: (ring_size - 1) K/V block transfers, fully overlapped.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention with K/V ring rotation over ``axis_name``.

    Args:
      q, k, v: per-shard ``[batch, seq_local, heads, head_dim]``; the
        global sequence is sharded over ``axis_name``.
      axis_name: bound mesh axis (inside ``shard_map``/``pmap``).
      scale: logit scale; default ``head_dim ** -0.5``.

    Returns:
      ``[batch, seq_local, heads, head_dim]`` — this shard's slice of the
      full-attention output.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # internal layout [b, h, s, c] keeps the matmuls MXU-shaped
    qh = jnp.moveaxis(q, 2, 1).astype(jnp.float32) * scale
    b, h, s_q, c = qh.shape

    def fold(o, m, l, k_blk, v_blk):
        """Fold one K/V block into the streaming-softmax accumulators."""
        kh = jnp.moveaxis(k_blk, 2, 1).astype(jnp.float32)  # [b,h,sk,c]
        vh = jnp.moveaxis(v_blk, 2, 1).astype(jnp.float32)
        logits = jnp.einsum("bhqc,bhkc->bhqk", qh, kh)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkc->bhqc", p, vh)
        return o_new, m_new, l_new

    def hop(carry, _):
        o, m, l, k_blk, v_blk = carry
        o, m, l = fold(o, m, l, k_blk, v_blk)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    o0 = jnp.zeros((b, h, s_q, c), jnp.float32)
    m0 = jnp.full((b, h, s_q), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    # Scan the first axis_size-1 hops (each ends by rotating K/V one step
    # around the ring), then fold the final block OUTSIDE the scan — the
    # last rotation's result would be discarded, so issuing it is pure
    # wasted ICI traffic. Total transfers: axis_size - 1 per K and V.
    (o, m, l, k_last, v_last), _ = jax.lax.scan(
        hop, (o0, m0, l0, k, v), None, length=axis_size - 1
    )
    o, m, l = fold(o, m, l, k_last, v_last)
    out = o / l[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)
