"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support (first-class per the framework goals; the reference
family has no attention at all — SURVEY.md §5 marks SP "absent by
construction", this is the forward-looking half of the mesh design whose
``sequence`` axis slot it reserves).

Mechanism: Q stays resident per shard; K/V blocks rotate around the ring
(``lax.ppermute`` — XLA lowers to ICI neighbor exchanges that overlap
with the block compute). Each hop runs the Pallas flash kernel on the
(resident Q, visiting K/V) pair — the [S_local, S_local] logit block
lives only in VMEM tiles, never in HBM — and the per-hop (output, lse)
pairs are folded with the streaming log-sum-exp combine, so the result
is EXACT full attention over the global sequence.

Causal mode: with contiguous sequence sharding, a visiting block from
shard ``src`` relates to resident rows of shard ``i`` as: fully visible
(``src < i``), diagonal (``src == i`` — local causal mask), or fully
masked (``src > i`` — skipped). The skip makes later shards idle part of
each rotation — the classic ring-causal load imbalance: shard 0 folds 1
block while shard N-1 folds N, so utilization averages ~(N+1)/2N.

``zigzag=True`` kills that tail: the global sequence is cut into ``2N``
chunks and shard ``i`` holds chunks ``(i, 2N-1-i)`` (layout from
:func:`zigzag_indices`; the llama3-style context-parallel ordering).
Per visiting block, each shard now folds exactly two half-quadrants —
(early rows x visiting early cols) on the ``src <= i`` triangle,
(late rows x visiting early cols) always, (late rows x visiting late
cols) on the mirrored triangle — constant work every hop on every
shard, same exact-attention total.

Backward (custom VJP): per-hop residuals are never saved — only this
shard's (q, k, v, out, GLOBAL lse). The backward re-rotates K/V around
the ring together with their gradient accumulators, and each hop calls
the pairwise flash backward kernels with the global lse
(:func:`..ops.pallas.flash_attention._flash_pair_grads`), which makes
the recomputed partial-block gradients exact against the full-sequence
softmax. Memory: O(S_local) residuals instead of O(hops * S_local^2)
that plain autodiff through the scan would save (round-2 VERDICT weak
#6).

ICI traffic: forward ``axis_size - 1`` K/V hops; backward ``axis_size``
hops of (K, V, dK, dV) — the extra hop returns the gradient
accumulators to their home shard.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.pallas.flash_attention import (
    NEG_INF,
    _flash_fwd,
    _flash_pair_grads,
    _round8,
)


def zigzag_indices(seq_len: int, n_shards: int):
    """``[n_shards, seq_len // n_shards]`` global positions per shard.

    Shard ``i`` holds chunks ``i`` and ``2 * n_shards - 1 - i`` of the
    ``2 * n_shards``-chunked sequence, concatenated. Callers permute
    tokens (and positional state) into this layout before a
    ``zigzag=True`` ring; ``indices.reshape(-1)`` is the permutation and
    ``argsort`` of it the inverse.
    """
    import numpy as np

    if seq_len % (2 * n_shards):
        raise ValueError(
            f"zigzag needs seq_len divisible by 2 x n_shards "
            f"({seq_len} vs 2 x {n_shards})"
        )
    c = seq_len // (2 * n_shards)
    idx = np.arange(seq_len).reshape(2 * n_shards, c)
    return np.stack([
        np.concatenate([idx[i], idx[2 * n_shards - 1 - i]])
        for i in range(n_shards)
    ])


def _lse_fold(o, m, z, out_j, lse_j):
    """Streaming log-sum-exp combine of one partial (out, lse)."""
    m_new = jnp.maximum(m, lse_j)
    corr = jnp.exp(m - m_new)
    w = jnp.exp(lse_j - m_new)
    o_new = o * corr[..., None] + out_j.astype(jnp.float32) * w[..., None]
    return o_new, m_new, z * corr + w


def _merge_heads(x):
    """[b, s, h, d] -> [b*h, s, d] (the flash kernels' layout)."""
    b, s, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)


def _split_heads(x3, b, h):
    bh, s, d = x3.shape
    return jnp.moveaxis(x3.reshape(b, h, s, d), 1, 2)


def _hop_cases(src, my, causal):
    """(fold_anything, use_causal_mask) for a visiting block."""
    if not causal:
        return jnp.bool_(True), jnp.bool_(False)
    return src <= my, src == my


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring(q3, k3, v3, scale, causal, block_q, block_k, interpret,
          axis_name, zigzag):
    impl = _zig_fwd_impl if zigzag else _ring_fwd_impl
    out, _ = impl(q3, k3, v3, scale, causal, block_q, block_k,
                  interpret, axis_name)
    return out


def _pair_fwd(q3, k_blk, v_blk, diag, scale, causal, block_q, block_k,
              interpret):
    """(out_j, lse_j) for one hop. ``diag`` (traced bool) selects the
    causal-masked kernel variant on the diagonal hop."""
    if not causal:
        return _flash_fwd(q3, k_blk, v_blk, scale, False, block_q,
                          block_k, interpret)
    return jax.lax.cond(
        diag,
        lambda: _flash_fwd(q3, k_blk, v_blk, scale, True, block_q,
                           block_k, interpret),
        lambda: _flash_fwd(q3, k_blk, v_blk, scale, False, block_q,
                           block_k, interpret),
    )


def _ring_fwd_impl(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                   axis_name):
    """Returns (out [bh, s, d], global lse [bh, s] f32)."""
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    bh, s_q, d = q3.shape

    o0 = jnp.zeros((bh, s_q, d), jnp.float32)
    m0 = jnp.full((bh, s_q), NEG_INF, jnp.float32)
    z0 = jnp.zeros((bh, s_q), jnp.float32)

    def fold(o, m, z, k_blk, v_blk, hop):
        src = (my - hop) % axis_size
        fold_any, diag = _hop_cases(src, my, causal)

        def do_fold():
            out_j, lse_j = _pair_fwd(q3, k_blk, v_blk, diag, scale,
                                     causal, block_q, block_k, interpret)
            return _lse_fold(o, m, z, out_j, lse_j)

        if not causal:
            return do_fold()
        return jax.lax.cond(fold_any, do_fold, lambda: (o, m, z))

    def hop_step(carry, hop):
        o, m, z, k_blk, v_blk = carry
        o, m, z = fold(o, m, z, k_blk, v_blk, hop)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, z, k_next, v_next), None

    # last hop folds outside the scan: its rotation would be discarded
    (o, m, z, k_last, v_last), _ = jax.lax.scan(
        hop_step, (o0, m0, z0, k3, v3), jnp.arange(axis_size - 1)
    )
    o, m, z = fold(o, m, z, k_last, v_last, axis_size - 1)

    z_safe = jnp.maximum(z, 1e-30)
    out = (o / z_safe[..., None]).astype(q3.dtype)
    lse = m + jnp.log(z_safe)
    return out, lse


def _zig_fwd_impl(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                  axis_name):
    """Zigzag forward: per-shard rows are (chunk my, chunk 2N-1-my).

    Per visiting block from ``src`` (its cols = chunks ``src`` /
    ``2N-1-src``) the three live quadrants are:

    - A: early rows x early cols — triangle (``src < my`` full,
      ``== my`` diagonal, ``> my`` skip);
    - B: late rows x early cols — ALWAYS fully visible
      (``src < N <= 2N-1-my``);
    - C: late rows x late cols — mirrored triangle (``src > my`` full,
      ``== my`` diagonal, ``< my`` skip).

    (Early rows x late cols is never visible.) Every shard folds ~2
    half-blocks per hop — the balanced schedule.
    """
    del causal  # zigzag IS the causal layout (validated by the wrapper)
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    bh, s, d = q3.shape
    hs = s // 2
    qa, qb = q3[:, :hs], q3[:, hs:]

    def init_state():
        return (jnp.zeros((bh, hs, d), jnp.float32),
                jnp.full((bh, hs), NEG_INF, jnp.float32),
                jnp.zeros((bh, hs), jnp.float32))

    def quad(state, q_half, k_half, v_half, diag):
        out_j, lse_j = _pair_fwd(q_half, k_half, v_half, diag, scale,
                                 True, block_q, block_k, interpret)
        return _lse_fold(*state, out_j, lse_j)

    def fold(sa, sb, k_blk, v_blk, hop):
        src = (my - hop) % axis_size
        kc, vc = k_blk[:, :hs], v_blk[:, :hs]
        kd, vd = k_blk[:, hs:], v_blk[:, hs:]
        diag = src == my
        sa = jax.lax.cond(
            src <= my, lambda: quad(sa, qa, kc, vc, diag), lambda: sa)
        sb = quad(sb, qb, kc, vc, jnp.bool_(False))
        sb = jax.lax.cond(
            src >= my, lambda: quad(sb, qb, kd, vd, diag), lambda: sb)
        return sa, sb

    def hop_step(carry, hop):
        sa, sb, k_blk, v_blk = carry
        sa, sb = fold(sa, sb, k_blk, v_blk, hop)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (sa, sb, k_next, v_next), None

    (sa, sb, k_last, v_last), _ = jax.lax.scan(
        hop_step, (init_state(), init_state(), k3, v3),
        jnp.arange(axis_size - 1),
    )
    sa, sb = fold(sa, sb, k_last, v_last, axis_size - 1)

    def finish(state):
        o, m, z = state
        z_safe = jnp.maximum(z, 1e-30)
        return (o / z_safe[..., None]).astype(q3.dtype), m + jnp.log(z_safe)

    out_a, lse_a = finish(sa)
    out_b, lse_b = finish(sb)
    return (jnp.concatenate([out_a, out_b], axis=1),
            jnp.concatenate([lse_a, lse_b], axis=1))


def _zig_vjp_bwd(scale, causal, block_q, block_k, interpret, axis_name,
                 res, do):
    """Zigzag backward: same three quadrants, grads per half; dK/dV
    accumulators for BOTH col halves rotate with their K/V blocks."""
    del causal
    q3, k3, v3, out, lse = res
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    hs = q3.shape[1] // 2

    do_c = do.astype(q3.dtype)
    dterm = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qa, qb = q3[:, :hs], q3[:, hs:]
    do_a, do_b = do_c[:, :hs], do_c[:, hs:]
    lse_a, lse_b = lse[:, :hs], lse[:, hs:]
    dt_a, dt_b = dterm[:, :hs], dterm[:, hs:]

    def quad_bwd(q_h, k_h, v_h, do_h, lse_h, dt_h, diag):
        def run(c):
            return _flash_pair_grads(
                q_h, k_h, v_h, do_h, lse_h, dt_h, scale=scale, causal=c,
                block_q=block_q, block_k=block_k, interpret=interpret)

        return jax.lax.cond(diag, lambda: run(True), lambda: run(False))

    def fold(dqa, dqb, dkc, dvc, dkd, dvd, k_blk, v_blk, hop):
        src = (my - hop) % axis_size
        kc, vc = k_blk[:, :hs], v_blk[:, :hs]
        kd, vd = k_blk[:, hs:], v_blk[:, hs:]
        diag = src == my

        def fold_a():
            dq_p, dk_p, dv_p = quad_bwd(qa, kc, vc, do_a, lse_a, dt_a,
                                        diag)
            return (dqa + dq_p.astype(jnp.float32),
                    dkc + dk_p.astype(jnp.float32),
                    dvc + dv_p.astype(jnp.float32))

        dqa, dkc, dvc = jax.lax.cond(
            src <= my, fold_a, lambda: (dqa, dkc, dvc))

        dq_p, dk_p, dv_p = quad_bwd(qb, kc, vc, do_b, lse_b, dt_b,
                                    jnp.bool_(False))
        dqb = dqb + dq_p.astype(jnp.float32)
        dkc = dkc + dk_p.astype(jnp.float32)
        dvc = dvc + dv_p.astype(jnp.float32)

        def fold_c():
            dq_p, dk_p, dv_p = quad_bwd(qb, kd, vd, do_b, lse_b, dt_b,
                                        diag)
            return (dqb + dq_p.astype(jnp.float32),
                    dkd + dk_p.astype(jnp.float32),
                    dvd + dv_p.astype(jnp.float32))

        dqb, dkd, dvd = jax.lax.cond(
            src >= my, fold_c, lambda: (dqb, dkd, dvd))
        return dqa, dqb, dkc, dvc, dkd, dvd

    def hop_step(carry, hop):
        dqa, dqb, k_blk, v_blk, dkc, dvc, dkd, dvd = carry
        dqa, dqb, dkc, dvc, dkd, dvd = fold(
            dqa, dqb, dkc, dvc, dkd, dvd, k_blk, v_blk, hop)
        k_blk, v_blk, dkc, dvc, dkd, dvd = jax.lax.ppermute(
            (k_blk, v_blk, dkc, dvc, dkd, dvd), axis_name, perm)
        return (dqa, dqb, k_blk, v_blk, dkc, dvc, dkd, dvd), None

    zero_h = lambda like: jnp.zeros(  # noqa: E731
        (like.shape[0], hs, like.shape[2]), jnp.float32)
    carry0 = (zero_h(q3), zero_h(q3), k3, v3,
              zero_h(k3), zero_h(v3), zero_h(k3), zero_h(v3))
    (dqa, dqb, k_last, v_last, dkc, dvc, dkd, dvd), _ = jax.lax.scan(
        hop_step, carry0, jnp.arange(axis_size - 1))
    dqa, dqb, dkc, dvc, dkd, dvd = fold(
        dqa, dqb, dkc, dvc, dkd, dvd, k_last, v_last, axis_size - 1)
    # one more rotation brings the accumulators home
    dkc, dvc, dkd, dvd = jax.lax.ppermute(
        (dkc, dvc, dkd, dvd), axis_name, perm)
    dq = jnp.concatenate([dqa, dqb], axis=1)
    dk = jnp.concatenate([dkc, dkd], axis=1)
    dv = jnp.concatenate([dvc, dvd], axis=1)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


def _ring_vjp_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                  axis_name, zigzag):
    impl = _zig_fwd_impl if zigzag else _ring_fwd_impl
    out, lse = impl(q3, k3, v3, scale, causal, block_q, block_k,
                    interpret, axis_name)
    return out, (q3, k3, v3, out, lse)


def _ring_vjp_bwd(scale, causal, block_q, block_k, interpret, axis_name,
                  zigzag, res, do):
    if zigzag:
        return _zig_vjp_bwd(scale, causal, block_q, block_k, interpret,
                            axis_name, res, do)
    q3, k3, v3, out, lse = res
    axis_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    do_c = do.astype(q3.dtype)
    dterm = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [bh, s]

    def pair_bwd(k_blk, v_blk, diag):
        def run(c):
            return _flash_pair_grads(
                q3, k_blk, v_blk, do_c, lse, dterm,
                scale=scale, causal=c, block_q=block_q, block_k=block_k,
                interpret=interpret,
            )

        if not causal:
            return run(False)
        return jax.lax.cond(diag, lambda: run(True), lambda: run(False))

    def fold(dq, dk_blk, dv_blk, k_blk, v_blk, hop):
        src = (my - hop) % axis_size
        fold_any, diag = _hop_cases(src, my, causal)

        def do_fold():
            dq_p, dk_p, dv_p = pair_bwd(k_blk, v_blk, diag)
            return (dq + dq_p.astype(jnp.float32),
                    dk_blk + dk_p.astype(jnp.float32),
                    dv_blk + dv_p.astype(jnp.float32))

        if not causal:
            return do_fold()
        return jax.lax.cond(
            fold_any, do_fold, lambda: (dq, dk_blk, dv_blk)
        )

    def hop_step(carry, hop):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        dq, dk_blk, dv_blk = fold(dq, dk_blk, dv_blk, k_blk, v_blk, hop)
        # K/V and their grad accumulators travel TOGETHER so each
        # shard's contribution lands on the right (rotating) block
        k_blk, v_blk, dk_blk, dv_blk = jax.lax.ppermute(
            (k_blk, v_blk, dk_blk, dv_blk), axis_name, perm
        )
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    dq0 = jnp.zeros(q3.shape, jnp.float32)
    dk0 = jnp.zeros(k3.shape, jnp.float32)
    dv0 = jnp.zeros(v3.shape, jnp.float32)
    # axis_size - 1 scanned hops, final fold outside, then ONE rotation
    # of just the grad accumulators brings them home (K/V's final
    # rotation would be wasted ICI traffic)
    (dq, k_last, v_last, dk, dv), _ = jax.lax.scan(
        hop_step, (dq0, k3, v3, dk0, dv0), jnp.arange(axis_size - 1)
    )
    dq, dk, dv = fold(dq, dk, dv, k_last, v_last, axis_size - 1)
    dk, dv = jax.lax.ppermute((dk, dv), axis_name, perm)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    zigzag: bool = False,
) -> jax.Array:
    """Exact attention with K/V ring rotation over ``axis_name``.

    Args:
      q, k, v: per-shard ``[batch, seq_local, heads, head_dim]``; the
        global sequence is sharded contiguously over ``axis_name``
        (shard i holds positions ``[i * seq_local, (i+1) * seq_local)``)
        — or, with ``zigzag=True``, in the :func:`zigzag_indices`
        layout (shard i holds chunks ``i`` and ``2N-1-i``), which
        balances the causal fold work across shards (kills the
        per-rotation idle tail of later shards). Requires ``causal``
        and an even ``seq_local``.
      axis_name: bound mesh axis (inside ``shard_map``/``pmap``).
      scale: logit scale; default ``head_dim ** -0.5``.
      causal: causal masking over GLOBAL positions.
      block_q, block_k: flash-kernel tile sizes (see
        :func:`..ops.pallas.flash_attention.flash_attention`).
      interpret: force Pallas interpret mode (default: auto — interpret
        everywhere except real TPU).

    Returns:
      ``[batch, seq_local, heads, head_dim]`` — this shard's slice of
      the full-attention output, differentiable (custom VJP).
    """
    if interpret is None:
        from ..ops.pallas import default_interpret

        interpret = default_interpret()
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, s_loc, h, d = q.shape
    if zigzag:
        if not causal:
            raise ValueError(
                "zigzag layout only applies to causal attention (there "
                "is no load imbalance to fix without causality)"
            )
        if s_loc % 2:
            raise ValueError(
                f"zigzag needs an even per-shard sequence, got {s_loc}"
            )
    eff_q = s_loc // 2 if zigzag else s_loc
    eff_k = k.shape[1] // 2 if zigzag else k.shape[1]
    block_q = _round8(min(block_q, eff_q))
    block_k = _round8(min(block_k, eff_k))
    out3 = _ring(
        _merge_heads(q), _merge_heads(k), _merge_heads(v), float(scale),
        bool(causal), int(block_q), int(block_k), bool(interpret),
        axis_name, bool(zigzag),
    )
    return _split_heads(out3, b, h)
