"""Neural-net ops: normalization and losses.

TPU-native equivalents of the kernels the reference borrows from
torch/cuDNN (see SURVEY.md §2.2): cross-replica batch norm replaces
``torch.nn.SyncBatchNorm`` (reference ``main.py:43``), the loss replaces
``nn.CrossEntropyLoss`` (reference ``main.py:48``).
"""

from .batch_norm import SyncBatchNorm
from .losses import cross_entropy_loss
from .moe import MoEMlp, shard_expert_params

__all__ = ["SyncBatchNorm", "cross_entropy_loss", "MoEMlp",
           "shard_expert_params"]
