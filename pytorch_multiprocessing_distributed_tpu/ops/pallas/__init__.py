"""Pallas TPU kernels for the framework's hot ops.

The reference leans on cuDNN/NCCL for its fused kernels and collectives
(SURVEY.md §2.2); XLA:TPU covers most of that surface automatically. These
kernels cover the spots where hand scheduling buys something XLA can't:

- :mod:`.flash_attention` — blockwise attention that never materializes
  the [S, S] logits in HBM (long-context support; XLA's dot+softmax+dot
  materializes logits).
- :mod:`.decode_attention` — flash-decode: the serving engine's
  one-query-per-slot cached attention step, K/V streamed once through
  VMEM with an online softmax and a per-slot position gate (cost tracks
  each slot's true length, not the window).
- :mod:`.fused_update` — single-pass SGD(momentum, nesterov, wd) update:
  one read of (param, grad, buf), one write of (param, buf), aliased
  in-place in HBM.
- :mod:`.ring_allreduce` — RDMA ring collectives over ICI, the
  educational/bench analogue of NCCL's ring all-reduce (production paths
  use ``lax.psum``, which XLA already lowers optimally).

All kernels run compiled on TPU and under ``interpret=True`` on CPU (the
test path; auto-selected when the backend is not TPU).
"""

from .decode_attention import (  # noqa: F401
    decode_attention, xla_decode_attention)
from .flash_attention import flash_attention  # noqa: F401
from .fused_update import fused_sgd_apply, sgd_pallas  # noqa: F401
from .ring_allreduce import ring_all_reduce  # noqa: F401


def default_interpret() -> bool:
    """Interpret mode unless running on real TPU hardware."""
    import jax

    return jax.default_backend() != "tpu"
