"""Blockwise (flash) attention as a Pallas TPU kernel.

Forward: one grid cell per (batch*head, q-block); the kernel streams
K/V blocks out of VMEM through the MXU, folding each into the running
max / denominator / unnormalized-output recurrence, so the full [S, S]
logit matrix never exists in HBM. This is the single-shard building
block of the framework's long-context story (ring attention rotates K/V
shards between chips with the same recurrence —
:mod:`..parallel.ring_attention`... see
``pytorch_multiprocessing_distributed_tpu/parallel/ring_attention.py``).

Backward: two Pallas kernels (standard flash-attention-2 style). The
forward saves the per-row log-sum-exp as a side output, so the backward
never redoes the softmax reduction; each kernel recomputes the QK block
product exactly ONCE per (q-block, k-block) pair inside VMEM — the dq
kernel accumulates over K blocks, the dk/dv kernel over Q blocks — with
peak memory O(S * block) instead of O(S^2). (Round-2 VERDICT weak #5:
the previous backward was plain-JAX scans recomputing QK twice.)

The pairwise-gradient entry point (:func:`_flash_pair_grads`) takes an
EXTERNAL log-sum-exp, which is exactly what a sequence-parallel ring
needs: ring attention calls it per hop with the global lse so per-hop
residuals never have to be saved (see
``pytorch_multiprocessing_distributed_tpu/parallel/ring_attention.py``).

The reference family has no attention at all (SURVEY.md §5 marks
sequence parallelism "absent by construction"); this kernel serves the
framework's ViT model family and the long-context mandate.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-finite: -inf breaks exp(m - m_new) when a row is all-masked


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
                scale, causal, block_q, block_k, kv_len):
    """One (batch*head, q-block, k-block) grid cell.

    The k dimension is the innermost grid axis: Pallas streams (1,
    block_k, d) K/V tiles from HBM through VMEM (auto double-buffered),
    while the softmax accumulators persist in VMEM scratch across the
    k iterations — VMEM residency is O(block) regardless of S.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def fold():
        # matmuls stay in the input dtype (bf16 hits the MXU's native
        # rate; a f32 upcast would quarter it) with f32 accumulation
        q = q_ref[0]  # [bq, d]
        kblk = k_ref[0]  # [bk, d]
        vblk = v_ref[0]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = col < kv_len  # padded K columns contribute nothing
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk, preferred_element_type=jnp.float32
        )

    if causal:
        # whole block strictly above the diagonal -> nothing to fold
        @pl.when(kb * block_k < (qi + 1) * block_q)
        def _():
            fold()
    else:
        fold()

    @pl.when(kb == n_k - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)
        # per-row log-sum-exp side output: the backward's softmax
        # normalizer, and ring attention's cross-hop combiner. Kept
        # [bq, 1]-shaped (trailing unit dim) — Mosaic requires the last
        # two block dims be (8k, 128k) or full, and (1, block_q) isn't.
        lse_ref[0] = m_scr[:] + jnp.log(l_safe)


def _pad_seq(x, block):
    s = x.shape[1]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    """q3: [bh, S_q, d], k3/v3: [bh, S_kv, d] (already head-merged).
    Returns ``(out [bh, S_q, d], lse [bh, S_q] f32)``. The K-column
    validity mask is derived from the KV length, NOT q's
    (cross-attention with S_q != S_kv is exact)."""
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    qp = _pad_seq(q3, block_q)
    kp = _pad_seq(k3, block_k)
    vp = _pad_seq(v3, block_k)
    sq_pad, sk_pad = qp.shape[1], kp.shape[1]
    grid = (bh, sq_pad // block_q, sk_pad // block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_pad, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, sq_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :q_len], lse[:, :q_len, 0]


def _bwd_mask(qi, kb, block_q, block_k, q_len, kv_len, causal):
    """Validity mask for one (q-block, k-block) pair. The backward MUST
    mask padded q rows too: their saved lse is ~NEG_INF, so an unmasked
    ``exp(s - lse)`` would be huge, not zero."""
    row = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    col = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    m = jnp.logical_and(row < q_len, col < kv_len)
    if causal:
        m = jnp.logical_and(m, col <= row)
    return m


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dt_ref, dq_ref,
                   acc, *, scale, causal, block_q, block_k, q_len, kv_len):
    """dq for one q-block, accumulated over the (innermost) k grid axis.
    QK is computed exactly once per (q-block, k-block) pair."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    def fold():
        # bf16 operands on the MXU, f32 accumulate (see _fwd_kernel)
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [bq, 1]
        dterm = dt_ref[0]  # [bq, 1] = rowsum(dO * O)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        mask = _bwd_mask(qi, kb, block_q, block_k, q_len, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - dterm)).astype(kblk.dtype)
        acc[:] += jnp.dot(ds, kblk, preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(kb * block_k < (qi + 1) * block_q)
        def _():
            fold()
    else:
        fold()

    @pl.when(kb == n_k - 1)
    def _():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dt_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, q_len, kv_len):
    """dk and dv for one k-block, accumulated over the (innermost) q grid
    axis — the transposed loop nest of :func:`_bwd_dq_kernel`."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def fold():
        # bf16 operands on the MXU, f32 accumulate (see _fwd_kernel)
        q = q_ref[0]
        kblk = k_ref[0]
        vblk = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [bq, 1]
        dterm = dt_ref[0]  # [bq, 1]
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        mask = _bwd_mask(qi, kb, block_q, block_k, q_len, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc[:] += jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, vblk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - dterm)).astype(q.dtype)
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(kb * block_k < (qi + 1) * block_q)
        def _():
            fold()
    else:
        fold()

    @pl.when(qi == n_q - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_pair_grads(q3, k3, v3, do, lse, dterm, *, scale, causal,
                      block_q, block_k, interpret):
    """(dq, dk, dv) for one q/kv pair given an EXTERNAL lse and D.

    ``lse [bh, S_q]`` is the softmax normalizer the probabilities are
    reconstructed against, and ``dterm [bh, S_q] = rowsum(dO * O)`` the
    softmax-jacobian diagonal. Passing them in (rather than recomputing)
    is what lets ring attention reuse these kernels per hop with the
    GLOBAL lse — gradients of a partial block against the full-sequence
    softmax come out exact, with no per-hop residuals.
    """
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    qp = _pad_seq(q3, block_q)
    dop = _pad_seq(do, block_q)
    kp = _pad_seq(k3, block_k)
    vp = _pad_seq(v3, block_k)
    pad_q = qp.shape[1] - lse.shape[1]
    # rows carried with a trailing unit dim (Mosaic block-shape legality)
    lsep = jnp.pad(lse, ((0, 0), (0, pad_q)),
                   constant_values=NEG_INF)[..., None]
    dtp = jnp.pad(dterm, ((0, 0), (0, pad_q)))[..., None]
    sq_pad, sk_pad = qp.shape[1], kp.shape[1]
    n_q, n_k = sq_pad // block_q, sk_pad // block_k

    qspec = pl.BlockSpec((1, block_q, d), lambda i, a, b: (i, a, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, block_k, d), lambda i, a, b: (i, b, 0),
                         memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, block_q, 1), lambda i, a, b: (i, a, 0),
                           memory_space=pltpu.VMEM)
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, q_len=q_len, kv_len=kv_len)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, n_q, n_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dtp)

    # transposed nest: grid (bh, k-block, q-block)
    qspec_t = pl.BlockSpec((1, block_q, d), lambda i, b, a: (i, a, 0),
                           memory_space=pltpu.VMEM)
    kspec_t = pl.BlockSpec((1, block_k, d), lambda i, b, a: (i, b, 0),
                           memory_space=pltpu.VMEM)
    rowspec_t = pl.BlockSpec((1, block_q, 1), lambda i, b, a: (i, a, 0),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, n_k, n_q),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, rowspec_t, rowspec_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_pad, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, sk_pad, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dtp)
    return dq[:, :q_len], dk[:, :kv_len], dv[:, :kv_len]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash3(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                        interpret)
    return out


def _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                          interpret)
    return out, (q3, k3, v3, out, lse)


def _flash3_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q3, k3, v3, out, lse = res
    do32 = do.astype(jnp.float32)
    dterm = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [bh, S_q]
    return _flash_pair_grads(
        q3, k3, v3, do.astype(q3.dtype), lse, dterm,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Memory-efficient exact attention.

    Args:
      q: ``[batch, seq_q, heads, head_dim]`` (the layout
        :mod:`..parallel.ring_attention` uses).
      k, v: ``[batch, seq_kv, heads, head_dim]`` — ``seq_kv`` may differ
        from ``seq_q`` (cross attention); lengths need not be multiples
        of the block sizes (padded + masked internally).
      scale: logit scale, default ``head_dim ** -0.5``.
      causal: apply a causal mask (requires ``seq_q == seq_kv``).
      block_q, block_k: VMEM tile sizes. The 512 default keeps the grid
        small enough that per-cell overhead doesn't dominate (measured
        on v5e: 512-blocks are ~2x faster than 256 and ~7x faster than
        128 at S=4096) while staying well inside VMEM at d<=128.
      interpret: force Pallas interpret mode; default = auto (interpret
        everywhere except real TPU).

    Returns:
      ``[batch, seq_q, heads, head_dim]`` attention output in ``q.dtype``.
    """
    if interpret is None:
        from . import default_interpret

        interpret = default_interpret()
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, s, h, d = q.shape
    s_kv = k.shape[1]
    if v.shape[1] != s_kv:
        raise ValueError(
            f"k and v sequence lengths differ: {s_kv} vs {v.shape[1]}"
        )
    if causal and s != s_kv:
        raise ValueError(
            f"causal flash attention needs seq_q == seq_kv, got {s} vs {s_kv}"
        )
    # Clamp blocks to the sequence, then 8-align the result so Mosaic
    # lowering gets legal TPU tile shapes (for small/odd lengths AND for
    # explicitly passed odd block sizes) — _pad_seq absorbs the rounding.
    block_q = _round8(min(block_q, s))
    block_k = _round8(min(block_k, s_kv))

    def merge(x):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    out3 = _flash3(
        merge(q), merge(k), merge(v), float(scale), bool(causal),
        int(block_q), int(block_k), bool(interpret),
    )
    return jnp.moveaxis(out3.reshape(b, h, s, d), 1, 2)


def _round8(n: int) -> int:
    return max(8, ((n + 7) // 8) * 8)
