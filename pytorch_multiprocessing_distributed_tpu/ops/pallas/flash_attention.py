"""Blockwise (flash) attention as a Pallas TPU kernel.

Forward: one grid cell per (batch*head, q-block); the kernel streams
K/V blocks out of VMEM through the MXU, folding each into the running
max / denominator / unnormalized-output recurrence, so the full [S, S]
logit matrix never exists in HBM. This is the single-shard building
block of the framework's long-context story (ring attention rotates K/V
shards between chips with the same recurrence —
:mod:`..parallel.ring_attention`... see
``pytorch_multiprocessing_distributed_tpu/parallel/ring_attention.py``).

Backward: blockwise recompute from the saved log-sum-exp (the standard
flash-attention backward), expressed as ``lax.scan`` over K/V (for dq)
and Q (for dk, dv) blocks in plain JAX — peak memory stays
O(S * block) instead of O(S^2).

The reference family has no attention at all (SURVEY.md §5 marks
sequence parallelism "absent by construction"); this kernel serves the
framework's ViT model family and the long-context mandate.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-finite: -inf breaks exp(m - m_new) when a row is all-masked


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr, *, scale,
                causal, block_q, block_k, kv_len):
    """One (batch*head, q-block, k-block) grid cell.

    The k dimension is the innermost grid axis: Pallas streams (1,
    block_k, d) K/V tiles from HBM through VMEM (auto double-buffered),
    while the softmax accumulators persist in VMEM scratch across the
    k iterations — VMEM residency is O(block) regardless of S.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def fold():
        q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
        kblk = k_ref[0].astype(jnp.float32)  # [bk, d]
        vblk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32)
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = col < kv_len  # padded K columns contribute nothing
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32
        )

    if causal:
        # whole block strictly above the diagonal -> nothing to fold
        @pl.when(kb * block_k < (qi + 1) * block_q)
        def _():
            fold()
    else:
        fold()

    @pl.when(kb == n_k - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc[:] / l_safe).astype(o_ref.dtype)


def _pad_seq(x, block):
    s = x.shape[1]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    """q3: [bh, S_q, d], k3/v3: [bh, S_kv, d] (already head-merged).
    Returns out [bh, S_q, d]. The K-column validity mask is derived from
    the KV length, NOT q's (cross-attention with S_q != S_kv is exact)."""
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    qp = _pad_seq(q3, block_q)
    kp = _pad_seq(k3, block_k)
    vp = _pad_seq(v3, block_k)
    sq_pad, sk_pad = qp.shape[1], kp.shape[1]
    grid = (bh, sq_pad // block_q, sk_pad // block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :q_len]


def _block_masks(q_len, kv_len, n_q, n_k, block_q, block_k, causal):
    """[n_q*bq, n_k*bk] validity mask factory, evaluated lazily per pair."""

    def mask(qb, kb):
        row = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        m = jnp.logical_and(row < q_len, col < kv_len)
        if causal:
            m = jnp.logical_and(m, col <= row)
        return m

    return mask


def _lse_blockwise(qb, kb_, mask_of, scale, n_k, block_q, block_k):
    """Recompute log-sum-exp per q row via the streaming recurrence.
    qb: [bh, n_q, bq, d], kb_: [bh, n_k, bk, d] -> lse [bh, n_q, bq]."""

    def for_qblock(qi, qblk):  # qblk: [bh, bq, d]
        def body(carry, inputs):
            m, l = carry
            ki, kblk = inputs
            s = jnp.einsum("bqd,bkd->bqk", qblk, kblk) * scale
            s = jnp.where(mask_of(qi, ki)[None], s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            l = l * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(s - m_new[..., None]), axis=-1
            )
            return (m_new, l), None

        bh, bq = qblk.shape[0], qblk.shape[1]
        init = (
            jnp.full((bh, bq), NEG_INF, jnp.float32),
            jnp.zeros((bh, bq), jnp.float32),
        )
        (m, l), _ = jax.lax.scan(
            body, init, (jnp.arange(n_k), jnp.moveaxis(kb_, 1, 0))
        )
        return m + jnp.log(jnp.maximum(l, 1e-30))

    n_q = qb.shape[1]
    return jax.vmap(for_qblock, in_axes=(0, 1), out_axes=1)(
        jnp.arange(n_q), qb
    )


def _flash_bwd_impl(q3, k3, v3, out, do, scale, causal, block_q, block_k):
    """Blockwise flash backward (plain JAX scans; O(S*block) peak).

    lse and the softmax-jacobian diagonal are recomputed blockwise from
    (q, k) / (p, do) — nothing O(S^2) is ever materialized, and the
    forward kernel doesn't need side outputs.
    """
    bh, q_len, d = q3.shape
    kv_len = k3.shape[1]
    f32 = functools.partial(jnp.asarray, dtype=jnp.float32)
    qp = _pad_seq(f32(q3), block_q)
    dop = _pad_seq(f32(do), block_q)
    kp = _pad_seq(f32(k3), block_k)
    vp = _pad_seq(f32(v3), block_k)
    sq_pad, sk_pad = qp.shape[1], kp.shape[1]
    n_q, n_k = sq_pad // block_q, sk_pad // block_k
    mask_of = _block_masks(q_len, kv_len, n_q, n_k, block_q, block_k, causal)

    # D_i = rowsum(dO * O) — the softmax-jacobian diagonal term.
    op_ = _pad_seq(f32(out), block_q)
    D = jnp.sum(dop * op_, axis=-1)  # [bh, sq_pad]

    qb = qp.reshape(bh, n_q, block_q, d)
    dob = dop.reshape(bh, n_q, block_q, d)
    Db = D.reshape(bh, n_q, block_q)
    kb_ = kp.reshape(bh, n_k, block_k, d)
    vb_ = vp.reshape(bh, n_k, block_k, d)
    lseb = _lse_blockwise(qb, kb_, mask_of, scale, n_k, block_q, block_k)

    def p_ds(qi, ki, qblk, kblk, vblk, lse_blk, do_blk, D_blk):
        """Recomputed probabilities and dS for one (q-block, k-block)."""
        s = jnp.einsum("bqd,bkd->bqk", qblk, kblk) * scale
        s = jnp.where(mask_of(qi, ki)[None], s, NEG_INF)
        p = jnp.exp(s - lse_blk[..., None])  # [bh, bq, bk]
        dp = jnp.einsum("bqd,bkd->bqk", do_blk, vblk)
        ds = p * (dp - D_blk[..., None])
        return p, ds

    # dq: scan K/V blocks for each Q block (carried over K).
    def dq_for_qblock(qi, qblk, do_blk, lse_blk, D_blk):
        def body(carry, inputs):
            ki, kblk, vblk = inputs
            _, ds = p_ds(qi, ki, qblk, kblk, vblk, lse_blk, do_blk, D_blk)
            return carry + jnp.einsum("bqk,bkd->bqd", ds, kblk) * scale, None

        init = jnp.zeros_like(qblk)
        dq, _ = jax.lax.scan(
            body, init,
            (jnp.arange(n_k), jnp.moveaxis(kb_, 1, 0), jnp.moveaxis(vb_, 1, 0)),
        )
        return dq

    dq = jax.vmap(
        dq_for_qblock, in_axes=(0, 1, 1, 1, 1), out_axes=1
    )(jnp.arange(n_q), qb, dob, lseb, Db)
    dq = dq.reshape(bh, sq_pad, d)[:, :q_len]

    # dk/dv: scan Q blocks for each K/V block.
    def dkv_for_kblock(ki, kblk, vblk):
        def body(carry, inputs):
            dk_acc, dv_acc = carry
            qi, qblk, do_blk, lse_blk, D_blk = inputs
            p, ds = p_ds(qi, ki, qblk, kblk, vblk, lse_blk, do_blk, D_blk)
            dv_acc = dv_acc + jnp.einsum("bqk,bqd->bkd", p, do_blk)
            dk_acc = dk_acc + jnp.einsum("bqk,bqd->bkd", ds, qblk) * scale
            return (dk_acc, dv_acc), None

        init = (jnp.zeros_like(kblk), jnp.zeros_like(vblk))
        (dk, dv), _ = jax.lax.scan(
            body, init,
            (jnp.arange(n_q), jnp.moveaxis(qb, 1, 0),
             jnp.moveaxis(dob, 1, 0), jnp.moveaxis(lseb, 1, 0),
             jnp.moveaxis(Db, 1, 0)),
        )
        return dk, dv

    dk, dv = jax.vmap(
        dkv_for_kblock, in_axes=(0, 1, 1), out_axes=1
    )(jnp.arange(n_k), kb_, vb_)
    dk = dk.reshape(bh, sk_pad, d)[:, :kv_len]
    dv = dv.reshape(bh, sk_pad, d)[:, :kv_len]
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash3(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                      interpret)


def _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret):
    out = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k,
                     interpret)
    return out, (q3, k3, v3, out)


def _flash3_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q3, k3, v3, out = res
    return _flash_bwd_impl(q3, k3, v3, out, do, scale, causal,
                           block_q, block_k)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Memory-efficient exact attention.

    Args:
      q: ``[batch, seq_q, heads, head_dim]`` (the layout
        :mod:`..parallel.ring_attention` uses).
      k, v: ``[batch, seq_kv, heads, head_dim]`` — ``seq_kv`` may differ
        from ``seq_q`` (cross attention); lengths need not be multiples
        of the block sizes (padded + masked internally).
      scale: logit scale, default ``head_dim ** -0.5``.
      causal: apply a causal mask (requires ``seq_q == seq_kv``).
      block_q, block_k: VMEM tile sizes (128-aligned for the MXU).
      interpret: force Pallas interpret mode; default = auto (interpret
        everywhere except real TPU).

    Returns:
      ``[batch, seq_q, heads, head_dim]`` attention output in ``q.dtype``.
    """
    if interpret is None:
        from . import default_interpret

        interpret = default_interpret()
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, s, h, d = q.shape
    s_kv = k.shape[1]
    if v.shape[1] != s_kv:
        raise ValueError(
            f"k and v sequence lengths differ: {s_kv} vs {v.shape[1]}"
        )
    if causal and s != s_kv:
        raise ValueError(
            f"causal flash attention needs seq_q == seq_kv, got {s} vs {s_kv}"
        )
    # Clamp blocks to the sequence, then 8-align the result so Mosaic
    # lowering gets legal TPU tile shapes (for small/odd lengths AND for
    # explicitly passed odd block sizes) — _pad_seq absorbs the rounding.
    block_q = _round8(min(block_q, s))
    block_k = _round8(min(block_k, s_kv))

    def merge(x):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    out3 = _flash3(
        merge(q), merge(k), merge(v), float(scale), bool(causal),
        int(block_q), int(block_k), bool(interpret),
    )
    return jnp.moveaxis(out3.reshape(b, h, s, d), 1, 2)


def _round8(n: int) -> int:
    return max(8, ((n + 7) // 8) * 8)
