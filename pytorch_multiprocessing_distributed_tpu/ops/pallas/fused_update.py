"""Single-pass fused SGD(momentum, nesterov, weight-decay) update kernel.

The optimizer update is pure HBM-bandwidth work: per parameter element it
reads (param, grad, buf) and writes (param, buf). This kernel does the
whole torch-exact update rule (``..train.optim`` docstring,
reference ``main.py:51-55``) in ONE pass with the outputs aliased onto
the inputs — params and momentum buffers are updated in place in HBM,
nothing else is allocated. XLA usually fuses the elementwise chain too;
the kernel makes the schedule explicit, guarantees 3-reads/2-writes, and
is the template for fancier fused updates (LAMB phase-2, EMA).

Exact rule (matching :func:`..train.optim.sgd`):
  g    = grad + wd * param
  buf  = init * momentum * buf + g      (init = 0.0 on the first step)
  d    = g + momentum * buf  (nesterov) | buf (classical)
  param -= lr * d
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_BLOCK_ROWS = 1024  # 1024x128 f32 = 512 KiB per operand block in VMEM


def _kernel(scalars_ref, p_ref, g_ref, b_ref, new_p_ref, new_b_ref, *,
            momentum, weight_decay, nesterov):
    lr = scalars_ref[0]
    init = scalars_ref[1]  # 0.0 first step (torch lazy buf init), else 1.0
    p = p_ref[:]
    g = g_ref[:] + weight_decay * p
    buf = init * momentum * b_ref[:] + g
    d = g + momentum * buf if nesterov else buf
    new_p_ref[:] = p - lr * d
    new_b_ref[:] = buf


def _fused_leaf(p, g, buf, scalars, *, momentum, weight_decay, nesterov,
                interpret):
    """Apply the kernel to one flattened/padded [rows, 128] leaf."""
    orig_shape, orig_dtype = p.shape, p.dtype
    n = p.size
    rows = -(-n // _LANE)
    pad = rows * _LANE - n

    def prep(x):
        flat = x.astype(jnp.float32).reshape(-1)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(rows, _LANE)

    p2, g2, b2 = prep(p), prep(g), prep(buf)
    block_rows = min(_BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_rows),)
    kernel = functools.partial(
        _kernel, momentum=momentum, weight_decay=weight_decay,
        nesterov=nesterov,
    )
    spec = pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    new_p, new_b = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # (lr, init) scalars
            spec, spec, spec,
        ],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        ],
        input_output_aliases={1: 0, 3: 1},  # param->new_param, buf->new_buf
        interpret=interpret,
    )(scalars, p2, g2, b2)

    def unprep(x):
        return x.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)

    return unprep(new_p), unprep(new_b)


def fused_sgd_apply(
    params: Any,
    grads: Any,
    momentum_bufs: Any,
    lr,
    *,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    nesterov: bool = True,
    initialized=True,
    interpret: Optional[bool] = None,
):
    """In-place-fused SGD over a whole parameter pytree.

    Returns ``(new_params, new_momentum_bufs)``. ``lr`` and
    ``initialized`` may be traced scalars (schedule / first-step flag).
    """
    if interpret is None:
        from . import default_interpret

        interpret = default_interpret()
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(initialized, jnp.float32),
    ])
    leaf = functools.partial(
        _fused_leaf, scalars=scalars, momentum=momentum,
        weight_decay=weight_decay, nesterov=nesterov, interpret=interpret,
    )
    pairs = jax.tree.map(leaf, params, grads, momentum_bufs)
    is_pair = lambda t: isinstance(t, tuple) and len(t) == 2  # noqa: E731
    new_params = jax.tree.map(lambda t: t[0], pairs, is_leaf=is_pair)
    new_bufs = jax.tree.map(lambda t: t[1], pairs, is_leaf=is_pair)
    return new_params, new_bufs


def sgd_pallas(
    learning_rate=0.1,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    nesterov: bool = True,
    interpret: Optional[bool] = None,
):
    """Drop-in :class:`..train.optim.Transform` whose update runs the
    fused kernel. Same trajectory as :func:`..train.optim.sgd` (pinned by
    ``tests/test_pallas_kernels.py``)."""
    from ...train.optim import OptState, Transform

    def init(params) -> OptState:
        zeros = jax.tree.map(jnp.zeros_like, params)
        return OptState(
            momentum=zeros,
            count=jnp.zeros((), jnp.int32),
            initialized=jnp.zeros((), jnp.bool_),
        )

    def apply(grads, state: OptState, params, lr_step=None):
        """Fused path: returns (new_params, new_state) directly."""
        lr = (
            learning_rate(lr_step) if callable(learning_rate)
            else jnp.asarray(learning_rate, jnp.float32)
        )
        new_params, new_bufs = fused_sgd_apply(
            params, grads, state.momentum, lr,
            momentum=momentum, weight_decay=weight_decay,
            nesterov=nesterov,
            initialized=state.initialized.astype(jnp.float32),
            interpret=interpret,
        )
        new_state = OptState(
            momentum=new_bufs,
            count=state.count + 1,
            initialized=jnp.ones((), jnp.bool_),
        )
        return new_params, new_state

    def update(grads, state: OptState, params, lr_step=None):
        """updates-contract shim (adds one extra param pass vs ``apply``)."""
        new_params, new_state = apply(grads, state, params, lr_step=lr_step)
        updates = jax.tree.map(lambda np_, p: np_ - p, new_params, params)
        return updates, new_state

    return Transform(init, update, apply)
