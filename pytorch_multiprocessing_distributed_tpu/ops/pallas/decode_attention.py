"""Flash-decode attention: one cached step over a KV window, fused.

The serving engine's decode step is the textbook bandwidth-bound
workload: ONE query token per slot attending over every cached column.
XLA's dot+softmax+dot materializes the ``[B, H, 1, S]`` logit row in
HBM twice (once for the softmax read-back, once for the PV matmul);
this kernel streams K/V blocks through VMEM exactly once, folding each
block into an online-softmax recurrence (running max / denominator /
unnormalized accumulator — the same recurrence as
:mod:`.flash_attention`, degenerate q-block of 1), so HBM traffic is
the single K/V read the step fundamentally owes.

Per-slot positions ride in SMEM: block ``kb`` is folded only when
``kb * block_k <= position`` — a slot at position p pays for
``ceil((p+1)/block_k)`` blocks, not ``S/block_k``, which is what makes
the engine's length-bucketed window *and* this kernel compose (the
bucket bounds the grid, the position gate bounds the work inside it).

Matmuls stay in the input dtype (bf16 hits the MXU's native rate),
accumulation is f32, outputs are f32 (the engine casts back to model
dtype after the residual add, matching the XLA path's dtypes exactly).

**graftquant**: every kernel (and every XLA reference) also takes the
KV operand as a :class:`...kv_quant.QuantizedKV` pair — int8 data plus
a per-(token, head) f32 scale streamed beside it (dense: a ``[B*H,
S]`` row per block; paged: the ``[ps]`` sidecar of the SAME page the
scalar-prefetched table steers in). The dequant is ONE multiply in the
VMEM stream, applied before the existing MXU dot — so the decode step's
dominant HBM bytes term (the K/V read) halves while the matmul dtype
and f32 accumulation stay exactly as above. The XLA fallbacks dequant
with the identical expression before the reference einsum, so CPU
tier-1 pins the exact math the TPU kernel runs.

``impl="xla"`` is the reference fallback — the exact einsum/softmax
math the engine shipped with (and ``inference.generate`` still uses),
kept here so both paths live side by side and the equivalence test has
a single seam. CPU tier-1 exercises the kernel via Pallas interpret
mode (auto-selected off-TPU, same convention as every kernel in this
package).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..kv_quant import QuantizedKV, dequantize_kv
from .flash_attention import NEG_INF

__all__ = ["decode_attention", "paged_decode_attention",
           "verify_decode_attention", "paged_verify_decode_attention",
           "xla_decode_attention", "xla_paged_decode_attention",
           "xla_verify_decode_attention",
           "xla_paged_verify_decode_attention"]


def _kernel_dequant(blk, scale_row, dtype):
    """graftquant's ONE in-kernel dequant expression: int8 lanes times
    the per-(token, head) f32 scale, cast to the MXU compute dtype —
    the same math as :func:`...kv_quant.dequantize_kv`, so the XLA
    fallbacks pin exactly what the kernel streams."""
    return (blk.astype(jnp.float32)
            * scale_row[..., None]).astype(dtype)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, scale, block_k,
                   quant):
    """One (slot*head, k-block) grid cell; k is the innermost axis so
    the softmax state lives in VMEM scratch across the K/V stream.
    ``quant`` (static) inserts two scale refs after v_ref and dequants
    each K/V block in the VMEM stream before the dot."""
    if quant:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    kb = pl.program_id(1)
    n_k = pl.num_programs(1)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[0]

    # whole block beyond the slot's position -> nothing to fold (this,
    # not the grid, is what makes cost track each slot's true length)
    @pl.when(kb * block_k <= pos)
    def _():
        q = q_ref[0]          # [1, d]
        kblk = k_ref[0]       # [bk, d]
        vblk = v_ref[0]
        if quant:
            kblk = _kernel_dequant(kblk, ks_ref[0], q.dtype)
            vblk = _kernel_dequant(vblk, vs_ref[0], q.dtype)
        s = jnp.dot(q, kblk.T,
                    preferred_element_type=jnp.float32) * scale  # [1, bk]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(col <= pos, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = acc[:] / jnp.maximum(l_scr[:], 1e-30)


def _pallas_decode(q, k, v, positions, scale, block_k, interpret,
                   k_scale=None, v_scale=None):
    """q [B, 1, H, Dh]; k/v [B, S, H, Dh]; positions [B] -> f32
    [B, 1, H, Dh]. Heads merge into the grid's batch axis (one
    (slot, head) pair per row program), K/V stream blockwise.
    graftquant: with ``k_scale``/``v_scale`` (``[B, S, H]`` f32) the
    K/V operands are int8 and each block dequants in VMEM."""
    b, _, h, d = q.shape
    s = k.shape[1]
    quant = k_scale is not None
    block_k = max(8, min(block_k, ((s + 7) // 8) * 8))
    pad = (-s) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if quant:
            k_scale = jnp.pad(k_scale, ((0, 0), (0, pad), (0, 0)))
            v_scale = jnp.pad(v_scale, ((0, 0), (0, pad), (0, 0)))
    n_k = k.shape[1] // block_k

    def merge(x):  # [B, S, H, Dh] -> [B*H, S, Dh]
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    def merge_scale(x):  # [B, S, H] -> [B*H, S]
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1])

    q3 = merge(q)                      # [B*H, 1, Dh]
    k3, v3 = merge(k), merge(v)
    # one position scalar per (slot, head) row program
    pos_bh = jnp.repeat(positions.astype(jnp.int32), h)

    in_specs = [
        pl.BlockSpec((1,), lambda i, kb: (i,),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 1, d), lambda i, kb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [pos_bh, q3, k3, v3]
    if quant:
        in_specs += [
            pl.BlockSpec((1, block_k), lambda i, kb: (i, kb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda i, kb: (i, kb),
                         memory_space=pltpu.VMEM),
        ]
        operands += [merge_scale(k_scale), merge_scale(v_scale)]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_k=block_k,
                          quant=quant),
        grid=(b * h, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d), lambda i, kb: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),   # output accumulator
            pltpu.VMEM((1, 1), jnp.float32),   # running max
            pltpu.VMEM((1, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(*operands)
    return jnp.moveaxis(out.reshape(b, h, 1, d), 1, 2)  # [B, 1, H, Dh]


def _paged_decode_kernel(pos_ref, tab_ref, q_ref, k_ref, v_ref, *rest,
                         scale, page_size, heads, quant):
    """One (slot*head, page) grid cell of the PAGED flash-decode: the
    same online-softmax recurrence as :func:`_decode_kernel`, but the
    K/V block for step ``kb`` is whatever PAGE the scalar-prefetched
    table maps column-block ``kb`` to — the index map does the
    indirection BEFORE the DMA, so the stream through VMEM is still
    one pass over exactly the pages the slot owns (never a gathered
    contiguous copy in HBM). ``quant`` (static) inserts the two scale
    sidecars, steered by the SAME table indirection."""
    if quant:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    i = pl.program_id(0)
    kb = pl.program_id(1)
    n_k = pl.num_programs(1)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[i // heads]

    # page entirely beyond the slot's position -> skip (same per-slot
    # cost gate as the dense kernel's block gate; unallocated table
    # entries point at the scratch page, whose values this gate and
    # the column mask keep out of the softmax)
    @pl.when(kb * page_size <= pos)
    def _():
        q = q_ref[0]             # [1, d]
        kblk = k_ref[0, 0]       # [ps, d]
        vblk = v_ref[0, 0]
        if quant:
            kblk = _kernel_dequant(kblk, ks_ref[0, 0], q.dtype)
            vblk = _kernel_dequant(vblk, vs_ref[0, 0], q.dtype)
        s = jnp.dot(q, kblk.T,
                    preferred_element_type=jnp.float32) * scale
        col = kb * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(col <= pos, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = acc[:] / jnp.maximum(l_scr[:], 1e-30)


def _pallas_paged_decode(q, k_pages, v_pages, page_table, positions,
                         scale, interpret, k_scale=None, v_scale=None):
    """q [B, 1, H, Dh]; k/v pages [P, H, ps, Dh]; page_table
    [B, n_win] int32; positions [B] -> f32 [B, 1, H, Dh]. Grid is
    (slot*head, page); the table rides in SMEM via scalar prefetch and
    steers each page block's DMA. graftquant: ``k_scale``/``v_scale``
    (``[P, H, ps]`` f32) ride the same indirection as their pages."""
    b, _, h, d = q.shape
    ps = k_pages.shape[2]
    n_win = page_table.shape[1]
    quant = k_scale is not None
    q3 = jnp.moveaxis(q, 2, 1).reshape(b * h, 1, d)  # [B*H, 1, Dh]

    in_specs = [
        pl.BlockSpec((1, 1, d),
                     lambda i, kb, pos, tab: (i, 0, 0)),
        pl.BlockSpec((1, 1, ps, d),
                     lambda i, kb, pos, tab:
                     (tab[i // h, kb], i % h, 0, 0)),
        pl.BlockSpec((1, 1, ps, d),
                     lambda i, kb, pos, tab:
                     (tab[i // h, kb], i % h, 0, 0)),
    ]
    operands = [q3, k_pages, v_pages]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, ps),
                         lambda i, kb, pos, tab:
                         (tab[i // h, kb], i % h, 0)),
            pl.BlockSpec((1, 1, ps),
                         lambda i, kb, pos, tab:
                         (tab[i // h, kb], i % h, 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # positions, page table
        grid=(b * h, n_win),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda i, kb, pos, tab: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),   # output accumulator
            pltpu.VMEM((1, 1), jnp.float32),   # running max
            pltpu.VMEM((1, 1), jnp.float32),   # running denominator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale,
                          page_size=ps, heads=h, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), jnp.float32),
        interpret=interpret,
    )(positions.astype(jnp.int32), page_table.astype(jnp.int32),
      *operands)
    return jnp.moveaxis(out.reshape(b, h, 1, d), 1, 2)  # [B, 1, H, Dh]


def _gather_paged_window(pages, page_table, q_dtype,
                         window: Optional[int] = None):
    """``take``-gather windowed pages into the contiguous
    ``[B, W, H, Dh]`` view the dense references consume. graftquant
    pages gather BOTH leaves through the same table, then dequant with
    the kernel's exact expression — per-element identical to the
    in-VMEM dequant, which is what keeps the XLA fallback the pin."""
    b, n_win = page_table.shape
    if isinstance(pages, QuantizedKV):
        h, ps, d = pages.shape[1], pages.shape[2], pages.shape[3]
        gd = jnp.take(pages.data, page_table, axis=0)
        gd = jnp.moveaxis(gd, 3, 2).reshape(b, n_win * ps, h, d)
        gs = jnp.take(pages.scale, page_table, axis=0)
        gs = jnp.moveaxis(gs, 3, 2).reshape(b, n_win * ps, h)
        g = dequantize_kv(QuantizedKV(gd, gs), q_dtype)
    else:
        h, ps, d = pages.shape[1], pages.shape[2], pages.shape[3]
        g = jnp.take(pages, page_table, axis=0)  # [B, n_win, H, ps, Dh]
        g = jnp.moveaxis(g, 3, 2).reshape(b, n_win * ps, h, d)
    if window is not None and window < n_win * ps:
        g = jax.lax.slice_in_dim(g, 0, window, axis=1)
    return g


def xla_paged_decode_attention(q, k_pages, v_pages, page_table,
                               positions, window: Optional[int] = None):
    """Reference paged path: ``take``-gather the windowed pages into
    the contiguous ``[B, W, H, Dh]`` view and run the EXACT dense
    reference math (:func:`xla_decode_attention`) — bit-identical to
    the dense-slot engine on the same logical columns, which is the
    seam the paged==dense equivalence pin rests on. Quantized pages
    dequant at the gather (the kernel's exact per-element math)."""
    k_win = _gather_paged_window(k_pages, page_table, q.dtype, window)
    v_win = _gather_paged_window(v_pages, page_table, q.dtype, window)
    mask = (jnp.arange(k_win.shape[1])[None, :] <= positions[:, None])
    return xla_decode_attention(q, k_win, v_win, mask)


def paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    positions: jax.Array,
    *,
    window: Optional[int] = None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-step cached attention through a page table (graftpage).

    Args:
      q: ``[B, 1, H, Dh]`` — one pending query token per slot.
      k_pages, v_pages: ``[P, H, page_size, Dh]`` page storage (ONE
        layer's pages — heads before the column offset so the Pallas
        block's trailing dims are the tileable ``[page_size, Dh]``),
        or a :class:`...kv_quant.QuantizedKV` pair (int8 data + the
        ``[P, H, page_size]`` f32 scale sidecar, dequanted in-stream).
      page_table: ``[B, n_win]`` int32 — slot ``b``'s logical column
        block ``kb`` lives in page ``page_table[b, kb]``. Callers pass
        the WINDOWED slice of the full table (``ceil(window /
        page_size)`` entries); unallocated entries point at the
        scratch page 0, whose contents the position mask keeps out of
        the softmax.
      positions: ``[B]`` int — slot ``b`` attends columns
        ``[0, positions[b]]`` inclusive.
      window: optional logical column bound (< ``n_win * page_size``
        trims the gathered tail on the XLA path; the Pallas path's
        column mask makes it a no-op there).
      impl / interpret: as :func:`decode_attention`.

    Returns ``[B, 1, H, Dh]`` f32 attention output (caller casts).
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        if interpret is None:
            from . import default_interpret

            interpret = default_interpret()
        scale = q.shape[-1] ** -0.5
        if isinstance(k_pages, QuantizedKV):
            return _pallas_paged_decode(
                q, k_pages.data, v_pages.data, page_table, positions,
                scale, bool(interpret), k_scale=k_pages.scale,
                v_scale=v_pages.scale)
        return _pallas_paged_decode(q, k_pages, v_pages, page_table,
                                    positions, scale, bool(interpret))
    if impl != "xla":
        raise ValueError(
            f"impl must be 'pallas', 'xla' or 'auto', got {impl!r}")
    return xla_paged_decode_attention(q, k_pages, v_pages, page_table,
                                      positions, window)


def xla_decode_attention(q, k, v, mask):
    """The reference math (bit-identical to the engine's original
    inline einsums and ``inference.generate._block_decode``): f32
    logits, masked softmax, f32 PV. ``mask``: [B, S] key validity."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    probs = jax.nn.softmax(
        jnp.where(mask[:, None, None, :], logits, -jnp.inf), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: Optional[jax.Array] = None,
    *,
    mask: Optional[jax.Array] = None,
    impl: str = "auto",
    block_k: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-step cached attention over a KV window.

    Args:
      q: ``[B, 1, H, Dh]`` — one pending query token per slot.
      k, v: ``[B, S, H, Dh]`` KV window (the engine passes the
        length-bucketed prefix slice of its slot caches), or a
        :class:`...kv_quant.QuantizedKV` pair (int8 data + the
        ``[B, S, H]`` f32 scale sidecar, dequanted in-stream).
      positions: ``[B]`` int — slot ``b`` attends columns
        ``[0, positions[b]]`` inclusive. Required for the Pallas path;
        the XLA path derives ``mask`` from it when ``mask`` is None.
      mask: ``[B, S]`` bool key validity (XLA path only) — lets ragged
        ``generate`` compose its pad-column mask in.
      impl: ``"pallas"`` | ``"xla"`` | ``"auto"`` (pallas on real TPU,
        xla elsewhere — the serving engine overrides to exercise the
        kernel in interpret mode on CPU tests).
      block_k: K/V block streamed per grid step (pallas path).
      interpret: force Pallas interpret mode; default auto (interpret
        everywhere except real TPU).

    Returns ``[B, 1, H, Dh]`` f32 attention output (caller casts).
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        if positions is None:
            raise ValueError("the pallas decode path needs positions")
        if mask is not None:
            raise ValueError(
                "mask composes only with impl='xla' (the pallas kernel "
                "masks from positions)")
        if interpret is None:
            from . import default_interpret

            interpret = default_interpret()
        scale = q.shape[-1] ** -0.5
        if isinstance(k, QuantizedKV):
            return _pallas_decode(q, k.data, v.data, positions, scale,
                                  int(block_k), bool(interpret),
                                  k_scale=k.scale, v_scale=v.scale)
        return _pallas_decode(q, k, v, positions, scale, int(block_k),
                              bool(interpret))
    if impl != "xla":
        raise ValueError(
            f"impl must be 'pallas', 'xla' or 'auto', got {impl!r}")
    if mask is None:
        if positions is None:
            raise ValueError("xla path needs positions or mask")
        mask = (jnp.arange(k.shape[1])[None, :]
                <= positions[:, None])
    if isinstance(k, QuantizedKV):
        k, v = dequantize_kv(k, q.dtype), dequantize_kv(v, q.dtype)
    return xla_decode_attention(q, k, v, mask)


# ------------------------------------------------------------- graftspec
#
# k-query VERIFY attention: the speculative-decode verify pass runs
# k+1 query tokens per slot (the pending token + k drafts) against the
# same cached columns one decode step reads, in ONE batched pass —
# more MXU rows over the SAME K/V stream, which is the whole
# bandwidth-bound argument for speculation (the committed costs.json
# budgets pin verify bytes ~ decode bytes at (k+1)x the query FLOPs).
# Query row i sits at column positions[b] + i and attends [0, pos+i]
# — after the caller's cache writes, that window includes the
# in-flight keys of the preceding draft queries, exactly the causal
# set a future single-query step would see. The XLA reference is the
# same einsum/masked-softmax math as xla_decode_attention with the
# row-staggered mask; the Pallas kernels are the flash recurrence
# with a [K1, d] query block instead of [1, d].


def _verify_kernel(pos_ref, q_ref, k_ref, v_ref, *rest, scale, block_k,
                   k1, quant):
    """One (slot*head, k-block) grid cell; the softmax state is [K1]
    rows of the same online recurrence as :func:`_decode_kernel`.
    ``quant`` (static): dequant each K/V block in-stream — the verify
    pass reads the SAME quantized pages one decode step reads, so
    spec-decode bandwidth halves with it."""
    if quant:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    kb = pl.program_id(1)
    n_k = pl.num_programs(1)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[0]

    # the block matters to SOME query row iff its first column is
    # within the last row's reach (pos + k1 - 1); per-row masking
    # below keeps earlier rows exact
    @pl.when(kb * block_k <= pos + k1 - 1)
    def _():
        q = q_ref[0]          # [K1, d]
        kblk = k_ref[0]       # [bk, d]
        vblk = v_ref[0]
        if quant:
            kblk = _kernel_dequant(kblk, ks_ref[0], q.dtype)
            vblk = _kernel_dequant(vblk, vs_ref[0], q.dtype)
        s = jnp.dot(q, kblk.T,
                    preferred_element_type=jnp.float32) * scale  # [K1, bk]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (k1, block_k), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (k1, block_k), 0)
        s = jnp.where(col <= pos + row, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = acc[:] / jnp.maximum(l_scr[:], 1e-30)


def _pallas_verify(q, k, v, positions, scale, block_k, interpret,
                   k_scale=None, v_scale=None):
    """q [B, K1, H, Dh]; k/v [B, S, H, Dh]; positions [B] -> f32
    [B, K1, H, Dh]. graftquant: ``k_scale``/``v_scale`` ([B, S, H]
    f32) mark the K/V operands int8, dequanted per block in VMEM."""
    b, k1, h, d = q.shape
    s = k.shape[1]
    quant = k_scale is not None
    block_k = max(8, min(block_k, ((s + 7) // 8) * 8))
    pad = (-s) % block_k
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if quant:
            k_scale = jnp.pad(k_scale, ((0, 0), (0, pad), (0, 0)))
            v_scale = jnp.pad(v_scale, ((0, 0), (0, pad), (0, 0)))
    n_k = k.shape[1] // block_k

    def merge(x):  # [B, S, H, Dh] -> [B*H, S, Dh]
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1], d)

    def merge_scale(x):  # [B, S, H] -> [B*H, S]
        return jnp.moveaxis(x, 2, 1).reshape(b * h, x.shape[1])

    q3 = merge(q)                      # [B*H, K1, Dh]
    k3, v3 = merge(k), merge(v)
    pos_bh = jnp.repeat(positions.astype(jnp.int32), h)

    in_specs = [
        pl.BlockSpec((1,), lambda i, kb: (i,),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((1, k1, d), lambda i, kb: (i, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, d), lambda i, kb: (i, kb, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [pos_bh, q3, k3, v3]
    if quant:
        in_specs += [
            pl.BlockSpec((1, block_k), lambda i, kb: (i, kb),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k), lambda i, kb: (i, kb),
                         memory_space=pltpu.VMEM),
        ]
        operands += [merge_scale(k_scale), merge_scale(v_scale)]

    out = pl.pallas_call(
        functools.partial(_verify_kernel, scale=scale, block_k=block_k,
                          k1=k1, quant=quant),
        grid=(b * h, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, k1, d), lambda i, kb: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, k1, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((k1, d), jnp.float32),   # output accumulator
            pltpu.VMEM((k1, 1), jnp.float32),   # running max
            pltpu.VMEM((k1, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(*operands)
    return jnp.moveaxis(out.reshape(b, h, k1, d), 1, 2)  # [B, K1, H, Dh]


def _paged_verify_kernel(pos_ref, tab_ref, q_ref, k_ref, v_ref, *rest,
                         scale, page_size, heads, k1, quant):
    """Paged k-query verify: :func:`_paged_decode_kernel`'s
    scalar-prefetched page indirection with the [K1, d] query block
    and the row-staggered column mask. ``quant`` (static): the scale
    sidecars ride the same table indirection."""
    if quant:
        ks_ref, vs_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, acc, m_scr, l_scr = rest
    i = pl.program_id(0)
    kb = pl.program_id(1)
    n_k = pl.num_programs(1)

    @pl.when(kb == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[i // heads]

    @pl.when(kb * page_size <= pos + k1 - 1)
    def _():
        q = q_ref[0]             # [K1, d]
        kblk = k_ref[0, 0]       # [ps, d]
        vblk = v_ref[0, 0]
        if quant:
            kblk = _kernel_dequant(kblk, ks_ref[0, 0], q.dtype)
            vblk = _kernel_dequant(vblk, vs_ref[0, 0], q.dtype)
        s = jnp.dot(q, kblk.T,
                    preferred_element_type=jnp.float32) * scale
        col = kb * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (k1, page_size), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (k1, page_size), 0)
        s = jnp.where(col <= pos + row, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jnp.dot(
            p.astype(vblk.dtype), vblk,
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_k - 1)
    def _():
        o_ref[0] = acc[:] / jnp.maximum(l_scr[:], 1e-30)


def _pallas_paged_verify(q, k_pages, v_pages, page_table, positions,
                         scale, interpret, k_scale=None, v_scale=None):
    """q [B, K1, H, Dh]; pages [P, H, ps, Dh]; page_table [B, n_win]
    -> f32 [B, K1, H, Dh]. graftquant: ``k_scale``/``v_scale``
    ([P, H, ps] f32) ride the same indirection as their pages."""
    b, k1, h, d = q.shape
    ps = k_pages.shape[2]
    n_win = page_table.shape[1]
    quant = k_scale is not None
    q3 = jnp.moveaxis(q, 2, 1).reshape(b * h, k1, d)

    in_specs = [
        pl.BlockSpec((1, k1, d),
                     lambda i, kb, pos, tab: (i, 0, 0)),
        pl.BlockSpec((1, 1, ps, d),
                     lambda i, kb, pos, tab:
                     (tab[i // h, kb], i % h, 0, 0)),
        pl.BlockSpec((1, 1, ps, d),
                     lambda i, kb, pos, tab:
                     (tab[i // h, kb], i % h, 0, 0)),
    ]
    operands = [q3, k_pages, v_pages]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, ps),
                         lambda i, kb, pos, tab:
                         (tab[i // h, kb], i % h, 0)),
            pl.BlockSpec((1, 1, ps),
                         lambda i, kb, pos, tab:
                         (tab[i // h, kb], i % h, 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # positions, page table
        grid=(b * h, n_win),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, k1, d),
                               lambda i, kb, pos, tab: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((k1, d), jnp.float32),   # output accumulator
            pltpu.VMEM((k1, 1), jnp.float32),   # running max
            pltpu.VMEM((k1, 1), jnp.float32),   # running denominator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_verify_kernel, scale=scale,
                          page_size=ps, heads=h, k1=k1, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, k1, d), jnp.float32),
        interpret=interpret,
    )(positions.astype(jnp.int32), page_table.astype(jnp.int32),
      *operands)
    return jnp.moveaxis(out.reshape(b, h, k1, d), 1, 2)


def xla_verify_decode_attention(q, k, v, positions):
    """Reference k-query verify math: xla_decode_attention's exact
    einsum/masked-softmax shape with the row-staggered mask — query
    row ``i`` attends columns ``[0, positions[b] + i]`` inclusive.
    K1=1 degenerates to the single-query reference bit-for-bit."""
    scale = q.shape[-1] ** -0.5
    k1 = q.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    mask = (jnp.arange(k.shape[1])[None, None, :]
            <= positions[:, None, None]
            + jnp.arange(k1)[None, :, None])          # [B, K1, S]
    probs = jax.nn.softmax(
        jnp.where(mask[:, None], logits, -jnp.inf), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))


def xla_paged_verify_decode_attention(q, k_pages, v_pages, page_table,
                                      positions,
                                      window: Optional[int] = None):
    """Paged reference verify: the same take-gather (+ graftquant
    dequant) as :func:`xla_paged_decode_attention`, then the dense
    reference."""
    k_win = _gather_paged_window(k_pages, page_table, q.dtype, window)
    v_win = _gather_paged_window(v_pages, page_table, q.dtype, window)
    return xla_verify_decode_attention(q, k_win, v_win, positions)


def verify_decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: jax.Array,
    *,
    impl: str = "auto",
    block_k: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Speculative-verify attention: ``K1 = k_draft + 1`` query tokens
    per slot over one KV window.

    Args:
      q: ``[B, K1, H, Dh]`` — row ``i`` is the query at column
        ``positions[b] + i`` (the pending token, then the k drafts).
      k, v: ``[B, S, H, Dh]`` KV window (the caller has already
        written the K1 in-flight columns, so row ``i`` sees its
        predecessors' keys — the causal verify set). May be
        :class:`...ops.kv_quant.QuantizedKV` (graftquant int8 +
        scale) — dequantized in the kernel's VMEM stream.
      positions: ``[B]`` int — row ``i`` attends ``[0, positions[b]
        + i]`` inclusive.
      impl / block_k / interpret: as :func:`decode_attention`.

    Returns ``[B, K1, H, Dh]`` f32 (caller casts)."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        if interpret is None:
            from . import default_interpret

            interpret = default_interpret()
        scale = q.shape[-1] ** -0.5
        if isinstance(k, QuantizedKV):
            return _pallas_verify(q, k.data, v.data, positions, scale,
                                  int(block_k), bool(interpret),
                                  k_scale=k.scale, v_scale=v.scale)
        return _pallas_verify(q, k, v, positions, scale, int(block_k),
                              bool(interpret))
    if impl != "xla":
        raise ValueError(
            f"impl must be 'pallas', 'xla' or 'auto', got {impl!r}")
    if isinstance(k, QuantizedKV):
        k = dequantize_kv(k, q.dtype)
        v = dequantize_kv(v, q.dtype)
    return xla_verify_decode_attention(q, k, v, positions)


def paged_verify_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    positions: jax.Array,
    *,
    window: Optional[int] = None,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Paged twin of :func:`verify_decode_attention` (graftspec x
    graftpage): the k-query verify reads KV through the same windowed
    page-table slice the single-query paged step uses. Pages may be
    :class:`...ops.kv_quant.QuantizedKV` (graftquant)."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        if interpret is None:
            from . import default_interpret

            interpret = default_interpret()
        scale = q.shape[-1] ** -0.5
        if isinstance(k_pages, QuantizedKV):
            return _pallas_paged_verify(
                q, k_pages.data, v_pages.data, page_table, positions,
                scale, bool(interpret),
                k_scale=k_pages.scale, v_scale=v_pages.scale)
        return _pallas_paged_verify(q, k_pages, v_pages, page_table,
                                    positions, scale, bool(interpret))
    if impl != "xla":
        raise ValueError(
            f"impl must be 'pallas', 'xla' or 'auto', got {impl!r}")
    return xla_paged_verify_decode_attention(q, k_pages, v_pages,
                                             page_table, positions,
                                             window)
