"""RDMA ring all-reduce as a Pallas TPU kernel — the NCCL-analogue demo.

The production gradient all-reduce is ``lax.psum`` (XLA already emits
bandwidth-optimal ICI rings for it — :mod:`..parallel.collectives`).
This kernel exists because SURVEY.md §2.2 names a hand-built collective
layer as part of the reference's implicit native stack (NCCL), and
because a visible, steppable ring is the right vehicle for benchmarking
ICI against XLA's lowering (``benchmarks/allreduce_bw.py``).

Algorithm (classic two-phase ring, 2·(n-1)/n · bytes over the wire):
  1. reduce-scatter: n-1 hops; at hop t rank r sends chunk (r - t) mod n
     rightward and accumulates incoming chunk (r - t - 1) mod n, so after
     the phase rank r holds the fully-reduced chunk (r + 1) mod n;
  2. all-gather: n-1 hops circulating the finished chunks.

Each hop is one ``make_async_remote_copy`` into the right neighbor's
double-buffered landing slot. Flow control is NCCL-style credit-based:
a receiver acks each consumed delivery back to its sender (left
neighbor), and a sender re-using a landing slot first waits for the ack
of its previous delivery into that slot — so a fast rank can never
overwrite data its neighbor has not yet consumed, regardless of ring
skew. An entry barrier keeps a rank from RDMA-ing into a kernel its
neighbor hasn't entered; a final drain rebalances the credit semaphores
to zero before exit.

Call inside ``shard_map`` with the target axis bound. Runs compiled on a
real multi-chip ICI ring; runs under Pallas interpret mode on the
virtualized CPU mesh (the test path, ``tests/test_pallas_kernels.py``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...utils.compat import axis_size


def _compiler_params(collective_id: int):
    """Mosaic compiler params across the TPUCompilerParams ->
    CompilerParams rename, passing only the fields this jax knows
    (``has_side_effects`` predates some 0.4.x builds; without it the
    test-visible semantics are unchanged — the output is consumed, so
    the RDMA ops are not DCE'd)."""
    import dataclasses

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    fields = {f.name for f in dataclasses.fields(cls)}
    kw = {"collective_id": collective_id}
    if "has_side_effects" in fields:
        kw["has_side_effects"] = True
    return cls(**kw)

_LANE = 128


def _ring_kernel(x_ref, o_ref, comm, send_sem, recv_sem, ack_sem, *,
                 axis_name, flow_control):
    """``flow_control=False`` only under interpret mode, whose lockstep
    execution makes the barrier/credit protocol unnecessary (and remote
    ``semaphore_signal`` is not implemented there)."""
    my = jax.lax.axis_index(axis_name)
    n = axis_size(axis_name)
    right = jax.lax.rem(my + 1, n)
    left = jax.lax.rem(my + n - 1, n)
    chunk = x_ref.shape[0] // n  # rows per chunk (pre-padded by caller)

    if flow_control:
        # Entry barrier: both neighbors' buffers exist before any RDMA.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

    o_ref[:] = x_ref[:]

    def hop(g, send_idx, recv_idx, accumulate):
        """One ring hop at global step ``g`` (slot parity g % 2)."""
        slot = jax.lax.rem(g, 2)

        if flow_control:
            # Credit: my previous delivery into right's comm[slot] (hop
            # g-2) must be consumed before I overwrite it.
            @pl.when(g >= 2)
            def _():
                pltpu.semaphore_wait(ack_sem.at[slot], 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[pl.ds(send_idx * chunk, chunk), :],
            dst_ref=comm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()  # my send delivered + left's symmetric delivery arrived

        if accumulate:
            o_ref[pl.ds(recv_idx * chunk, chunk), :] = (
                o_ref[pl.ds(recv_idx * chunk, chunk), :] + comm[slot]
            )
        else:
            o_ref[pl.ds(recv_idx * chunk, chunk), :] = comm[slot]

        if flow_control:
            # Consumed — return the credit to the sender (left neighbor).
            pltpu.semaphore_signal(
                ack_sem.at[slot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

    # Phase 1 — reduce-scatter.
    def rs_body(t, _):
        hop(
            t,
            jax.lax.rem(my - t + 2 * n, n),
            jax.lax.rem(my - t - 1 + 2 * n, n),
            accumulate=True,
        )
        return 0

    jax.lax.fori_loop(0, n - 1, rs_body, 0)

    # Phase 2 — all-gather: rank r owns reduced chunk (r + 1) mod n.
    def ag_body(t, _):
        hop(
            n - 1 + t,  # global step: slot parity continues across phases
            jax.lax.rem(my + 1 - t + 2 * n, n),
            jax.lax.rem(my - t + 2 * n, n),
            accumulate=False,
        )
        return 0

    jax.lax.fori_loop(0, n - 1, ag_body, 0)

    if flow_control:
        # Drain: the final delivery on each slot was acked by my right but
        # never waited on — consume both so the semaphores exit at zero.
        # (2·(n-1) >= 2 hops for n >= 2, so both slots saw >= 1 send.)
        pltpu.semaphore_wait(ack_sem.at[0], 1)
        pltpu.semaphore_wait(ack_sem.at[1], 1)


def ring_all_reduce(
    x: jax.Array,
    axis_name: str,
    *,
    interpret: Optional[bool] = None,
    collective_id: int = 7,
) -> jax.Array:
    """Sum-all-reduce ``x`` over ``axis_name`` via an explicit RDMA ring.

    Must be called inside ``shard_map``/``pmap`` with ``axis_name``
    bound. Semantically identical to ``jax.lax.psum(x, axis_name)``.
    """
    if interpret is None:
        from . import default_interpret

        interpret = default_interpret()
    n = axis_size(axis_name)
    if n == 1:
        return x

    orig_shape, orig_dtype = x.shape, x.dtype
    size = math.prod(orig_shape) if orig_shape else 1
    flat = x.astype(jnp.float32).reshape(-1)
    # rows must split into n equal chunks of whole (8, 128)-tile rows
    rows = -(-flat.size // _LANE)
    rows = -(-rows // (8 * n)) * (8 * n)
    pad = rows * _LANE - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(rows, _LANE)
    chunk = rows // n

    kernel = functools.partial(
        _ring_kernel, axis_name=axis_name, flow_control=not interpret
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, _LANE), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=_compiler_params(collective_id),
        interpret=interpret,
    )(x2)
    return out.reshape(-1)[:size].reshape(orig_shape).astype(orig_dtype)
