"""Loss functions.

``cross_entropy_loss`` is the TPU-native stand-in for the reference's
``nn.CrossEntropyLoss()`` (``main.py:48``, applied at ``main.py:105``):
softmax cross-entropy from integer labels, mean-reduced over the batch.
Computed in float32 for bf16 stability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_sample(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """``[batch]`` per-sample softmax cross-entropy with integer targets."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return logz - label_logits


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer targets.

    Args:
      logits: ``[batch, num_classes]``.
      targets: ``[batch]`` int labels.
    """
    return jnp.mean(cross_entropy_per_sample(logits, targets))
