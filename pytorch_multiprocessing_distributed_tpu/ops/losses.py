"""Loss functions.

``cross_entropy_loss`` is the TPU-native stand-in for the reference's
``nn.CrossEntropyLoss()`` (``main.py:48``, applied at ``main.py:105``):
softmax cross-entropy from integer labels, mean-reduced over the batch.
Computed in float32 for bf16 stability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_sample(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """``[batch]`` per-sample softmax cross-entropy with integer targets."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return logz - label_logits


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer targets.

    Args:
      logits: ``[batch, num_classes]``.
      targets: ``[batch]`` int labels.
    """
    return jnp.mean(cross_entropy_per_sample(logits, targets))


# eval loops need the UN-reduced form of the same criterion (for the
# validity-masked sums in train/step.py _eval_body); every mean loss in
# this module carries its per-sample companion as an attribute.
cross_entropy_loss.per_sample = cross_entropy_per_sample


def smooth_cross_entropy_loss(label_smoothing: float):
    """Factory: mean cross-entropy with label smoothing ``eps``.

    ``torch.nn.CrossEntropyLoss(label_smoothing=eps)`` semantics: the
    target distribution is ``(1-eps)`` on the label plus ``eps/C``
    uniform, so ``loss = (1-eps)*CE(label) + eps * mean_c(-log p_c)``.
    ``eps=0`` reduces exactly to :func:`cross_entropy_loss`.
    """
    eps = float(label_smoothing)
    if not 0.0 <= eps < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {eps}")
    if eps == 0.0:
        return cross_entropy_loss

    def per_sample_fn(logits: jax.Array, targets: jax.Array) -> jax.Array:
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)  # [batch]
        label_logits = jnp.take_along_axis(
            logits, targets[:, None], axis=-1
        )[:, 0]
        # mean over classes of -log p_c  ==  logz - mean_c(logit_c)
        uniform_term = logz - jnp.mean(logits, axis=-1)
        return (1.0 - eps) * (logz - label_logits) + eps * uniform_term

    def loss_fn(logits: jax.Array, targets: jax.Array) -> jax.Array:
        return jnp.mean(per_sample_fn(logits, targets))

    loss_fn.per_sample = per_sample_fn
    return loss_fn
