"""Loss functions.

``cross_entropy_loss`` is the TPU-native stand-in for the reference's
``nn.CrossEntropyLoss()`` (``main.py:48``, applied at ``main.py:105``):
softmax cross-entropy from integer labels, mean-reduced over the batch.
Computed in float32 for bf16 stability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_per_sample(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """``[batch]`` per-sample softmax cross-entropy with integer targets."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return logz - label_logits


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer targets.

    Args:
      logits: ``[batch, num_classes]``.
      targets: ``[batch]`` int labels.
    """
    return jnp.mean(cross_entropy_per_sample(logits, targets))


# eval loops need the UN-reduced form of the same criterion (for the
# validity-masked sums in train/step.py _eval_body); every mean loss in
# this module carries its per-sample companion as an attribute.
cross_entropy_loss.per_sample = cross_entropy_per_sample


def chunked_lm_ce(
    h: jax.Array,
    kernel: jax.Array,
    bias,
    targets: jax.Array,
    weights: jax.Array,
    n_chunks: int,
) -> jax.Array:
    """Next-token CE fused with the LM head, streamed over vocab chunks.

    The dense path materializes ``[B, S, V]`` f32 logits (GPT-2 small at
    8x1024: 1.6 GB) plus their softmax cotangent in the backward. Here
    the head matmul and the log-sum-exp stream over ``n_chunks`` vocab
    slices (``lax.scan``): live memory is ``O(B*S*V/n_chunks)`` while
    the result — ``sum(weights * CE)`` — is EXACTLY the dense value
    (same f32 ops, streaming max/LSE fold). The custom VJP recomputes
    each chunk's logits (flash-attention-style remat) and streams
    ``dh``/``dkernel``/``dbias`` the same way, so the full logits tensor
    never exists in either pass. The sequential analogue of the
    pipelined trainer's vocab-PARALLEL LSE loss (parallel/gpt_pipeline).

    Args:
      h: ``[B, S, D]`` final hidden states (post final-LN).
      kernel: ``[D, V]`` head weights.
      bias: ``[V]`` head bias, or None (``GPT(head_bias=False)``).
      targets: ``[B, S]`` int next-token labels.
      weights: ``[B, S]`` f32 validity weights.
      n_chunks: vocab slices to stream over (V is padded up to a
        multiple; padded slots carry -inf bias => exactly zero mass).

    Returns the scalar ``sum(weights * per_position_CE)``.
    """
    return _chunked_ce(h.astype(jnp.float32), kernel, bias, targets,
                       weights, n_chunks)


from functools import partial as _partial  # noqa: E402


@_partial(jax.custom_vjp, nondiff_argnums=(5,))
def _chunked_ce(h, kernel, bias, targets, weights, n_chunks):
    ce_sum, _res = _chunked_ce_fwd_impl(h, kernel, bias, targets, weights,
                                        n_chunks)
    return ce_sum


def _chunk_views(kernel, bias, n_chunks):
    """-> (k_chunks [n, D, Vc], b_chunks [n, Vc], vc). Pads V up to a
    multiple of n_chunks; padded slots get bias -inf (zero softmax
    mass) and kernel 0."""
    d, v = kernel.shape
    vc = -(-v // n_chunks)
    pad = n_chunks * vc - v
    kernel = jnp.pad(kernel.astype(jnp.float32), ((0, 0), (0, pad)))
    if bias is None:
        bias = jnp.zeros((v,), jnp.float32)
    bias = jnp.pad(bias.astype(jnp.float32), (0, pad),
                   constant_values=-jnp.inf)
    k_chunks = kernel.reshape(d, n_chunks, vc).transpose(1, 0, 2)
    b_chunks = bias.reshape(n_chunks, vc)
    return k_chunks, b_chunks, vc


def _chunked_ce_fwd_impl(h, kernel, bias, targets, weights, n_chunks):
    b, s, d = h.shape
    hf = h.reshape(-1, d)  # [N, D], N = B*S
    tgt = targets.reshape(-1)
    k_chunks, b_chunks, vc = _chunk_views(kernel, bias, n_chunks)

    def fold(carry, ck):
        m, sse, tlog, c = carry
        kc, bc = ck
        logits = hf @ kc + bc  # [N, Vc] f32
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        sse = sse * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # target logit if it falls in this chunk
        idx = tgt - c * vc
        mine = jnp.logical_and(idx >= 0, idx < vc)
        picked = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, vc - 1)[:, None], axis=-1
        )[:, 0]
        tlog = tlog + jnp.where(mine, picked, 0.0)
        return (m_new, sse, tlog, c + 1), None

    n = hf.shape[0]
    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((), jnp.int32))
    (m, sse, tlog, _), _ = jax.lax.scan(fold, init, (k_chunks, b_chunks))
    lse = jnp.log(sse) + m
    w = weights.reshape(-1)
    ce_pos = lse - tlog
    ce_sum = jnp.sum(ce_pos * w)
    return ce_sum, (lse, ce_pos)


def _chunked_ce_fwd(h, kernel, bias, targets, weights, n_chunks):
    # NB custom_vjp convention: fwd keeps the PRIMAL signature (the
    # nondiff arg stays in place); only bwd receives it first.
    ce_sum, (lse, ce_pos) = _chunked_ce_fwd_impl(
        h, kernel, bias, targets, weights, n_chunks)
    return ce_sum, (h, kernel, bias, targets, weights, lse, ce_pos)


def _chunked_ce_bwd(n_chunks, res, g):
    import numpy as np

    h, kernel, bias, targets, weights, lse, ce_pos = res
    b, s, d = h.shape
    hf = h.reshape(-1, d)
    tgt = targets.reshape(-1)
    gw = (g * weights.reshape(-1)).astype(jnp.float32)  # [N]
    k_chunks, b_chunks, vc = _chunk_views(kernel, bias, n_chunks)

    def fold(carry, ck):
        dh, c = carry
        kc, bc = ck
        logits = hf @ kc + bc                        # recompute [N, Vc]
        p = jnp.exp(logits - lse[:, None])           # softmax slice
        idx = tgt - c * vc
        mine = jnp.logical_and(idx >= 0, idx < vc)
        onehot = jnp.zeros_like(p).at[
            jnp.arange(p.shape[0]), jnp.clip(idx, 0, vc - 1)
        ].set(jnp.where(mine, 1.0, 0.0))
        dl = gw[:, None] * (p - onehot)              # [N, Vc]
        dh = dh + dl @ kc.T
        dkc = hf.T @ dl                              # [D, Vc]
        dbc = jnp.sum(dl, axis=0)                    # [Vc]
        return (dh, c + 1), (dkc, dbc)

    init = (jnp.zeros_like(hf), jnp.zeros((), jnp.int32))
    (dh, _), (dks, dbs) = jax.lax.scan(fold, init, (k_chunks, b_chunks))
    v = kernel.shape[1]
    dkernel = dks.transpose(1, 0, 2).reshape(d, -1)[:, :v]
    dbias = None if bias is None else dbs.reshape(-1)[:v]
    dtargets = np.zeros(targets.shape, jax.dtypes.float0)
    # d(ce_sum)/d(w) = g * per-position CE (saved from the forward)
    dweights = (g * ce_pos).reshape(weights.shape).astype(weights.dtype)
    return (dh.reshape(b, s, d).astype(h.dtype), dkernel, dbias,
            dtargets, dweights)


_chunked_ce.defvjp(_chunked_ce_fwd, _chunked_ce_bwd)


def smooth_cross_entropy_loss(label_smoothing: float):
    """Factory: mean cross-entropy with label smoothing ``eps``.

    ``torch.nn.CrossEntropyLoss(label_smoothing=eps)`` semantics: the
    target distribution is ``(1-eps)`` on the label plus ``eps/C``
    uniform, so ``loss = (1-eps)*CE(label) + eps * mean_c(-log p_c)``.
    ``eps=0`` reduces exactly to :func:`cross_entropy_loss`.
    """
    eps = float(label_smoothing)
    if not 0.0 <= eps < 1.0:
        raise ValueError(f"label_smoothing must be in [0, 1), got {eps}")
    if eps == 0.0:
        return cross_entropy_loss

    def per_sample_fn(logits: jax.Array, targets: jax.Array) -> jax.Array:
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)  # [batch]
        label_logits = jnp.take_along_axis(
            logits, targets[:, None], axis=-1
        )[:, 0]
        # mean over classes of -log p_c  ==  logz - mean_c(logit_c)
        uniform_term = logz - jnp.mean(logits, axis=-1)
        return (1.0 - eps) * (logz - label_logits) + eps * uniform_term

    def loss_fn(logits: jax.Array, targets: jax.Array) -> jax.Array:
        return jnp.mean(per_sample_fn(logits, targets))

    loss_fn.per_sample = per_sample_fn
    return loss_fn
