"""Loss functions.

``cross_entropy_loss`` is the TPU-native stand-in for the reference's
``nn.CrossEntropyLoss()`` (``main.py:48``, applied at ``main.py:105``):
softmax cross-entropy from integer labels, mean-reduced over the batch.
Computed in float32 for bf16 stability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy with integer targets.

    Args:
      logits: ``[batch, num_classes]``.
      targets: ``[batch]`` int labels.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - label_logits)
