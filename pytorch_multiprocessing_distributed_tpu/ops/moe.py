"""Mixture-of-Experts MLP with expert parallelism (EP).

The reference has no MoE (SURVEY.md §2.3 marks expert parallelism
absent); this is part of the framework's scale-out surface, built the
idiomatic TPU way: the layer is written with GLOBAL semantics
(Switch-style top-1 routing with a fixed per-expert capacity so every
shape is static), the expert-indexed weight tensors carry a mesh-axis
annotation, and GSPMD partitions the dispatch/combine einsums —
lowering them to the all-to-all exchanges an NCCL MoE implementation
would hand-write.

Routing (Switch Transformer top-1 by default; ``top_k >= 2`` switches
to GShard-style renormalized top-k with choice-priority capacity):
  gates  = softmax(x @ Wg)                      [B, S, E]
  expert = top_k(gates) choices                 [B, S, K]
  slot   = position of each (token, choice) within its expert's
           capacity C = ceil(S * K * capacity_factor / E); choice j
           claims slots only after every choice < j; assignments past
           capacity are DROPPED (output 0 — the residual carries them)
  dispatch[b, s, e, c] = 1 iff some choice of token (b, s) is slot c
           of expert e
  h = expert_mlp_e(dispatch^T x)                [E, B, C, D] (vmapped)
  y[b, s] = sum_j weight_j * h[expert_j, b, slot_j]
           (weight = raw top prob for K=1, renormalized top-K else)

Under ``shard_expert_params`` + a mesh, each device stores E/ep of the
expert weights and computes only its experts' FLOPs.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from ..utils.compat import get_abstract_mesh


class MoEMlp(nn.Module):
    """Switch-style top-1 MoE feed-forward block.

    Attributes:
      n_experts: number of expert MLPs (E).
      d_hidden: expert hidden width.
      capacity_factor: per-expert capacity = ceil(S * factor / E).
      expert_axis: optional mesh axis name baked into a
        ``with_sharding_constraint`` on the expert-indexed activations
        (use together with :func:`shard_expert_params`); ``None`` runs
        unconstrained (single device / tests).
      dtype: compute dtype (params stay f32).
      top_k: experts per token. 1 = Switch (combine weight is the RAW
        top softmax probability); >= 2 = GShard-style (weights are the
        top-k probabilities renormalized to sum to 1; choice ``j``
        claims capacity slots only after every choice ``< j`` — a
        token's secondary expert drops before anyone's primary does).
    """

    n_experts: int
    d_hidden: int
    capacity_factor: float = 1.0
    expert_axis: Optional[str] = None
    dtype: Any = None
    top_k: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if not 1 <= self.top_k <= self.n_experts:
            raise ValueError(
                f"top_k must be in [1, n_experts={self.n_experts}], "
                f"got {self.top_k}"
            )
        b, s, d = x.shape
        e = self.n_experts
        # capacity scales with top_k: k assignments per token compete
        # for the same expert slots (GShard sizes top-2 at 2S/E)
        cap = max(
            1, int(-(-s * self.top_k * self.capacity_factor // e))
        )
        dtype = self.dtype or x.dtype

        wg = self.param("gate", nn.initializers.lecun_normal(), (d, e),
                        jnp.float32)
        w1 = self.param(
            "w1", nn.initializers.lecun_normal(), (e, d, self.d_hidden),
            jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, self.d_hidden),
                        jnp.float32)
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(), (e, self.d_hidden, d),
            jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)

        k = self.top_k
        router_logits = x.astype(jnp.float32) @ wg  # [B, S, E]
        gates = jax.nn.softmax(
            router_logits, axis=-1
        )  # [B, S, E] — routing math in f32 always
        topv, topi = jax.lax.top_k(gates, k)  # [B, S, K]
        if k == 1:
            weights = topv  # Switch: the raw top probability
        else:
            # GShard: renormalize over the selected experts
            weights = topv / jnp.sum(topv, axis=-1, keepdims=True)
        onehots = jax.nn.one_hot(topi, e, dtype=jnp.float32)  # [B, S, K, E]

        # Load-balancing auxiliary loss (Switch Transformer): E * <f, p>
        # where f_e = fraction of tokens whose PRIMARY choice is expert
        # e (hard, pre-capacity — also the GShard convention for top-2)
        # and p_e = mean router probability of expert e. Minimized
        # (= 1.0) at uniform routing; without it routing collapses onto
        # a few experts in real training. Differentiable through p only
        # (f is argmax-hard), which is exactly the Switch formulation.
        # Sown under the "losses" collection — training steps read it
        # via ``mutable=["losses"]`` and add ``weight * aux``;
        # eval/apply without mutable discards it.
        f = jnp.mean(onehots[:, :, 0, :].reshape(-1, e), axis=0)  # [E]
        p = jnp.mean(gates.reshape(-1, e), axis=0)  # [E]
        self.sow("losses", "moe_aux", e * jnp.sum(f * p))
        # Router z-loss (ST-MoE): mean logsumexp(logits)^2 keeps router
        # logits small/stable in bf16 training.
        self.sow(
            "losses", "moe_z",
            jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2),
        )

        # Per-choice capacity slots: choice j's tokens claim an
        # expert's slots only after every choice < j (sequence order
        # within a choice), so a secondary assignment can never evict a
        # primary one. ``offset`` carries the running per-expert count.
        dispatches = []
        offset = jnp.zeros((b, 1, e), jnp.float32)
        for j in range(k):
            oh = onehots[:, :, j, :]  # [B, S, E]
            pos = (jnp.cumsum(oh, axis=1) + offset) * oh  # 1-based
            slot = (jnp.sum(pos, axis=-1) - 1.0).astype(jnp.int32)
            offset = offset + jnp.sum(oh, axis=1, keepdims=True)
            kept = (slot < cap)[..., None]  # tokens past capacity drop
            dispatches.append(
                oh[..., None]
                * jax.nn.one_hot(
                    jnp.clip(slot, 0, cap - 1), cap
                )[:, :, None, :]
                * kept[..., None]
            )  # [B, S, E, C]
        # a token's choices go to DIFFERENT experts, so the per-choice
        # dispatch masks are disjoint and their sum stays one-hot
        dispatch = sum(dispatches)

        xin = x.astype(dtype)
        expert_in = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(dtype), xin
        )  # [E, B, C, D] — GSPMD lowers this to the all-to-all dispatch
        expert_in = self._constrain(expert_in)

        def one_expert(inp, w1e, b1e, w2e, b2e):
            h = jax.nn.relu(inp @ w1e.astype(dtype) + b1e.astype(dtype))
            return h @ w2e.astype(dtype) + b2e.astype(dtype)

        h = jax.vmap(one_expert)(expert_in, w1, b1, w2, b2)  # [E, B, C, D]
        h = self._constrain(h)

        combine = sum(
            dispatches[j] * weights[:, :, j, None, None] for j in range(k)
        )  # [B, S, E, C]
        y = jnp.einsum(
            "bsec,ebcd->bsd", combine.astype(dtype), h
        )  # the all-to-all return + weighted combine
        return y.astype(x.dtype)

    def _constrain(self, t):
        if self.expert_axis is None or self.is_initializing():
            return t
        mesh = get_abstract_mesh()
        if mesh is None or self.expert_axis not in getattr(
            mesh, "axis_names", ()
        ):
            # no mesh context (e.g. plain CPU apply in tests): the
            # constraint is a layout hint, not semantics — skip it
            return t
        return jax.lax.with_sharding_constraint(
            t, P(self.expert_axis, *([None] * (t.ndim - 1)))
        )


def shard_expert_params(params, mesh, axis: str):
    """Place a MoEMlp param tree with expert dims sharded over ``axis``."""
    from jax.sharding import NamedSharding

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w1", "b1", "w2", "b2"):
            sh = NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))
        else:
            sh = NamedSharding(mesh, P())
        return jax.device_put(leaf, sh)

    return jax.tree_util.tree_map_with_path(place, params)


# ------------------------------------------------------------- graftcheck

def audit_programs():
    """graftcheck registration hook: the expert-parallel MoE layer.

    The layer's dispatch/combine einsums are WRITTEN dense; the whole
    EP design rests on GSPMD lowering them to expert-axis exchanges
    instead of replicating every expert's input. That is invisible at
    the jaxpr level, so this program COMPILES (CPU, partitioned over a
    ``model``-axis expert mesh with sharded expert weights) and the
    committed HLO budget records the exchange the partitioner actually
    emits — growing all-gather volume here means dropped expert
    sharding (the capacity-vs-replication trade of arXiv:2004.13336).
    """
    def build():
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        from ..parallel.mesh import MODEL_AXIS, audit_mesh

        mesh = audit_mesh(data=1, model=4)
        d = 8  # token feature width of the audit program
        layer = MoEMlp(n_experts=4, d_hidden=32,
                       expert_axis=MODEL_AXIS, capacity_factor=4.0,
                       dtype=jnp.bfloat16)
        x = jax.ShapeDtypeStruct((2, 16, d), jnp.float32)
        params = jax.eval_shape(
            lambda: layer.init(jax.random.PRNGKey(0),
                               jnp.zeros((2, 16, d))))["params"]

        def shard(path, leaf):
            name = path[-1].key if hasattr(path[-1], "key") else ""
            spec = (P(MODEL_AXIS, *([None] * (leaf.ndim - 1)))
                    if name in ("w1", "b1", "w2", "b2") else P())
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype,
                sharding=NamedSharding(mesh, spec))

        params = jax.tree_util.tree_map_with_path(shard, params)

        def fn(p, inp):
            return layer.apply({"params": p}, inp)

        return {
            "fn": fn, "args": (params, x), "mesh": mesh,
            "compile": True, "compile_fn": jax.jit(fn),
            # expert weights stay resident-sharded: nothing close to
            # the full [E, d, d_hidden] w1/w2 stack may gather
            # (derived from the layer so a geometry change tracks)
            "max_allgather_bytes":
                layer.n_experts * d * layer.d_hidden * 4 - 1,
        }

    return [{"name": "moe_mlp_ep", "min_devices": 4, "build": build}]
