"""Mixture-of-Experts MLP with expert parallelism (EP).

The reference has no MoE (SURVEY.md §2.3 marks expert parallelism
absent); this is part of the framework's scale-out surface, built the
idiomatic TPU way: the layer is written with GLOBAL semantics
(Switch-style top-1 routing with a fixed per-expert capacity so every
shape is static), the expert-indexed weight tensors carry a mesh-axis
annotation, and GSPMD partitions the dispatch/combine einsums —
lowering them to the all-to-all exchanges an NCCL MoE implementation
would hand-write.

Routing (Switch Transformer, top-1):
  gates  = softmax(x @ Wg)                      [B, S, E]
  expert = argmax(gates)                        [B, S]
  slot   = position of each token within its expert's capacity C
           (C = ceil(S * capacity_factor / E)); tokens past capacity
           are DROPPED (their output is 0 — the residual carries them)
  dispatch[b, s, e, c] = 1 iff token (b, s) is slot c of expert e
  h = expert_mlp_e(dispatch^T x)                [E, B, C, D] (vmapped)
  y[b, s] = gate[b, s, expert] * h[expert, b, slot]

Under ``shard_expert_params`` + a mesh, each device stores E/ep of the
expert weights and computes only its experts' FLOPs.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P


class MoEMlp(nn.Module):
    """Switch-style top-1 MoE feed-forward block.

    Attributes:
      n_experts: number of expert MLPs (E).
      d_hidden: expert hidden width.
      capacity_factor: per-expert capacity = ceil(S * factor / E).
      expert_axis: optional mesh axis name baked into a
        ``with_sharding_constraint`` on the expert-indexed activations
        (use together with :func:`shard_expert_params`); ``None`` runs
        unconstrained (single device / tests).
      dtype: compute dtype (params stay f32).
    """

    n_experts: int
    d_hidden: int
    capacity_factor: float = 1.0
    expert_axis: Optional[str] = None
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        e = self.n_experts
        cap = max(1, int(-(-s * self.capacity_factor // e)))
        dtype = self.dtype or x.dtype

        wg = self.param("gate", nn.initializers.lecun_normal(), (d, e),
                        jnp.float32)
        w1 = self.param(
            "w1", nn.initializers.lecun_normal(), (e, d, self.d_hidden),
            jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, self.d_hidden),
                        jnp.float32)
        w2 = self.param(
            "w2", nn.initializers.lecun_normal(), (e, self.d_hidden, d),
            jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)

        router_logits = x.astype(jnp.float32) @ wg  # [B, S, E]
        gates = jax.nn.softmax(
            router_logits, axis=-1
        )  # [B, S, E] — routing math in f32 always
        expert = jnp.argmax(gates, axis=-1)  # [B, S]
        gate = jnp.max(gates, axis=-1)  # [B, S]

        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [B, S, E]

        # Load-balancing auxiliary loss (Switch Transformer): E * <f, p>
        # where f_e = fraction of tokens dispatched to expert e (hard,
        # pre-capacity) and p_e = mean router probability of expert e.
        # Minimized (= 1.0) at uniform routing; without it top-1 routing
        # collapses onto a few experts in real training. Differentiable
        # through p only (f is argmax-hard), which is exactly the Switch
        # formulation. Sown under the "losses" collection — training
        # steps read it via ``mutable=["losses"]`` and add
        # ``weight * aux``; eval/apply without mutable discards it.
        f = jnp.mean(onehot.reshape(-1, e), axis=0)  # [E]
        p = jnp.mean(gates.reshape(-1, e), axis=0)  # [E]
        self.sow("losses", "moe_aux", e * jnp.sum(f * p))
        # Router z-loss (ST-MoE): mean logsumexp(logits)^2 keeps router
        # logits small/stable in bf16 training.
        self.sow(
            "losses", "moe_z",
            jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2),
        )
        # slot of each token within its expert (0-based), per batch row
        pos = jnp.cumsum(onehot, axis=1) * onehot  # [B, S, E], 1-based
        slot = (jnp.sum(pos, axis=-1) - 1.0).astype(jnp.int32)  # [B, S]
        kept = (slot < cap)[..., None]  # tokens past capacity drop
        dispatch = (
            onehot[..., None]
            * jax.nn.one_hot(jnp.clip(slot, 0, cap - 1), cap)[:, :, None, :]
            * kept[..., None]
        )  # [B, S, E, C]

        xin = x.astype(dtype)
        expert_in = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(dtype), xin
        )  # [E, B, C, D] — GSPMD lowers this to the all-to-all dispatch
        expert_in = self._constrain(expert_in)

        def one_expert(inp, w1e, b1e, w2e, b2e):
            h = jax.nn.relu(inp @ w1e.astype(dtype) + b1e.astype(dtype))
            return h @ w2e.astype(dtype) + b2e.astype(dtype)

        h = jax.vmap(one_expert)(expert_in, w1, b1, w2, b2)  # [E, B, C, D]
        h = self._constrain(h)

        combine = dispatch * gate[..., None, None]  # [B, S, E, C]
        y = jnp.einsum(
            "bsec,ebcd->bsd", combine.astype(dtype), h
        )  # the all-to-all return + weighted combine
        return y.astype(x.dtype)

    def _constrain(self, t):
        if self.expert_axis is None or self.is_initializing():
            return t
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or self.expert_axis not in getattr(
            mesh, "axis_names", ()
        ):
            # no mesh context (e.g. plain CPU apply in tests): the
            # constraint is a layout hint, not semantics — skip it
            return t
        return jax.lax.with_sharding_constraint(
            t, P(self.expert_axis, *([None] * (t.ndim - 1)))
        )


def shard_expert_params(params, mesh, axis: str):
    """Place a MoEMlp param tree with expert dims sharded over ``axis``."""
    from jax.sharding import NamedSharding

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w1", "b1", "w2", "b2"):
            sh = NamedSharding(mesh, P(axis, *([None] * (leaf.ndim - 1))))
        else:
            sh = NamedSharding(mesh, P())
        return jax.device_put(leaf, sh)

    return jax.tree_util.tree_map_with_path(place, params)
