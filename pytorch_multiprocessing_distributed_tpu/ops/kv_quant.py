"""graftquant: int8 KV-cache quantization as a pytree pair.

The serving stack's decode hot loop is bandwidth- and residency-bound:
KV pages are the dominant bytes term of every flash-decode dispatch and
the per-slot HBM term that bounds batch. Storing K/V **int8 with
per-token-per-head f32 scales** halves both at a budgeted logit cost —
the scale sidecar lives BESIDE the data with the trailing ``[...,
head_dim]`` pair untouched, so the tileable layout the Pallas kernels
stream is unchanged and the dequant is one multiply in the VMEM stream.

The representation is :class:`QuantizedKV`, a registered pytree node
``(data int8, scale f32)`` whose scale carries the data's shape MINUS
the trailing head_dim axis (quantization groups over head_dim — one
amax per (…, token, head) group):

* dense slot caches: data ``[L, slots, s_max, H, Dh]`` int8,
  scale ``[L, slots, s_max, H]`` f32;
* paged caches: data ``[L, pages, H, page_size, Dh]`` int8,
  scale ``[L, pages, H, page_size]`` f32.

Because it is a pytree, every existing jitted program signature,
``donate_argnums`` index, and ``out_shardings`` arity is UNCHANGED — a
quantized cache operand simply flattens to two leaves where one used to
be. Donation still reuses both buffers (int8->int8, f32->f32), scan
carries it, and ``jax.tree.map(ShapeDtypeStruct, …)`` lowers it for the
graftcheck audit. Duck-typed ``.shape``/``.dtype``/``__getitem__``
(layer indexing slices BOTH leaves) keep the generate/engine call sites
readable.

The quant formula (device and the numpy host twin used by the
prefill->decode wire path are test-pinned equal, so a transferred block
splices WITHOUT requantization):

    amax  = max(|x|) over head_dim            (per token, per head)
    scale = amax / 127        (1.0 where the group is all-zero)
    q     = clip(round(x / scale), -127, 127) as int8

Dequant is ``q * scale`` cast to the compute dtype — shared verbatim by
the Pallas kernels and the XLA fallback, so CPU tests pin the exact
math the TPU runs. Not token-exact vs the unquantized engine: the
harness pins greedy transcripts on canonical configs and budgets the
max-abs-logit delta instead (tests/test_graftquant.py).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "QuantizedKV",
    "KV_DTYPES",
    "quantize_kv",
    "dequantize_kv",
    "quantize_kv_np",
    "kv_slice_in_dim",
    "stack_kv",
]

# engine-facing names for the cache element layout; "model" keeps the
# historical behaviour (cache dtype == model dtype)
KV_DTYPES = ("model", "int8")

_QMAX = 127.0
# The scale formula multiplies by this precomputed reciprocal instead of
# writing ``amax / _QMAX``: XLA strength-reduces division-by-constant to
# a reciprocal multiply inside jit, so the literal division is 1 ULP off
# the numpy twin on a few percent of values. One shared constant makes
# the eager, jitted, and host paths run the SAME f32 multiply.
_INV_QMAX = np.float32(1.0 / _QMAX)


class QuantizedKV:
    """Pytree pair ``(data int8, scale f32)`` for a quantized KV cache.

    ``scale.shape == data.shape[:-1]`` — one scale per head_dim group.
    Registered as a pytree node so jit/scan/donation/sharding treat it
    as two ordinary leaves; duck-typed just enough (``shape``/``dtype``
    delegate to ``data``, ``__getitem__`` indexes both leaves) that
    cache-shaped code reads the same in both modes."""

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    # ---- array duck typing (reads delegate to the int8 payload)
    @property
    def shape(self):
        return self.data.shape

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes) + int(self.scale.nbytes)

    def __getitem__(self, idx):
        # leading-axis indexing only (layer/page selection): the
        # trailing head_dim axis exists on data alone, so an index
        # touching it would desynchronize the pair
        return QuantizedKV(self.data[idx], self.scale[idx])

    def __repr__(self):
        return (f"QuantizedKV(data={self.data.shape}:{self.data.dtype}, "
                f"scale={self.scale.shape}:{self.scale.dtype})")

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


jax.tree_util.register_pytree_node(
    QuantizedKV,
    lambda kv: kv.tree_flatten(),
    QuantizedKV.tree_unflatten,
)


def quantize_kv(x) -> QuantizedKV:
    """Symmetric per-(…, token, head) int8 quantization over the
    trailing head_dim axis. f32 math regardless of the input dtype so
    the device formula and the numpy host twin agree bit-exactly."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0.0, amax * _INV_QMAX, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -_QMAX, _QMAX)
    return QuantizedKV(q.astype(jnp.int8), scale.astype(jnp.float32))


def dequantize_kv(kv: QuantizedKV, dtype):
    """``data * scale`` in f32, cast to the compute ``dtype`` — the ONE
    dequant expression, shared by the Pallas kernels (in the VMEM
    stream) and the XLA fallbacks (before the reference einsum)."""
    return (kv.data.astype(jnp.float32)
            * kv.scale[..., None]).astype(dtype)


def quantize_kv_np(x):
    """Host (numpy) twin of :func:`quantize_kv` for the prefill->decode
    PageTransfer path: the prefill replica quantizes OFF the device hot
    path and the block splices into the decode pool without
    requantization. Returns ``(data int8, scale f32)`` ndarrays,
    test-pinned bit-equal to the device formula."""
    xf = np.asarray(x).astype(np.float32)
    amax = np.max(np.abs(xf), axis=-1)
    scale = np.where(amax > 0.0, amax * _INV_QMAX,
                     np.float32(1.0)).astype(np.float32)
    q = np.clip(np.round(xf / scale[..., None]), -_QMAX, _QMAX)
    return q.astype(np.int8), scale


def kv_slice_in_dim(kv, start, size, axis: int):
    """``lax.slice_in_dim`` over a cache that may be quantized. The
    sliced axis must precede the trailing head_dim axis (windowing
    slices tokens, never lanes), so the SAME axis index is valid on
    both leaves."""
    if isinstance(kv, QuantizedKV):
        return QuantizedKV(
            jax.lax.slice_in_dim(kv.data, start, size, axis=axis),
            jax.lax.slice_in_dim(kv.scale, start, size, axis=axis))
    return jax.lax.slice_in_dim(kv, start, size, axis=axis)


def stack_kv(leaves):
    """``jnp.stack`` over per-layer cache slices that may be quantized
    pairs — rebuilds the ``[L, …]`` leading axis on BOTH leaves."""
    if leaves and isinstance(leaves[0], QuantizedKV):
        return QuantizedKV(jnp.stack([kv.data for kv in leaves]),
                           jnp.stack([kv.scale for kv in leaves]))
    return jnp.stack(leaves)
