"""Cross-replica synchronized batch normalization.

TPU-native equivalent of ``torch.nn.SyncBatchNorm`` (applied to every BN
layer of the reference model at ``main.py:43`` via
``convert_sync_batchnorm``). Instead of a NCCL all-reduce of per-GPU
statistics inside a CUDA kernel, the batch mean and mean-of-squares are
``lax.pmean``-ed over the named ``data`` mesh axis — XLA lowers this to an
ICI all-reduce fused into the surrounding computation.

Semantics match torch BatchNorm2d/SyncBatchNorm exactly (gated by tests
in ``tests/test_batch_norm.py``):

- normalization uses the *biased* batch variance (``E[x^2] - E[x]^2`` over
  the GLOBAL batch when an axis name is given);
- running stats follow torch's convention
  ``running = (1 - momentum) * running + momentum * stat`` with
  ``momentum = 0.1`` (note: flax linen's ``momentum`` is the complement);
- the running variance is updated with the *unbiased* estimate
  (``biased * n / (n - 1)`` with ``n`` the global reduce count), as torch
  does;
- eval mode normalizes with the running statistics.

Statistics are always computed in float32 regardless of the compute dtype
(bf16-safe, matching torch's mixed-precision BN behavior).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


class SyncBatchNorm(nn.Module):
    """BatchNorm over ``(batch, spatial...)`` with optional cross-replica sync.

    Attributes:
      use_running_average: if True, use stored batch_stats (eval mode).
      axis_name: mesh axis to ``pmean`` statistics over. ``None`` gives
        plain per-replica BatchNorm (identical to torch BatchNorm2d).
      momentum: torch-convention update fraction for running stats.
      epsilon: numerical stability constant (torch default 1e-5).
      dtype: compute/output dtype (e.g. bf16); stats are f32 internally.
    """

    use_running_average: Optional[bool] = None
    axis_name: Optional[str] = None
    momentum: float = 0.1
    epsilon: float = 1e-5
    dtype: Any = None
    param_dtype: Any = jnp.float32
    use_bias: bool = True
    use_scale: bool = True

    @nn.compact
    def __call__(self, x: jax.Array, use_running_average: Optional[bool] = None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        num_features = x.shape[-1]
        reduction_axes = tuple(range(x.ndim - 1))  # all but channel (NHWC)

        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (num_features,)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (num_features,)
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduction_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduction_axes)
            # local element count per channel
            local_n = 1
            for ax in reduction_axes:
                local_n *= x.shape[ax]
            n = jnp.asarray(local_n, jnp.float32)
            if self.axis_name is not None and not self.is_initializing():
                # Global statistics over the data axis: one fused pmean of
                # [mean, mean_sq] — the SyncBatchNorm stat exchange. Skipped
                # at init time so modules can be initialized outside the
                # mesh/pmap context (shapes are identical either way).
                mean, mean_sq = jax.lax.pmean((mean, mean_sq), self.axis_name)
                n = n * jax.lax.psum(1, self.axis_name)
            var = mean_sq - jnp.square(mean)  # biased, used for normalization

            if not self.is_initializing():
                m = self.momentum
                unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
                ra_mean.value = (1.0 - m) * ra_mean.value + m * mean
                ra_var.value = (1.0 - m) * ra_var.value + m * unbiased

        y = (x.astype(jnp.float32) - mean) / jnp.sqrt(var + self.epsilon)
        if self.use_scale:
            scale = self.param(
                "scale", nn.initializers.ones, (num_features,), self.param_dtype
            )
            y = y * scale
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (num_features,), self.param_dtype
            )
            y = y + bias
        out_dtype = self.dtype or x.dtype
        return y.astype(out_dtype)
