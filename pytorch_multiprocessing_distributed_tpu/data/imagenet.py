"""ImageNet-scale input pipeline (BASELINE.md configs #2, #3, #5).

The reference pipeline is CIFAR-only (``data.py:6-59``); the framework's
headline target is ResNet-50/ImageNet (BASELINE.json north star), so the
data layer must scale to 224x224/1000-class traffic. Two sources:

- :class:`FolderImageNet` — reads a ``train/<wnid>/*.JPEG``-style tree
  (the torchvision ``ImageFolder`` layout) using Pillow when available.
  Decoding is lazy per batch: only the epoch's index permutation lives in
  memory, never the dataset.
- :func:`synthetic_imagenet` — deterministic class-separable synthetic
  set generated ON DEMAND per index (an ``IndexedDataset``), so
  ImageNet-shaped benches run data-free at any nominal dataset size
  without materializing terabytes.

Both plug into the same :class:`..parallel.sampler` sharding math as
CIFAR (DistributedSampler-parity), via :class:`IndexedLoader` — the
lazy-source counterpart of :class:`.pipeline.ShardedLoader`.

Standard ImageNet train aug = RandomResizedCrop(224) + HFlip; eval =
Resize(256) + CenterCrop(224); normalization by the usual per-channel
mean/std.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..parallel.sampler import padded_epoch_indices

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize_imagenet(images: np.ndarray) -> np.ndarray:
    """uint8 [N,H,W,C] -> float32 normalized by ImageNet mean/std."""
    x = images.astype(np.float32) / 255.0
    return (x - IMAGENET_MEAN) / IMAGENET_STD


# --------------------------------------------------------------- datasets


class IndexedDataset:
    """Minimal lazy-dataset protocol: ``len(ds)``, ``ds.get(indices, rng,
    train) -> (uint8 images [n,H,W,C], int32 labels [n])``."""

    image_size: int = 224
    num_classes: int = 1000

    def __len__(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def get(self, indices, rng, train):  # pragma: no cover - interface
        raise NotImplementedError


class SyntheticImageNet(IndexedDataset):
    """Class-separable synthetic images computed per index on demand.

    Each class gets a fixed low-frequency pattern; per-sample noise is
    seeded by the index, so any slice of the dataset is reproducible
    without storing it. Default nominal size matches ImageNet-1k train.
    """

    def __init__(self, n: int = 1_281_167, *, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0):
        self._n = n
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed
        # per-class pattern basis: 8x8 low-res patterns upsampled on use
        rng = np.random.default_rng(seed)
        self._patterns = rng.integers(
            64, 192, size=(num_classes, 8, 8, 3)
        ).astype(np.uint8)

    def __len__(self) -> int:
        return self._n

    def label_of(self, idx: np.ndarray) -> np.ndarray:
        # index-determined label (golden-ratio hash for class balance)
        return ((idx * 2654435761) % self.num_classes).astype(np.int32)

    def get(self, indices, rng, train):
        idx = np.asarray(indices, np.int64)
        labels = self.label_of(idx)
        s = self.image_size
        reps = -(-s // 8)
        base = np.repeat(
            np.repeat(self._patterns[labels], reps, axis=1), reps, axis=2
        )[:, :s, :s, :]
        # per-index deterministic noise via a vectorized integer hash (no
        # RNG state): sample i's pixels depend only on (seed, index i)
        pix = np.arange(s * s * 3, dtype=np.uint32).reshape(1, s, s, 3)
        h = (
            (idx[:, None, None, None] + self.seed).astype(np.uint32)
            * np.uint32(2654435761)
        ) ^ (pix * np.uint32(2246822519))
        h ^= h >> np.uint32(13)
        noise = (h % np.uint32(49)).astype(np.int32) - 24
        images = np.clip(base.astype(np.int32) + noise, 0, 255).astype(np.uint8)
        return images, labels


class FolderImageNet(IndexedDataset):
    """``root/<split>/<wnid>/*.JPEG`` tree, decoded lazily via Pillow.

    Class ids are assigned by sorted wnid (torchvision ``ImageFolder``
    semantics), so checkpoints trained elsewhere line up.

    Decoding is PARALLEL over a persistent thread pool (Pillow releases
    the GIL inside JPEG decode) — the analogue of the reference's
    ``num_workers=4`` loader processes (``data.py:44``), without which
    serial decode starves the chip at ImageNet rates (VERDICT r1).
    ``num_workers=0`` selects serial decode (same per-image seed scheme,
    bit-identical output — pinned by test).
    """

    _EXTS = (".jpeg", ".jpg", ".png", ".bmp")

    def __init__(self, root: str, split: str = "train", *,
                 image_size: int = 224, num_workers: Optional[int] = None):
        self.image_size = image_size
        self.num_workers = (
            num_workers if num_workers is not None
            else min(8, os.cpu_count() or 1)
        )
        self._pool = None
        base = os.path.join(root, split)
        if not os.path.isdir(base):
            raise FileNotFoundError(f"no ImageNet split dir at {base}")
        self.paths: List[str] = []
        labels: List[int] = []
        wnids = sorted(
            d for d in os.listdir(base)
            if os.path.isdir(os.path.join(base, d))
        )
        self.wnid_to_label = {w: i for i, w in enumerate(wnids)}
        self.num_classes = max(len(wnids), 1)
        for w in wnids:
            d = os.path.join(base, w)
            for name in sorted(os.listdir(d)):
                if name.lower().endswith(self._EXTS):
                    self.paths.append(os.path.join(d, name))
                    labels.append(self.wnid_to_label[w])
        self.labels = np.asarray(labels, np.int32)

    def __len__(self) -> int:
        return len(self.paths)

    def _ensure_pool(self):
        if self._pool is None and self.num_workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                self.num_workers, thread_name_prefix="pmdt-decode"
            )
        return self._pool

    def get(self, indices, rng, train, seeds=None):
        from PIL import Image, ImageFile  # lazy: ships with torchvision stacks

        # Real ImageNet shards contain truncated JPEGs (and CMYK,
        # grayscale, palette images — ``convert("RGB")`` below absorbs
        # those). DECISION OF RECORD: tolerate truncation the way
        # torchvision-based pipelines conventionally do (the cut-off
        # region decodes gray) rather than letting one bad file kill an
        # epoch hours in; a file that cannot be decoded AT ALL still
        # fails fast with its path in the error (below).
        ImageFile.LOAD_TRUNCATED_IMAGES = True

        idx = np.asarray(indices)
        s = self.image_size
        out = np.empty((len(idx), s, s, 3), np.uint8)
        # Per-image child seeds drawn ONCE from the epoch stream, so the
        # augmentation randomness is deterministic regardless of decode
        # order / worker count (serial and parallel bit-match). A caller
        # may pass pre-drawn ``seeds`` instead (the loader draws them per
        # REPLICA stream but decodes all replicas in one pool round).
        if seeds is None:
            seeds = rng.integers(0, 2**63, size=len(idx))
        else:
            seeds = np.asarray(seeds)

        def work(row: int) -> None:
            r = np.random.default_rng(seeds[row])
            path = self.paths[idx[row]]
            try:
                with Image.open(path) as im:
                    im = im.convert("RGB")
                    if train:
                        out[row] = _random_resized_crop(im, s, r)
                    else:
                        out[row] = _center_crop(im, s)
            except Exception as e:
                # name the file: "UnidentifiedImageError" alone is
                # useless against a 1.2M-file tree
                raise RuntimeError(
                    f"cannot decode image {path!r}: {type(e).__name__}: {e}"
                ) from e

        pool = self._ensure_pool()
        if pool is None:
            for row in range(len(idx)):
                work(row)
        else:
            # list() drains the iterator so worker exceptions propagate
            list(pool.map(work, range(len(idx))))
        return out, self.labels[idx]

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_pool"] = None  # executors don't pickle; recreated on demand
        return d


def synthetic_imagenet(n: int = 4096, *, image_size: int = 224,
                       num_classes: int = 1000, seed: int = 0
                       ) -> SyntheticImageNet:
    return SyntheticImageNet(n, image_size=image_size,
                             num_classes=num_classes, seed=seed)


# ------------------------------------------------------------ transforms


def _random_resized_crop(im, size: int, rng: np.random.Generator):
    """torchvision RandomResizedCrop(size): area in [0.08, 1], aspect in
    [3/4, 4/3], 10 tries then center-crop fallback."""
    w, h = im.size
    area = w * h
    arr = None
    for _ in range(10):
        target_area = area * rng.uniform(0.08, 1.0)
        aspect = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = int(rng.integers(0, w - cw + 1))
            y0 = int(rng.integers(0, h - ch + 1))
            box = (x0, y0, x0 + cw, y0 + ch)
            arr = np.asarray(im.resize((size, size), box=box), np.uint8)
            break
    if arr is None:  # extreme-aspect fallback (torchvision center-crops)
        arr = _center_crop(im, size)
    # HFlip is an independent transform after the crop in torchvision, so
    # it applies on the fallback path too.
    if rng.random() < 0.5:
        arr = arr[:, ::-1]
    return arr


def _center_crop(im, size: int):
    """Resize(short side -> size*256/224) + CenterCrop(size)."""
    w, h = im.size
    scale = (size * 256 // 224) / min(w, h)
    im = im.resize((max(1, round(w * scale)), max(1, round(h * scale))))
    w, h = im.size
    x0 = (w - size) // 2
    y0 = (h - size) // 2
    return np.asarray(im.crop((x0, y0, x0 + size, y0 + size)), np.uint8)


def _synthetic_train_aug(images: np.ndarray, rng: np.random.Generator
                         ) -> np.ndarray:
    """Cheap train-time aug for already-sized (synthetic) images: random
    flip only — crop geometry is meaningless for generated patterns."""
    flips = rng.random(images.shape[0]) < 0.5
    images = images.copy()
    images[flips] = images[flips, :, ::-1, :]
    return images


# ----------------------------------------------------------------- loader


class IndexedLoader:
    """DistributedSampler-parity batch loader over a lazy
    :class:`IndexedDataset` (the ImageNet counterpart of
    :class:`.pipeline.ShardedLoader`, same replica-ordered superbatch
    layout and epoch-seeded shard math — ``..parallel.sampler``)."""

    def __init__(
        self,
        dataset: IndexedDataset,
        *,
        batch_size: int,
        world_size: int,
        replica_ids: Optional[Sequence[int]] = None,
        train: bool = True,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        with_valid: bool = False,
        prefetch_batches: int = 2,
    ):
        if batch_size % world_size:
            raise ValueError(
                f"global batch {batch_size} not divisible by world {world_size}"
            )
        self.dataset = dataset
        self.prefetch_batches = prefetch_batches
        self.batch_size = batch_size
        self.per_replica = batch_size // world_size
        self.world_size = world_size
        self.replica_ids = (
            list(replica_ids) if replica_ids is not None
            else list(range(world_size))
        )
        self.train = train
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.with_valid = with_valid
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    @property
    def dataset_size(self) -> int:
        return len(self.dataset)

    def _shard_len(self) -> int:
        n, w = len(self.dataset), self.world_size
        return n // w if (self.drop_last and n % w) else -(-n // w)

    def __len__(self) -> int:
        n = self._shard_len()
        return n // self.per_replica if self.drop_last else -(-n // self.per_replica)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        """Batches come off a background assembly thread through a bounded
        queue (``prefetch_batches`` deep): index->decode->augment->
        normalize for batch k+1 overlaps the training step on batch k —
        together with the thread-pool decode, the ``num_workers=4`` +
        ``pin_memory`` analogue (reference ``data.py:41-53``).
        ``prefetch_batches=0`` iterates inline (tests/debug)."""
        if self.prefetch_batches <= 0:
            yield from self._produce()
            return

        q: queue.Queue = queue.Queue(maxsize=self.prefetch_batches)
        stop = threading.Event()
        _DONE = object()

        def producer():
            try:
                for item in self._produce():
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
                item = _DONE
            except BaseException as e:  # surfaced on the consumer side
                item = e
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

        t = threading.Thread(
            target=producer, daemon=True, name="pmdt-batch-assembly"
        )
        t.start()
        try:
            while True:
                item = q.get()
                if item is _DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def _produce(self) -> Iterator[Tuple[np.ndarray, ...]]:
        padded = np.asarray(padded_epoch_indices(
            len(self.dataset), self.world_size, shuffle=self.shuffle,
            seed=self.seed, epoch=self._epoch, drop_last=self.drop_last,
        ))
        shards = [padded[r :: self.world_size] for r in self.replica_ids]
        positions = [
            np.asarray(r) + self.world_size * np.arange(self._shard_len())
            for r in self.replica_ids
        ]
        # one decode/augment stream PER REPLICA (seed, epoch, 77, r): a
        # host assembling only replica r must draw the same augmentations
        # r would get on a single host (same fix as ShardedLoader —
        # pinned by the 2-host e2e test)
        rngs = [
            np.random.default_rng(
                np.random.SeedSequence([self.seed, self._epoch, 77, int(r)])
            )
            for r in self.replica_ids
        ]
        for b in range(len(self)):
            lo = b * self.per_replica
            hi = lo + self.per_replica
            idx_parts = [np.asarray(s[lo:hi]) for s in shards]
            if isinstance(self.dataset, FolderImageNet):
                # seeds drawn per REPLICA stream, decode in ONE pool
                # round (per-replica get calls would serialize the
                # thread-pool decode at a fraction of its width)
                seeds = np.concatenate([
                    r.integers(0, 2**63, size=len(p))
                    for p, r in zip(idx_parts, rngs)
                ])
                images, labels = self.dataset.get(
                    np.concatenate(idx_parts), None, self.train,
                    seeds=seeds)
            elif isinstance(self.dataset, SyntheticImageNet):
                # index-deterministic (rng unused by get); only the
                # train aug draws, per replica stream
                images, labels = self.dataset.get(
                    np.concatenate(idx_parts), rngs[0], self.train)
                if self.train:
                    images = np.concatenate([
                        _synthetic_train_aug(part, r)
                        for part, r in zip(
                            np.array_split(images, len(rngs)), rngs)
                    ])
            else:
                # general protocol: one get per replica with its stream
                img_parts, lab_parts = [], []
                for p, r in zip(idx_parts, rngs):
                    ims, labs = self.dataset.get(p, r, self.train)
                    img_parts.append(ims)
                    lab_parts.append(labs)
                images = np.concatenate(img_parts)
                labels = np.concatenate(lab_parts)
            out = (normalize_imagenet(images), labels.astype(np.int32))
            if self.with_valid:
                valid = np.concatenate(
                    [p[lo:hi] < len(self.dataset) for p in positions]
                )
                out = out + (valid,)
            yield out
