"""Input pipeline: datasets, augmentations, per-host sharded loading.

TPU-native replacement for the reference's data layer (``data.py:6-59``):
torchvision CIFAR-10 + ``DistributedSampler`` + 4-worker ``DataLoader``
becomes a numpy-native CIFAR reader, vectorized host-side augmentations,
a per-replica sharded loader with DistributedSampler-exact index
assignment, and double-buffered async device prefetch (the pinned-memory
H2D analogue, SURVEY.md §2.2).
"""

from .cifar import load_cifar10, synthetic_cifar10
from .transforms import normalize, random_crop_flip
from .lm import TokenLoader, synthetic_tokens
from .text import load_text_corpus, tokenize, detokenize
from .pipeline import ShardedLoader, get_loader, prefetch_to_device
from .imagenet import (
    FolderImageNet,
    IndexedLoader,
    SyntheticImageNet,
    normalize_imagenet,
    synthetic_imagenet,
)

__all__ = [
    "TokenLoader",
    "synthetic_tokens",
    "load_text_corpus",
    "tokenize",
    "detokenize",
    "load_cifar10",
    "synthetic_cifar10",
    "normalize",
    "random_crop_flip",
    "ShardedLoader",
    "get_loader",
    "prefetch_to_device",
    "FolderImageNet",
    "IndexedLoader",
    "SyntheticImageNet",
    "normalize_imagenet",
    "synthetic_imagenet",
]
