"""Host-side image augmentations, vectorized over the batch.

Parity targets (reference ``data.py:11-19``):
- train: Resize(32) -> RandomCrop(32, padding=8) -> RandomHorizontalFlip
  -> ToTensor -> Normalize(mean=.5, std=.5)   (Resize is a no-op at 32x32)
- test:  Resize(32) -> ToTensor -> Normalize(mean=.5, std=.5)

Implemented as batched numpy ops (one vectorized gather instead of
per-sample PIL calls across 4 worker processes — the reference's
``num_workers=4`` exists to hide exactly this cost, ``data.py:44``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

MEAN = 0.5
STD = 0.5


def normalize(images: np.ndarray) -> np.ndarray:
    """uint8 [N,H,W,C] -> float32, scaled to [0,1] then (x-mean)/std.

    ToTensor + Normalize(mean=std=0.5) == maps pixels into [-1, 1].
    """
    x = images.astype(np.float32) / 255.0
    return (x - MEAN) / STD


def random_crop_flip(
    images: np.ndarray,
    rng: np.random.Generator,
    *,
    padding: int = 8,
    flip_prob: float = 0.5,
) -> np.ndarray:
    """RandomCrop(32, padding=8) + RandomHorizontalFlip, batched.

    Zero-pads by ``padding`` on each side then crops a random 32x32
    window per sample (torchvision RandomCrop default constant-0 fill),
    then flips each sample with probability 1/2.
    """
    n, h, w, c = images.shape
    padded = np.pad(
        images,
        ((0, 0), (padding, padding), (padding, padding), (0, 0)),
        mode="constant",
    )
    ys = rng.integers(0, 2 * padding + 1, size=n)
    xs = rng.integers(0, 2 * padding + 1, size=n)
    # vectorized window gather
    row_idx = ys[:, None] + np.arange(h)[None, :]  # [N,H]
    col_idx = xs[:, None] + np.arange(w)[None, :]  # [N,W]
    out = padded[np.arange(n)[:, None, None], row_idx[:, :, None],
                 col_idx[:, None, :], :]
    flips = rng.random(n) < flip_prob
    out[flips] = out[flips, :, ::-1, :]
    return out
