"""CIFAR-10 loading without a torchvision dependency.

Reads the standard ``cifar-10-batches-py`` pickle archive (the same bytes
torchvision's ``datasets.CIFAR10`` parses for the reference at
``data.py:21-28``, with ``download=False`` — the reference assumes the
data is already on disk, and so do we). When the archive is absent,
:func:`synthetic_cifar10` provides a deterministic class-separable stand-in
so smoke tests and benches run data-free.
"""

from __future__ import annotations

import os
import pickle
from typing import Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]  # images uint8 [N,32,32,3], labels int32 [N]


def _read_batch(path: str) -> Arrays:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    images = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # -> NHWC
    labels = np.asarray(d[b"labels"], np.int32)
    return np.ascontiguousarray(images), labels


def load_cifar10(root: str = "./cifar10_data", train: bool = True) -> Arrays:
    """Load a CIFAR-10 split from ``{root}/cifar-10-batches-py``.

    Raises FileNotFoundError when the archive is missing (the reference
    behavior with ``download=False``).
    """
    base = os.path.join(root, "cifar-10-batches-py")
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    images, labels = [], []
    for name in names:
        x, y = _read_batch(os.path.join(base, name))
        images.append(x)
        labels.append(y)
    return np.concatenate(images), np.concatenate(labels)


def synthetic_cifar10(
    n: int = 50000, *, seed: int = 0, num_classes: int = 10
) -> Arrays:
    """Deterministic learnable fake CIFAR: class-dependent colored noise.

    Each class gets a fixed mean image (low-frequency pattern), so models
    can actually fit it — loss decrease on this data is a meaningful
    smoke signal, unlike pure noise.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    protos = np.stack(
        [
            127.5
            + 80.0 * np.stack(
                [
                    np.sin(2 * np.pi * ((c + 1) * xx / 3 + c / num_classes)),
                    np.cos(2 * np.pi * ((c + 2) * yy / 3)),
                    np.sin(2 * np.pi * (xx + yy) * (c + 1) / 4),
                ],
                axis=-1,
            )
            for c in range(num_classes)
        ]
    )  # [C,32,32,3]
    noise = rng.normal(0.0, 24.0, size=(n, 32, 32, 3)).astype(np.float32)
    images = np.clip(protos[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels
