"""Per-replica sharded batch loading with async device prefetch.

The reference's loader stack (``data.py:31-53``): a ``DistributedSampler``
per rank + ``DataLoader(batch_size // world_size, num_workers=4,
pin_memory=True)``. Here one host feeds ALL its local replicas: each
replica's index stream comes from its own
:class:`..parallel.DistributedShardSampler` (index-exact with the
reference), the host assembles the per-host superbatch in device order,
and :func:`prefetch_to_device` double-buffers the H2D transfer so the
copy for step k+1 overlaps the compute of step k (the pinned-memory +
worker-process analogue).
"""

from __future__ import annotations

import collections
import threading
from typing import Iterator, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, data_axis_size
from ..parallel.sampler import DistributedShardSampler, padded_epoch_indices
from .transforms import normalize, random_crop_flip


class ShardedLoader:
    """Iterates epoch batches for the local replicas of the data axis.

    Args:
      images, labels: full dataset arrays (uint8 NHWC / int labels).
      batch_size: GLOBAL batch size (the reference divides by world_size,
        ``data.py:39``; per-replica batch = ``batch_size // world``).
      world_size: data-axis size.
      replica_ids: which replicas this host assembles (all of them on a
        single host; a sub-range under multi-host).
      train: apply random crop+flip augmentation.
      shuffle: epoch-seeded shuffle (the reference enables it for BOTH
        splits, ``data.py:31-37`` — test-set shuffling is behavior of
        record).
      drop_last: torch DataLoader default False keeps ragged final
        batches; per-shard counts stay equal because the SAMPLER pads to
        equal shards first (torch semantics).
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        *,
        batch_size: int,
        world_size: int,
        replica_ids: Optional[Sequence[int]] = None,
        train: bool = True,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        with_valid: bool = False,
    ):
        if batch_size % world_size:
            raise ValueError(
                f"global batch {batch_size} not divisible by world {world_size}"
            )
        self.images = images
        self.labels = labels
        self.batch_size = batch_size
        self.per_replica = batch_size // world_size
        self.world_size = world_size
        self.replica_ids = list(replica_ids) if replica_ids is not None else list(
            range(world_size)
        )
        self.train = train
        self.seed = seed
        self.shuffle = shuffle
        # samplers kept for shard metadata (num_samples, valid_mask); the
        # epoch permutation itself is drawn ONCE in __iter__ and sliced,
        # not re-drawn per replica.
        self.samplers = [
            DistributedShardSampler(
                len(images), r, world_size, shuffle=shuffle, seed=seed,
                drop_last=drop_last,
            )
            for r in self.replica_ids
        ]
        self.drop_last = drop_last
        self.with_valid = with_valid
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        for s in self.samplers:
            s.set_epoch(epoch)

    def __len__(self) -> int:
        n = self.samplers[0].num_samples
        if self.drop_last:
            return n // self.per_replica
        return -(-n // self.per_replica)

    @property
    def dataset_size(self) -> int:
        return len(self.images)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yields ``(images, labels)`` float32/int32 host superbatches of
        shape ``[len(replica_ids) * per_replica, ...]`` ordered by replica
        — slice i*per_replica:(i+1)*per_replica belongs to replica_ids[i],
        exactly what a ``P('data')`` sharding assigns to that device.
        With ``with_valid=True`` a bool validity vector is appended
        (False marks the sampler's wraparound-padding duplicates)."""
        padded = np.asarray(
            padded_epoch_indices(
                len(self.images),
                self.world_size,
                shuffle=self.shuffle,
                seed=self.seed,
                epoch=self._epoch,
                drop_last=self.drop_last,
            )
        )
        shards = [padded[r :: self.world_size] for r in self.replica_ids]
        valids = [s.valid_mask() for s in self.samplers]
        n_batches = len(self)
        # ONE augmentation stream PER REPLICA, seeded by (seed, epoch,
        # replica_id): a host assembling only replica r must draw
        # exactly the augmentations replica r would get on a single
        # host, or multi-host training silently diverges from the
        # equivalent single-host run (caught by the 2-host e2e test).
        aug_rngs = [
            np.random.default_rng(
                np.random.SeedSequence([self.seed, self._epoch, int(r)])
            )
            for r in self.replica_ids
        ]
        for b in range(n_batches):
            lo, hi = b * self.per_replica, (b + 1) * self.per_replica
            idx = np.concatenate([np.asarray(s[lo:hi]) for s in shards])
            imgs = self.images[idx]
            if self.train:
                # split by the ACTUAL per-replica chunk of this batch —
                # the final batch is ragged under drop_last=False, and
                # slicing by the nominal per_replica there would feed
                # rows to the wrong replica's stream
                imgs = np.concatenate([
                    random_crop_flip(part, rng)
                    for part, rng in zip(
                        np.array_split(imgs, len(aug_rngs)), aug_rngs)
                ])
            out = (normalize(imgs), self.labels[idx].astype(np.int32))
            if self.with_valid:
                valid = np.concatenate([v[lo:hi] for v in valids])
                out = out + (valid,)
            yield out


def prefetch_to_device(
    loader, mesh: Mesh, *, size: int = 2, axis_name: str = DATA_AXIS
):
    """Wrap a host batch iterator with sharded async device placement.

    ``jax.device_put`` is asynchronous — enqueueing the transfer for the
    next batch before the current step's results are consumed overlaps
    H2D with compute, which is what the reference buys with
    ``pin_memory=True`` + worker processes (``data.py:41-53``).
    """
    queue = collections.deque()
    multihost = jax.process_count() > 1

    def place(x):
        sharding = NamedSharding(
            mesh, P(axis_name, *([None] * (x.ndim - 1)))
        )
        if multihost:
            # each host contributes only its local replicas' rows
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    def put(batch):
        return jax.tree.map(place, batch)

    it = iter(loader)
    try:
        while len(queue) < size:
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out


def get_loader(args, mesh: Mesh, *, data=None):
    """Build (train_loader, test_loader) — reference ``get_loader``
    (``data.py:6-59``) reimagined per-host.

    ``args`` needs ``batch_size`` and optionally ``dataset`` (``cifar`` |
    ``imagenet``), ``data_root``, ``synthetic``, ``image_size``,
    ``num_classes``. ``data`` may inject ``(train_imgs, train_lbls,
    test_imgs, test_lbls)`` directly (tests). Prints the rank-0 dataset
    banner (``data.py:54-57``) minus the leftover debug prints of
    ``data.py:29-30``.
    """
    import jax

    from ..parallel import dist
    from .cifar import load_cifar10, synthetic_cifar10

    world = data_axis_size(mesh)
    # Multi-host: each host assembles only the replicas (data-axis coords)
    # whose devices it owns — the per-host half of DistributedSampler's
    # job. Mesh layout is jax.devices() order, so host p owns the
    # contiguous coord block [p*world/hosts, (p+1)*world/hosts).
    hosts = jax.process_count()
    if hosts > 1:
        if world % hosts:
            raise ValueError(
                f"data axis {world} not divisible by host count {hosts}"
            )
        per_host = world // hosts
        pid = jax.process_index()
        replica_ids = list(range(pid * per_host, (pid + 1) * per_host))
    else:
        replica_ids = None  # all replicas

    if data is None and getattr(args, "dataset", "cifar") == "imagenet":
        return _get_imagenet_loaders(args, world, replica_ids)

    if data is not None:
        tr_x, tr_y, te_x, te_y = data
    elif getattr(args, "synthetic", False):
        import os as _os

        # PMDT_SMALL_SYNTH shrinks the synthetic set for smoke tests/CI:
        # "1" (or any non-int) = 2048/512; an integer > 1 = that many
        # training samples (test set = 1/4 of it).
        small = _os.environ.get("PMDT_SMALL_SYNTH")
        if small:
            try:
                n = int(small)
            except ValueError:
                n = 1
            n_tr, n_te = (n, max(1, n // 4)) if n > 1 else (2048, 512)
        else:
            n_tr, n_te = (50000, 10000)
        tr_x, tr_y = synthetic_cifar10(n_tr, seed=0)
        te_x, te_y = synthetic_cifar10(n_te, seed=1)
    else:
        root = getattr(args, "data_root", "") or "./cifar10_data"
        tr_x, tr_y = load_cifar10(root, train=True)
        te_x, te_y = load_cifar10(root, train=False)

    train_loader = ShardedLoader(
        tr_x, tr_y, batch_size=args.batch_size, world_size=world, train=True,
        replica_ids=replica_ids,
    )
    test_loader = ShardedLoader(
        te_x, te_y, batch_size=args.batch_size, world_size=world, train=False,
        shuffle=True,  # reference shuffles the test sampler too (data.py:35-37)
        replica_ids=replica_ids,
        with_valid=True,  # exact eval accuracy under wraparound padding
    )
    if dist.is_primary():
        print("-------------------Make loader-------------------")
        print(
            "Train Dataset :", train_loader.dataset_size,
            "   Test Dataset :", test_loader.dataset_size,
        )
    return train_loader, test_loader


def _get_imagenet_loaders(args, world: int, replica_ids):
    """ImageNet-scale route of :func:`get_loader` (BASELINE.md configs
    #2/#3/#4): lazy :class:`..data.imagenet.IndexedLoader` over either the
    on-demand synthetic set (``--synthetic``) or a ``train/``+``val/``
    ImageFolder tree at ``--data_root``."""
    import os as _os

    from ..parallel import dist
    from .imagenet import FolderImageNet, IndexedLoader, SyntheticImageNet

    image_size = getattr(args, "image_size", None) or 224
    if getattr(args, "synthetic", False):
        num_classes = getattr(args, "num_classes", None) or 1000
        # PMDT_SMALL_SYNTH shrinks the nominal set for smoke tests/CI;
        # the full synthetic set is ImageNet-1k-sized (computed lazily —
        # nothing is materialized either way).
        n_tr, n_te = (
            (1024, 256) if _os.environ.get("PMDT_SMALL_SYNTH")
            else (1_281_167, 50_000)
        )
        train_ds = SyntheticImageNet(
            n_tr, image_size=image_size, num_classes=num_classes, seed=0
        )
        test_ds = SyntheticImageNet(
            n_te, image_size=image_size, num_classes=num_classes, seed=1
        )
    else:
        root = getattr(args, "data_root", "") or "./imagenet"
        train_ds = FolderImageNet(root, "train", image_size=image_size)
        test_ds = FolderImageNet(root, "val", image_size=image_size)

    train_loader = IndexedLoader(
        train_ds, batch_size=args.batch_size, world_size=world, train=True,
        replica_ids=replica_ids,
    )
    test_loader = IndexedLoader(
        test_ds, batch_size=args.batch_size, world_size=world, train=False,
        shuffle=True,  # test-sampler shuffling is behavior of record
        replica_ids=replica_ids,
        with_valid=True,
    )
    if dist.is_primary():
        print("-------------------Make loader-------------------")
        print(
            "Train Dataset :", train_loader.dataset_size,
            "   Test Dataset :", test_loader.dataset_size,
        )
    return train_loader, test_loader
