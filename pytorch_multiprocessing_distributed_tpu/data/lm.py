"""Token-stream data pipeline for language-model training.

The LM counterpart of the image ``ShardedLoader`` (``pipeline.py``): a
flat token stream is cut into fixed ``[batch, seq_len]`` windows and
epoch-seed shuffled. Unlike ``ShardedLoader`` this loader yields the
FULL global batch — the train step's ``P("data")`` in_spec does the
replica sharding (single-host; multi-host per-host assembly would need
``replica_ids`` parity with the image loader). No reference
counterpart (the reference is vision-only); built for
:func:`..train.lm.make_lm_train_step` /
:class:`..models.gpt.GPT`.

``synthetic_tokens`` generates a deterministic Zipf-ish stream so LM
training is runnable data-free, mirroring ``--synthetic`` for images.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def synthetic_tokens(n: int, vocab_size: int = 257, seed: int = 0) -> np.ndarray:
    """Deterministic pseudo-text: Zipf-distributed token stream.

    Zipf rather than uniform so models exhibit realistic early loss
    drops (frequent-token mass is learnable) — uniform streams plateau
    at ``log(V)`` and make smoke-test learnability assertions flaky.
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    return rng.choice(vocab_size, size=n, p=probs).astype(np.int32)


class TokenLoader:
    """Epoch iterator of ``[global_batch, seq_len]`` windows.

    Windows are NON-overlapping contiguous slices of the stream
    (window ``i`` = tokens ``[i*seq_len, (i+1)*seq_len + 1)`` is NOT
    used — the next-token shift happens inside the train step, so plain
    ``seq_len`` windows suffice). The final partial window is dropped
    (an LM step needs full static shapes).

    Args:
      tokens: 1-D int array, the corpus.
      batch_size: GLOBAL batch (split over ``world_size`` by the step's
        sharding, like the image loader).
      seq_len: tokens per sample.
      world_size: data-axis size; ``batch_size`` must divide by it.
      shuffle: epoch-seeded shuffle of window order.
      drop_last: drop the ragged final batch (default True: static
        shapes are what jit wants; False pads by wraparound like the
        sampler so every batch is full).
    """

    def __init__(
        self,
        tokens: np.ndarray,
        *,
        batch_size: int,
        seq_len: int,
        world_size: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        tokens = np.asarray(tokens)
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D, got shape {tokens.shape}")
        if batch_size % world_size:
            raise ValueError(
                f"global batch {batch_size} must divide by "
                f"world_size {world_size}"
            )
        n_windows = len(tokens) // seq_len
        if n_windows < batch_size:
            raise ValueError(
                f"corpus of {len(tokens)} tokens yields {n_windows} "
                f"windows of {seq_len} — fewer than one global batch "
                f"({batch_size})"
            )
        self.windows = tokens[: n_windows * seq_len].reshape(
            n_windows, seq_len
        )
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.world_size = world_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle (same contract as the image loader)."""
        self.epoch = epoch

    def __len__(self) -> int:
        n = len(self.windows)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self) -> Iterator[np.ndarray]:
        order = np.arange(len(self.windows))
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(order)
        n_batches = len(self)
        for b in range(n_batches):
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            if len(idx) < self.batch_size:
                # wraparound padding (sampler semantics) for the ragged
                # final batch when drop_last=False
                idx = np.concatenate(
                    [idx, order[: self.batch_size - len(idx)]]
                )
            yield self.windows[idx]
