"""Byte-level text corpus: raw text files -> LM token streams.

The zero-egress answer to "train on my text": no vocab files, no
downloaded tokenizer — every byte is a token (ids 0..255), and id 256
separates documents. That is exactly ``gpt_tiny``'s 257-token vocab, so
``train_lm.py --corpus my.txt`` works out of the box; larger vocabs
simply leave the rest of their embedding rows cold. Byte-level LMs are
a standard, competitive baseline (the reference has no text path at
all — SURVEY.md scopes it to CIFAR images).

Round trip is lossless: ``detokenize(tokenize(text)) == text`` for any
UTF-8 input (invalid sequences degrade to U+FFFD only at the final
string decode; the byte stream itself is preserved exactly).
"""

from __future__ import annotations

import os
from typing import Iterable, Union

import numpy as np

#: document-separator token id (first id past the byte range)
DOC_SEP = 256


def sniff_bytes(head: bytes) -> str:
    """Classify a file's leading bytes: ``'npy'`` (np.save), ``'npz'``
    (zip: np.savez), or ``'text'``. Magic bytes, not extension — numpy
    tooling output is all bytes <= 255, so byte-tokenizing it would
    pass every downstream vocab guard and train on garbage silently.
    Single source of truth for the CLI's ``--corpus`` sniff and the
    per-file guards below."""
    if head[:6] == b"\x93NUMPY":
        return "npy"
    if head[:4] == b"PK\x03\x04":
        return "npz"
    return "text"

#: smallest vocab that fits byte tokens + the separator
BYTE_VOCAB = 257


def tokenize(text: Union[str, bytes]) -> np.ndarray:
    """Text (or raw bytes) -> int32 token ids in [0, 255]."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.frombuffer(text, dtype=np.uint8).astype(np.int32)


def detokenize(tokens: Iterable[int]) -> str:
    """Token ids -> text. Ids > 255 (DOC_SEP, or cold ids a model with a
    larger vocab may emit early in training) become newlines rather than
    corrupting the byte stream."""
    arr = np.asarray(list(tokens) if not hasattr(tokens, "astype")
                     else tokens).astype(np.int64).ravel()
    arr = np.where(arr > 255, np.int64(ord("\n")), arr)
    arr = np.where(arr < 0, np.int64(ord("\n")), arr)
    return arr.astype(np.uint8).tobytes().decode("utf-8", errors="replace")


def load_text_corpus(path: str) -> np.ndarray:
    """A ``.txt``/arbitrary file — or a directory of them — as one
    int32 token stream, files joined by :data:`DOC_SEP`.

    Directory mode reads every regular file in sorted order (stable
    across hosts — the loaders shard this stream deterministically)."""
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if os.path.isfile(os.path.join(path, n))
        )
        if not names:
            raise FileNotFoundError(f"no files under corpus dir {path}")
        parts = []
        for k, name in enumerate(names):
            if k:
                parts.append(np.asarray([DOC_SEP], np.int32))
            with open(os.path.join(path, name), "rb") as f:
                data = f.read()
            if sniff_bytes(data) != "text":
                raise ValueError(
                    f"corpus dir {path} contains numpy tooling output "
                    f"({name!r}) — pass the .npy array directly as "
                    "--corpus, or keep only text files in the directory")
            parts.append(tokenize(data))
        return np.concatenate(parts)
    with open(path, "rb") as f:
        data = f.read()
    if sniff_bytes(data) != "text":
        raise ValueError(
            f"{path} is numpy tooling output, not text — load it with "
            "np.load (the train_lm CLI does this for .npy --corpus "
            "files automatically)")
    return tokenize(data)
