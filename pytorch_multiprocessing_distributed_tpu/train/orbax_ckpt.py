"""Sharded (optionally async) checkpointing via Orbax.

The msgpack writer (:mod:`.checkpoint`) is artifact-parity-first: ONE
``model_{epoch}.pth`` file matching the reference's naming
(``main.py:75-77``), byte-stable and torch-interoperable. Its cost at
scale is structural: every sharded leaf is all-gathered onto the
primary host before serialization — O(model) extra HBM + host RAM +
cross-host traffic per save, and training stalls for the whole write.

This backend is the TPU-native path for large sharded states (ZeRO-1 /
FSDP / TP / pipelined): each host writes only the shards it owns
(OCDBT), restore places shards directly onto the target sharding with
no gather anywhere, and ``async_=True`` overlaps serialization with
the next training steps (the classic TPU checkpoint pattern). The two
backends share retention and auto-resume semantics; they differ only
in artifact shape (directory-per-epoch vs one file).

No reference counterpart (the reference has save-only torch.save,
SURVEY.md §5 "Checkpoint / resume"); this is framework surface the
scale story requires.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..runtime.faults import maybe_fault, register_site
from .state import TrainState

# the sharded-writer hazard point: a failed orbax commit must surface
# as ITS error at the save call (orbax's manager keeps partial step
# dirs out of all_steps(), so a failed save never becomes a resume
# candidate — the fault matrix pins the fail-fast side here)
_SITE_SAVE = register_site(
    "train.orbax_save", "orbax sharded checkpoint save/commit")


class OrbaxCheckpointer:
    """Epoch-keyed sharded checkpoints under ``{save_path}/orbax/``.

    Drop-in peer of the msgpack trio (``save_checkpoint`` /
    ``latest_checkpoint`` / ``prune_checkpoints``): ``save(state,
    epoch)``, ``latest_epoch()``, retention via ``keep``. All hosts
    must call ``save``/``restore`` (orbax coordinates the multi-host
    write/read); there is no primary-host gating to get wrong.

    Args:
      save_path: experiment directory (the ``orbax/`` subdir is
        created inside it).
      keep: retain only the newest K epochs (None/0 = keep all) —
        mirrors ``--keep_checkpoints``.
      async_: overlap serialization with training; ``wait()`` (or
        ``close()``) blocks until the last save is durable. The
        preemption path must use ``async_=False`` semantics — call
        ``wait()`` right after ``save`` — because the process exits
        immediately afterwards.
    """

    def __init__(self, save_path: str, *, keep: Optional[int] = None,
                 async_: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(os.path.join(save_path, "orbax"))
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep or None,
                enable_async_checkpointing=async_,
                create=True,
            ),
        )

    def save(self, state: TrainState, epoch: int) -> str:
        """Write ``state`` under step key ``epoch``; returns the epoch
        directory path (which exists once the save is durable — see
        ``async_``).

        Overwrites an existing epoch key: re-running an experiment into
        the same save_path (or resuming from an earlier epoch) replaces
        the artifact, matching the msgpack writer's ``model_{epoch}.pth``
        semantics — orbax's default would raise StepAlreadyExistsError
        after a full epoch of training."""
        # settle in-flight async work FIRST: an epoch whose commit is
        # mid-flight is invisible to has_epoch, and a blind re-save of
        # it would raise StepAlreadyExistsError (observed shape: async
        # periodic save + SIGTERM re-saving the same resume point)
        maybe_fault(_SITE_SAVE)
        self.manager.wait_until_finished()
        if self.has_epoch(epoch):
            self.manager.delete(epoch)
        self.manager.save(epoch, args=self._ocp.args.StandardSave(state))
        return os.path.join(self.directory, str(epoch))

    def has_epoch(self, epoch: int) -> bool:
        return epoch in (self.manager.all_steps() or [])

    def restore(self, template: TrainState,
                epoch: Optional[int] = None) -> TrainState:
        """Restore epoch (default: latest) INTO ``template``'s
        structure, dtypes, and shardings — sharded leaves come back
        sharded exactly as the template's, with each host reading only
        its own shards."""
        if epoch is None:
            epoch = self.latest_epoch()
            if epoch is None:
                raise FileNotFoundError(
                    f"no orbax checkpoint under {self.directory}"
                )
        return self.manager.restore(
            epoch, args=self._ocp.args.StandardRestore(template)
        )

    def latest_epoch(self) -> Optional[int]:
        """Newest saved epoch, PRIMARY-verdict-broadcast under
        multi-host: per-host resolution can disagree (NFS
        attribute-cache staleness, partially visible OCDBT commits)
        and misaligned start epochs deadlock the per-epoch
        collectives — same pattern as
        ``checkpoint.resolve_auto_resume``. Every caller gets the
        broadcast for free (main.py previously inlined it)."""
        epoch = self.manager.latest_step()
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils

            epoch = int(multihost_utils.broadcast_one_to_all(
                np.int32(-1 if epoch is None else epoch)
            ))
            epoch = None if epoch < 0 else epoch
        return epoch

    def wait(self) -> None:
        """Block until any in-flight async save is durable."""
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()

    def __enter__(self) -> "OrbaxCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
