"""Training engine: optimizer transforms, SPMD step, loops, checkpointing.

TPU-native replacement for the reference's training engine
(``main.py:32-177``): the per-process ``main``/``train``/``validate``
trio becomes a jitted SPMD step over the mesh plus host-side epoch loops
that reproduce the reference's meters, stdout format and log rows.
"""

from .optim import sgd, multistep_lr, OptState, Transform
from .state import TrainState, create_train_state
from .step import (
    make_train_step,
    make_eval_step,
    make_train_step_tp,
    make_eval_step_tp,
    shard_state,
    state_shardings,
    tp_param_spec,
)
from .checkpoint import save_checkpoint, load_checkpoint
from .orbax_ckpt import OrbaxCheckpointer

__all__ = [
    "sgd",
    "multistep_lr",
    "OptState",
    "Transform",
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_eval_step",
    "make_train_step_tp",
    "make_eval_step_tp",
    "shard_state",
    "state_shardings",
    "tp_param_spec",
    "save_checkpoint",
    "load_checkpoint",
    "OrbaxCheckpointer",
]
