"""In-framework optimizer transforms and LR schedules.

Pure-functional (optax-style) re-implementation of the exact update rule
the reference configures (``main.py:51-59``): SGD with lr 0.1, momentum
0.9, weight decay 1e-4, Nesterov, under a MultiStepLR(milestones=[60,80],
gamma=0.1) epoch schedule. Parity with ``torch.optim.SGD`` is pinned by
trajectory tests (``tests/test_optim.py``).

torch SGD semantics reproduced exactly:
  g   = grad + wd * param
  buf = momentum * buf + g          (first step: buf = g)
  d   = g + momentum * buf          (nesterov)  |  d = buf (classical)
  param -= lr * d

The schedule quirk of record (SURVEY.md §3.5.1 — the reference steps the
scheduler only on rank 0, silently diverging LR across ranks): here the
schedule is a pure function of the epoch, evaluated identically on every
replica. At the reference's defaults (20 epochs) the milestones never
fire, so behavior is bit-identical.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Sequence, Union

import jax
import jax.numpy as jnp

Schedule = Callable[[jax.Array], jax.Array]  # step/epoch -> lr
ScalarOrSchedule = Union[float, Schedule]


class OptState(NamedTuple):
    """State threaded through updates: momentum buffers + step count."""

    momentum: Any  # pytree like params (zeros-initialized buffers)
    count: jax.Array  # number of updates applied
    initialized: jax.Array  # False until the first update (torch buf init)


class Transform(NamedTuple):
    """A gradient transform: ``init(params) -> state``,
    ``update(grads, state, params, lr_scale) -> (updates, state)``.

    ``updates`` are ADDED to params (they carry the minus sign), matching
    ``jax.tree.map(lambda p, u: p + u, params, updates)``.

    ``apply`` (optional): fused whole-update path
    ``apply(grads, state, params, lr_step) -> (new_params, new_state)``.
    When set, the train step uses it instead of ``update`` +
    ``apply_updates`` — the seam for single-pass Pallas updates
    (:func:`..ops.pallas.sgd_pallas`).

    ``shard_update``/``shard_finish`` (optional, graftzero): the
    ZeRO-1 split. ``shard_update`` has ``update``'s signature but
    returns the pre-finish update DIRECTION (everything elementwise —
    it runs on flat 1-D shards of the parameter space);
    ``shard_finish(updates, params, lr_step) -> updates`` applies the
    post-gather phase (the LR scale; LAMB adds its per-leaf trust
    ratio) on full leaves. BOTH shipped transforms define the pair —
    keeping the final leafwise ops in the same fusion context as the
    replicated update is what makes sharded == replicated bitwise. A
    custom transform may leave both unset; graftzero then runs its
    unmodified ``update`` directly on the shards, which is only
    correct if that update is purely elementwise.
    """

    init: Callable[[Any], OptState]
    update: Callable[..., Any]
    apply: Any = None
    shard_update: Any = None
    shard_finish: Any = None


def multistep_lr(
    base_lr: float, milestones: Sequence[int] = (60, 80), gamma: float = 0.1
) -> Schedule:
    """torch ``MultiStepLR``: lr = base * gamma^(#milestones <= epoch).

    The reference calls ``scheduler.step()`` at the top of each epoch
    (``main.py:69-70``), so the drop takes effect for the milestone epoch
    itself — this closed form reproduces that.
    """
    ms = jnp.asarray(sorted(milestones))

    def schedule(epoch) -> jax.Array:
        n_passed = jnp.sum(jnp.asarray(epoch) >= ms)
        return base_lr * jnp.power(gamma, n_passed.astype(jnp.float32))

    return schedule


def cosine_lr(
    base_lr: float, total_epochs: int, warmup_epochs: int = 0,
    min_lr: float = 0.0,
) -> Schedule:
    """Cosine decay with optional linear warmup (epoch-indexed, like the
    reference's per-epoch MultiStepLR; epochs count from 1).

    ``lr(e) = min_lr + (base - min_lr) * (1 + cos(pi * t)) / 2`` with
    ``t = (e - warmup - 1) / (total - warmup)`` — torch
    ``CosineAnnealingLR`` indexing: the FIRST post-warmup epoch trains
    at ``base`` and the LAST trains just above ``min_lr`` (never AT it —
    the trainer sets the epoch before training, so mapping the final
    epoch to t=1 would spend a whole epoch at lr=min, doing nothing at
    the default min_lr=0). During warmup the LR ramps linearly from
    ``base/warmup`` to ``base``.
    """
    if total_epochs < 1:
        raise ValueError(f"total_epochs must be >= 1, got {total_epochs}")
    if not 0 <= warmup_epochs < total_epochs:
        raise ValueError(
            f"warmup_epochs must be in [0, total_epochs), got "
            f"{warmup_epochs} of {total_epochs}"
        )

    def schedule(epoch) -> jax.Array:
        e = jnp.asarray(epoch, jnp.float32)
        warm = base_lr * e / jnp.maximum(warmup_epochs, 1)
        span = jnp.maximum(total_epochs - warmup_epochs, 1)
        t = jnp.clip((e - warmup_epochs - 1) / span, 0.0, 1.0)
        cos = min_lr + (base_lr - min_lr) * 0.5 * (1 + jnp.cos(jnp.pi * t))
        return jnp.where(e <= warmup_epochs, warm, cos)

    return schedule


def sgd(
    learning_rate: ScalarOrSchedule = 0.1,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    nesterov: bool = True,
) -> Transform:
    """torch-exact SGD(momentum, weight_decay, nesterov) as a pure transform.

    ``learning_rate`` may be a float or a schedule evaluated on the value
    passed as ``lr_step`` to ``update`` (the trainer passes the epoch,
    matching the reference's per-epoch MultiStepLR).
    """

    def init(params) -> OptState:
        zeros = jax.tree.map(jnp.zeros_like, params)
        return OptState(
            momentum=zeros,
            count=jnp.zeros((), jnp.int32),
            initialized=jnp.zeros((), jnp.bool_),
        )

    def shard_update(grads, state: OptState, params, lr_step=None):
        """The ELEMENTWISE phase: weight decay + momentum + nesterov
        combine, returning the update DIRECTION ``d`` (no LR). Runs
        identically on full leaves and on graftzero's flat 1-D shards;
        the LR scale stays in ``shard_finish`` so the zero path's
        post-gather leafwise ops mirror the replicated update's exactly
        (same final fusion context -> bit-identical trajectories)."""

        def one(g, p, buf):
            g = g + weight_decay * p
            # torch lazily initializes buf = g on the first step (not
            # momentum*0 + g — identical value, kept for clarity).
            new_buf = jnp.where(state.initialized, momentum * buf + g, g)
            d = g + momentum * new_buf if nesterov else new_buf
            return d, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum)
        is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
        directions = jax.tree.map(lambda t: t[0], flat, is_leaf=is_pair)
        bufs = jax.tree.map(lambda t: t[1], flat, is_leaf=is_pair)
        new_state = OptState(
            momentum=bufs,
            count=state.count + 1,
            initialized=jnp.ones((), jnp.bool_),
        )
        return directions, new_state

    def shard_finish(updates, params, lr_step=None):
        if callable(learning_rate):
            lr = learning_rate(lr_step)
        else:
            lr = jnp.asarray(learning_rate, jnp.float32)
        return jax.tree.map(lambda d: -lr * d, updates)

    def update(grads, state: OptState, params, lr_step=None):
        # the replicated update IS the two phases composed — one copy
        # of the math, so graftzero's sharded run == replicated run
        d, new_state = shard_update(grads, state, params, lr_step=lr_step)
        return shard_finish(d, params, lr_step=lr_step), new_state

    return Transform(init, update, shard_update=shard_update,
                     shard_finish=shard_finish)


def apply_updates(params, updates):
    """``param + update`` over the tree (updates carry the minus sign)."""
    return jax.tree.map(lambda p, u: p + u, params, updates)
