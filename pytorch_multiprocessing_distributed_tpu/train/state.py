"""Train state: the one pytree threaded through the compiled step.

Bundles what the reference keeps as three mutable objects (the DDP module
buffers, ``optimizer`` state and the epoch counter, ``main.py:42-59``)
into a single immutable pytree, so the whole update is one XLA program
with donated inputs (no host round-trips between forward, all-reduce and
the optimizer, unlike the reference's ``loss.backward(); optimizer.step()``
split at ``main.py:108-110``).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp

from .optim import OptState, Transform


@flax.struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: OptState
    epoch: jax.Array  # current epoch (drives the LR schedule)
    # Exponential moving average of params ({} = EMA off). A dict rather
    # than Optional so the pytree STRUCTURE is stable for jit caching and
    # msgpack round-trips; populated by create_train_state(ema=True).
    ema_params: Any = flax.struct.field(default_factory=dict)


def create_train_state(model, rng, sample_input, optimizer: Transform,
                       ema: bool = False) -> TrainState:
    """Initialize model variables + optimizer buffers.

    Weight layout note: under SPMD there is no DDP-style rank-0 broadcast
    (reference relies on DDP's ctor broadcast, ``main.py:44``) — every
    replica computes the same initialization from the same seed.

    ``ema=True`` seeds an EMA copy of the params (tracked in-step by
    the trainer's ``ema_decay``; used for evaluation/checkpointing).
    """
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        epoch=jnp.ones((), jnp.int32),
        ema_params=jax.tree.map(jnp.array, params) if ema else {},
    )
