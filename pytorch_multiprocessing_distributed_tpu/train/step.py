"""The compiled SPMD train/eval step.

This is the parity moment for the reference's hot loop (``main.py:
101-110``): H2D copy, DDP forward (with SyncBatchNorm stat exchange),
cross-entropy, backward with bucketed NCCL all-reduce, SGD step. Here the
entire iteration is ONE jitted ``shard_map`` program over the mesh:

- the global batch arrives sharded over the ``data`` axis (per-replica
  slice = ``batch // world_size``, reference ``data.py:39``);
- params/optimizer state are replicated; the model's BatchNorm binds the
  ``data`` axis name, so batch statistics are ``pmean``-synced in-step
  (== SyncBatchNorm, reference ``main.py:43``);
- gradients are ``pmean``-ed over ``data`` — DDP averages gradients by
  world size, and XLA lowers this to the same ring all-reduce NCCL would
  run, but fused into the step and riding ICI;
- loss / prec@1 / correct counts are reduced in-step, so the host reads
  back three scalars instead of shipping logits (the reference pays a
  device->host sync per batch for ``.item()`` at ``main.py:113-115``).

State is donated: params are updated in place in HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.losses import cross_entropy_loss, cross_entropy_per_sample
from ..runtime import hbm
from ..utils.compat import shard_map
from ..utils.metrics import topk_accuracy
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
from .optim import Transform, apply_updates
from .state import TrainState


def _train_body(model, optimizer: Transform, loss_fn: Callable,
                axis_name: Optional[str], remat: bool = False,
                grad_accum: int = 1, dp_size: int = 1,
                clip_grad_norm: Optional[float] = None,
                ema_decay: Optional[float] = None,
                zero_plan=None, zero_overlap: bool = True):
    """The one train-step body both parallelism paths share.

    ``axis_name`` set: per-shard view under ``shard_map`` — grads/metrics
    are explicitly ``pmean``/``psum``-ed over the data axis (the DDP
    analogue). ``axis_name=None``: global view under GSPMD jit — the loss
    is already a global mean, so autodiff produces the reduction and the
    collective calls drop out.

    ``remat``: wrap the forward in ``jax.checkpoint`` so the backward
    recomputes activations instead of keeping them resident in HBM —
    the standard TPU memory/FLOPs trade that buys batch sizes the chip
    could not otherwise hold (~1.3x step time for ~the forward's
    activation footprint back).

    ``grad_accum``: split the batch into this many microbatches and run
    them sequentially under ``lax.scan``, summing gradients, before the
    ONE optimizer step — the standard large-global-batch trade (activation
    memory of one microbatch, one all-reduce, one weight update). The
    microbatch split is STRIDED (sample ``i`` goes to microbatch
    ``i % grad_accum``) so that under GSPMD the batch dimension's
    data-axis sharding stays device-local through the reshape — a
    contiguous split would gather each microbatch from a subset of
    devices (an all-to-all). BatchNorm statistics are computed per
    microbatch and the running stats see ``grad_accum`` momentum updates
    per step (torch grad-accumulation semantics: N small forwards).
    """

    if grad_accum < 1:
        raise ValueError(
            f"grad_accum must be >= 1, got {grad_accum} (1 = no "
            "accumulation; 0/negative would silently disable it)"
        )
    if clip_grad_norm is not None and not clip_grad_norm > 0:
        raise ValueError(
            f"clip_grad_norm must be > 0, got {clip_grad_norm} (a "
            "negative bound would NEGATE gradients; pass None to disable)"
        )
    if ema_decay is not None and not 0.0 < ema_decay < 1.0:
        raise ValueError(
            f"ema_decay must be in (0, 1), got {ema_decay} (>= 1 "
            "diverges exponentially; pass None to disable)"
        )

    def grad_of(params, stats, images, labels):
        def compute_loss(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            return loss_fn(logits, labels), (logits, mutated["batch_stats"])

        if remat:
            compute_loss = jax.checkpoint(compute_loss)
        return jax.value_and_grad(compute_loss, has_aux=True)(params)

    def body(state: TrainState, images, labels):
        if grad_accum > 1:
            b = images.shape[0]
            # Under shard_map ``b`` IS the per-device batch; under GSPMD
            # it is global, and the PER-DEVICE batch (b / dp) must still
            # divide by grad_accum or the strided microbatch reshape
            # loses its device-locality (GSPMD would silently insert an
            # all-to-all per microbatch — the cost this split avoids).
            if b % (grad_accum * dp_size):
                if axis_name is None:
                    detail = (f"global batch {b}, data-parallel degree "
                              f"{dp_size}")
                    per_dev = b // dp_size
                else:
                    # shard_map body: b is already the PER-DEVICE batch
                    detail = (f"per-device batch {b} as seen inside "
                              f"shard_map; the global batch is b x "
                              f"world_size")
                    per_dev = b
                raise ValueError(
                    f"per-device batch {per_dev} is not divisible by "
                    f"grad_accum={grad_accum} ({detail})"
                )

            def to_micro(x):
                return strided_microbatches(x, grad_accum)

            def micro(carry, mb):
                stats, gsum, lsum, csum = carry
                imgs, labs = mb
                (loss, (logits, new_stats)), grads = grad_of(
                    state.params, stats, imgs, labs
                )
                pred = jnp.argmax(logits, axis=-1)
                corr = jnp.sum((pred == labs).astype(jnp.int32))
                gsum = jax.tree.map(jnp.add, gsum, grads)
                return (new_stats, gsum, lsum + loss, csum + corr), None

            carry0 = (
                state.batch_stats,
                jax.tree.map(jnp.zeros_like, state.params),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.int32),
            )
            (new_stats, gsum, lsum, correct), _ = jax.lax.scan(
                micro, carry0, (to_micro(images), to_micro(labels))
            )
            # equal-sized microbatches: mean of means == global mean
            grads = jax.tree.map(lambda g: g / grad_accum, gsum)
            loss = lsum / grad_accum
        else:
            (loss, (logits, new_stats)), grads = grad_of(
                state.params, state.batch_stats, images, labels
            )
            pred = jnp.argmax(logits, axis=-1)
            correct = jnp.sum((pred == labels).astype(jnp.int32))

        if zero_plan is not None:
            # graftzero (parallel/zero.py): the grad psum + replicated
            # update becomes reduce-scatter -> sharded update ->
            # all-gather; the guard predicate moves to the scattered
            # shards (same values, partitioned) with ONE summed scalar
            # psum, still BEFORE clipping
            from ..parallel import zero as zero_mod

            g_shards = zero_mod.reduce_scatter_grads(
                grads, zero_plan, axis_name, mean=True,
                overlap=zero_overlap)
            finite = zero_mod.finite_shards(g_shards, axis_name)
            if clip_grad_norm is not None:
                g_shards = zero_mod.clip_shards_by_global_norm(
                    g_shards, axis_name, clip_grad_norm)
            new_params, new_opt = zero_mod.apply_sharded_update(
                optimizer, state.opt_state, g_shards, state.params,
                axis_name, lr_step=state.epoch, overlap=zero_overlap)
        else:
            if axis_name is not None:
                # The DDP all-reduce moment (reference main.py:109):
                # average gradients across the data axis. BN stats were
                # already pmean-ed inside the forward (axis bound by
                # shard_map).
                grads = jax.lax.pmean(grads, axis_name)

            # NaN/inf guard predicate off the AVERAGED grads
            # (replicated, so every shard agrees) and BEFORE clipping —
            # a non-finite norm would poison the clip scale itself
            finite = finite_grads(grads)

            if clip_grad_norm is not None:
                # Global-norm clipping of the ALREADY-averaged
                # gradients (torch.nn.utils.clip_grad_norm_ semantics:
                # one norm over every leaf; scale only when the norm
                # exceeds the bound).
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grads)
                ))
                scale = jnp.minimum(1.0, clip_grad_norm / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * scale, grads)

            if getattr(optimizer, "apply", None) is not None:
                # fused whole-update path (the Pallas single-pass SGD)
                new_params, new_opt = optimizer.apply(
                    grads, state.opt_state, state.params,
                    lr_step=state.epoch
                )
            else:
                updates, new_opt = optimizer.update(
                    grads, state.opt_state, state.params,
                    lr_step=state.epoch
                )
                new_params = apply_updates(state.params, updates)

        count = jnp.asarray(labels.shape[0], jnp.int32)
        if axis_name is not None:
            loss = jax.lax.pmean(loss, axis_name)
            correct = jax.lax.psum(correct, axis_name)
            count = jax.lax.psum(count, axis_name)
        metrics = {"loss": loss, "correct": correct, "count": count}
        metrics["prec1"] = 100.0 * correct / count

        new_state = state.replace(
            params=new_params, batch_stats=new_stats, opt_state=new_opt
        )
        if ema_decay is not None and state.ema_params:
            new_state = new_state.replace(
                ema_params=jax.tree.map(
                    lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
                    state.ema_params, new_params,
                )
            )
        new_state, metrics = guard_nonfinite(finite, new_state, state,
                                             metrics)
        return new_state, metrics

    return body


def make_train_step(
    model,
    optimizer: Transform,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    axis_name: str = DATA_AXIS,
    remat: bool = False,
    grad_accum: int = 1,
    clip_grad_norm=None,
    ema_decay=None,
    zero: bool = False,
    zero_overlap: bool = True,
):
    """Build the jitted DP train step.

    Returns ``step(state, images, labels) -> (state, metrics)`` where
    ``metrics = {loss, prec1, correct, count}`` are already globally
    reduced (scalars, replicated).

    ``zero=True`` (graftzero, ``parallel/zero.py``): gradients are
    reduce-scattered along the data axis into per-rank flat shards, the
    optimizer update runs on the local shard only (moments sharded —
    the state must carry a :class:`..parallel.zero.ZeroOptState`, build
    it with ``zero.zeroify_state``), and updated params are
    all-gathered back. Same trajectory bit-for-bit (test-pinned;
    exception: ``clip_grad_norm``, whose global norm is necessarily a
    psum of per-shard partial sums — a different summation order than
    the replicated leafwise norm, so clipped runs agree to float
    reassociation tolerance rather than bitwise). Optimizer HBM drops
    ~1/N per chip. ``zero_overlap=False`` serializes the bucketed
    collectives behind the full backward (the bench's overlap
    baseline).
    """
    if not zero:
        sharded = shard_map(
            _train_body(model, optimizer, loss_fn, axis_name,
                        remat=remat, grad_accum=grad_accum,
                        clip_grad_norm=clip_grad_norm,
                        ema_decay=ema_decay),
            mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=(0,))
    return _lazy_zero_step(
        lambda plan: _train_body(
            model, optimizer, loss_fn, axis_name, remat=remat,
            grad_accum=grad_accum, clip_grad_norm=clip_grad_norm,
            ema_decay=ema_decay, zero_plan=plan,
            zero_overlap=zero_overlap),
        mesh, axis_name, n_batch_args=2)


def _lazy_zero_step(make_body, mesh: Mesh, axis_name: str,
                    n_batch_args: int, entry=None):
    """Lazily-bound graftzero jit: shard_map in/out specs depend on the
    state's bucket layout (``ZeroOptState.plan``), so the program binds
    on first call keyed on the state's pytree structure — the shard_map
    twin of :func:`lazy_gspmd_jit`, shared by the image and LM DP
    steps. ``entry(step_fn) -> step_fn`` optionally wraps the jitted
    callee (the LM path's trace-time shape validation).

    The returned step also emits the ``train.grad_comm`` instant on the
    graftscope bus and a fleet arrival stamp per dispatch — the STATIC
    per-step collective bytes from the plan (the
    ``fleet.static_collective_bytes`` discipline: never a device read,
    never a dispatch-only stopwatch), feeding the straggler report's
    byte join. Disarmed cost: two module-global reads.
    """
    from ..parallel import zero as zero_mod
    from ..runtime import fleet as graftfleet
    from ..runtime import scope as graftscope

    compiled = {}

    def _bind(state):
        if not isinstance(state.opt_state, zero_mod.ZeroOptState):
            raise ValueError(
                "zero=True needs a zero-sharded state — build it with "
                "parallel.zero.zeroify_state(state, mesh) after init/"
                "resume")
        key = jax.tree.structure(state)
        if key not in compiled:
            spec = zero_mod.train_state_specs(state, axis_name)
            sharded = shard_map(
                make_body(state.opt_state.plan),
                mesh=mesh,
                in_specs=(spec,) + (P(axis_name),) * n_batch_args,
                out_specs=(spec, P()),
                check_vma=False,
            )
            if entry is not None:
                sharded = entry(sharded)
            compiled[key] = jax.jit(sharded, donate_argnums=(0,))
        return compiled[key]

    def step(state, *args):
        fn = _bind(state)
        if (graftscope.active_scope() is not None
                or graftfleet.active_fleet() is not None):
            plan = state.opt_state.plan
            comm = zero_mod.static_comm_bytes(plan)
            nbytes = comm["reduce_scatter"] + comm["all_gather"]
            graftscope.emit(
                "train.grad_comm", cat="train", nbytes=nbytes,
                buckets=len(plan.buckets), axis=axis_name,
                bucket_bytes=[
                    b.padded * jnp.dtype(b.dtype).itemsize
                    for b in plan.buckets])
            graftfleet.note_arrival("train.grad_comm", axis=axis_name,
                                    nbytes=nbytes)
        return fn(state, *args)

    # graftcheck's lowering handle (the lazy_gspmd_jit contract): the
    # underlying jax.jit program for a given state structure, so the
    # donation/HLO audits interrogate the EXACT program the trainer
    # runs (abstract states work — structure + plan are all it reads)
    step.jit_program = _bind
    return step


def make_eval_step(
    model,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    loss_fn: Callable = cross_entropy_loss,
):
    """Build the jitted eval step (reference ``validate`` inner loop,
    ``main.py:144-151``): forward in eval mode (running BN stats), loss +
    correct-count, globally reduced.

    Two fixes over the reference's eval semantics:
    - the correct count is ``psum``-ed across the data axis (the
      reference divides a per-rank count by the FULL dataset size,
      ``main.py:151,168`` — wrong by ~world_size; its ``reduce_tensor``
      fix is dead code);
    - a per-sample validity mask excludes the sampler's wraparound-
      padding duplicates, so accuracy is exact even when the dataset
      size is not divisible by world_size (SURVEY.md §3.5.3).

    Returns ``step(state, images, labels, valid) -> metrics`` with
    ``metrics = {loss, loss_sum, correct, correct5, count, prec1,
    prec5}``; the sums/counts are masked sums over REAL samples only
    (``correct5``/``prec5`` = top-5, the metric the reference's README
    quotes but never computes — the trainer's stdout/log formats ignore
    it for reference parity; library callers read it from the dict).
    """

    sharded = shard_map(
        _eval_body(model, axis_name, loss_fn=loss_fn),
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def _eval_body(model, axis_name: Optional[str],
               loss_fn: Callable = cross_entropy_loss):
    """Shared eval body (masked-validity accounting) for both paths —
    explicit ``psum`` under ``shard_map`` when ``axis_name`` is set,
    global sums under GSPMD jit when it is ``None``.

    The per-sample criterion mirrors the TRAIN loss (``loss_fn``'s
    ``.per_sample`` companion when it has one — e.g. label smoothing —
    plain cross-entropy otherwise), so train/test losses stay
    comparable, like the reference's shared ``criterion`` (main.py:48).
    """
    per_sample = getattr(loss_fn, "per_sample", cross_entropy_per_sample)

    def body(state: TrainState, images, labels, valid):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )
        w = valid.astype(jnp.float32)
        per_sample_loss = per_sample(logits, labels)
        pred = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((pred == labels).astype(jnp.float32) * w)
        # top-5: the metric the reference's README quotes but its code
        # never computes (README.md:13-17 vs main.py:129-130); provided
        # at the metrics level, stdout/log formats stay reference-exact.
        # The [maxk, batch] correctness matrix comes from the SAME
        # jittable helper the meters use (utils/metrics.topk_accuracy).
        k = min(5, logits.shape[-1])
        _, correct_mat = topk_accuracy(logits, labels, topk=(k,))
        in_top5 = jnp.any(correct_mat, axis=0)
        correct5 = jnp.sum(in_top5.astype(jnp.float32) * w)
        loss_sum = jnp.sum(per_sample_loss * w)
        count = jnp.sum(w)
        if axis_name is not None:
            loss_sum, correct, correct5, count = jax.lax.psum(
                (loss_sum, correct, correct5, count), axis_name
            )
        metrics = {
            "loss_sum": loss_sum,
            "correct": correct.astype(jnp.int32),
            "correct5": correct5.astype(jnp.int32),
            "count": count.astype(jnp.int32),
        }
        safe = jnp.maximum(metrics["count"], 1)
        metrics["loss"] = loss_sum / safe
        metrics["prec1"] = 100.0 * metrics["correct"] / safe
        metrics["prec5"] = 100.0 * metrics["correct5"] / safe
        return metrics

    return body


def _check_tp_model(model) -> None:
    """The GSPMD path's one model contract, enforced where it matters.

    Under global-semantics jit there is no bound mesh axis, so a model
    built with ``bn_axis="data"`` would crash deep inside BatchNorm at
    trace time with an unbound-axis error and no pointer here. (BN stats
    are global by construction on this path — ``bn_axis=None`` IS
    sync-BN.)
    """
    if getattr(model, "bn_axis", None) is not None:
        raise ValueError(
            "make_*_step_tp requires a model built with bn_axis=None: "
            "under GSPMD jit batch statistics are computed over the "
            f"global batch (= sync-BN); got bn_axis={model.bn_axis!r}. "
            "Build the model with bn_axis=None for model_parallel > 1 "
            "(see main.py)."
        )


def finite_grads(grads):
    """On-device all-finite predicate over a gradient tree — the
    NaN/inf skip-and-count guard's ONE scalar bool. No host sync: the
    step SELECTS between updated and carried state with it, and the
    skip indicator rides the metrics dict (``skipped``) into the
    trainer's existing windowed metric fetches like every other
    scalar. A single poisoned batch (loss overflow, corrupt record)
    then costs one skipped step instead of NaN'd params and momenta
    forever.

    The reduction SHAPE matters under GSPMD: a per-leaf
    ``all(isfinite)`` AND-chain lowers to one tiny pred all-reduce PER
    LEAF on a sharded step (~+38 serialized collective launches per
    step for the FSDP/TP LM steps, each paying fixed launch latency
    on a pod). Summing per-leaf non-finite COUNTS keeps every
    cross-leaf combine an ADD, the one form XLA's AllReduceReassociate
    pass folds into a single fused all-reduce (``AR(a)+AR(b) ->
    AR(a+b)``, applied transitively down the chain) — AND-combines
    have no such pass. That fold happens in the TPU/GPU compiler
    pipelines where collective launch latency is real; the committed
    CPU-lowered fingerprints still count one all-reduce per leaf (the
    CPU pipeline skips collective-optimization passes — its
    "collectives" are shared-memory copies with no launch cost).
    int32 counts are exact (no float rounding), and a total of 0 is
    equivalent to every leaf all-finite. On replicated grads (the
    shard_map DP paths guard AFTER the psum) the whole reduction is
    local either way."""
    bad = jnp.asarray(0, jnp.int32)
    for g in jax.tree.leaves(grads):
        bad = bad + jnp.sum(
            jnp.logical_not(jnp.isfinite(g)).astype(jnp.int32))
    return bad == 0


def guard_nonfinite(finite, new_state, state, metrics):
    """Skip-and-count: keep ``new_state`` when ``finite``, carry the
    OLD state through otherwise (params, stats, momenta and EMA all
    selected — a non-finite grad must not leak into ANY buffer), and
    record the skip in ``metrics['skipped']``. Pure ``jnp.where`` on
    a scalar predicate: no branch, no host sync, donation-friendly."""
    guarded = jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                           new_state, state)
    metrics["skipped"] = (~finite).astype(jnp.int32)
    return guarded, metrics


def strided_microbatches(x, accum: int):
    """``[b, ...] -> [accum, b//accum, ...]``, STRIDED (sample ``i`` to
    microbatch ``i % accum``): under GSPMD the batch dim's data-axis
    sharding stays device-local through the reshape — a contiguous
    split would gather each microbatch from a device subset (an
    all-to-all). The ONE copy of the convention (image + LM steps)."""
    b = x.shape[0]
    return x.reshape(b // accum, accum, *x.shape[1:]).swapaxes(0, 1)


def tp_param_spec(leaf, tp: int) -> P:
    """Partition rule for tensor parallelism over the ``model`` axis.

    Shard the trailing dimension — the output-feature dim of every Dense
    kernel ``(in, out)`` and Conv kernel ``(H, W, Cin, Cout)``, and the
    channel dim of BN scale/bias/stats — when it divides evenly;
    replicate everything else (scalars, odd-sized leaves). Keeping ALL
    channel-indexed leaves sharded the same way means layer outputs,
    their BN parameters and their optimizer moments line up with no
    resharding between layers; XLA/GSPMD propagates the specs and
    inserts the (all-gather / reduce-scatter) collectives.
    """
    shape = getattr(leaf, "shape", ())
    if tp > 1 and len(shape) >= 1 and shape[-1] % tp == 0 and shape[-1] >= tp:
        return P(*([None] * (len(shape) - 1)), MODEL_AXIS)
    return P()


def zero1_opt_spec(leaf, dp: int, tp: int) -> P:
    """Partition rule for ZeRO-1 optimizer-state sharding.

    Starts from the TP trailing-dim rule (moments must line up with
    their params on the ``model`` axis), then additionally shards the
    LARGEST remaining divisible dimension over ``data`` — each DP
    replica then stores only 1/dp of every moment buffer, and GSPMD
    turns the weight update into reduce-scatter(grads) -> sharded
    update -> all-gather(params), the ZeRO-1 schedule (cf. SURVEY §2.3
    "sharded optimizer: optional optimization").
    """
    spec = list(tp_param_spec(leaf, tp))
    shape = getattr(leaf, "shape", ())
    spec += [None] * (len(shape) - len(spec))
    best, best_size = None, 0
    for i, n in enumerate(shape):
        if spec[i] is None and n % dp == 0 and n >= dp and n > best_size:
            best, best_size = i, n
    if best is not None:
        spec[best] = DATA_AXIS
    return P(*spec)


def state_shardings(state, mesh: Mesh, *, zero1: bool = False,
                    fsdp: bool = False):
    """NamedSharding pytree for a :class:`TrainState` under TP (and,
    optionally, ZeRO sharding over ``data``).

    Optimizer moments mirror parameter shapes, so the trailing-dim TP
    rule covers params, batch_stats and opt_state uniformly.

    ``zero1`` spreads each optimizer moment buffer across the data axis
    (params stay replicated per DP rank — the ZeRO-1 memory point).

    ``fsdp`` is the ZeRO-3 point: params, batch_stats AND moments are
    all sharded over ``data`` (largest divisible dim,
    :func:`zero1_opt_spec`), so each replica stores ~1/dp of the whole
    model. GSPMD then materializes full params layer-by-layer at use
    (all-gather in the forward/backward) and reduce-scatters gradients —
    the FSDP schedule — instead of keeping a resident replica. This is
    the trade that fits models bigger than chip HBM; for HBM-resident
    models pure DP is faster (no per-layer gathers).
    """
    tp = mesh.shape[MODEL_AXIS]
    dp = mesh.shape[DATA_AXIS]

    def tp_sh(l):
        return NamedSharding(mesh, tp_param_spec(l, tp))

    def dp_sh(l):
        return NamedSharding(mesh, zero1_opt_spec(l, dp, tp))

    param_sh = dp_sh if fsdp else tp_sh
    opt_sh = dp_sh if (zero1 or fsdp) else tp_sh

    return state.replace(
        params=jax.tree.map(param_sh, state.params),
        batch_stats=jax.tree.map(param_sh, state.batch_stats),
        opt_state=jax.tree.map(opt_sh, state.opt_state),
        epoch=NamedSharding(mesh, P()),
        ema_params=jax.tree.map(param_sh, state.ema_params),
    )


def shard_state(state, mesh: Mesh, *, zero1: bool = False,
                fsdp: bool = False):
    """Place a replicated state onto the mesh with TP/ZeRO shardings."""
    placed = jax.tree.map(
        lambda l, s: jax.device_put(l, s),
        state,
        state_shardings(state, mesh, zero1=zero1, fsdp=fsdp),
    )
    # graftmeter: this is the moment trainer state lands on the mesh —
    # ledger the residency here (disarmed: one global read)
    register_state_hbm(placed)
    return placed


def register_state_hbm(state, prefix: str = "train") -> None:
    """Put a :class:`TrainState`'s resident footprint on the armed
    graftmeter HBM ledger (no-op when disarmed — one global read):
    parameters, optimizer moments, batch stats and the EMA shadow,
    each its own gauge. Bytes are PER-CHIP, from host sharding
    metadata only (``hbm.tree_shard_nbytes`` — a replicated leaf
    charges its full size, a ``P(data)``-sharded leaf its
    ``1/data``-slice), so under graftzero/ZeRO-1/FSDP the
    ``hbm_opt_state_bytes`` gauge on ``/metrics`` IS the measured
    ~1/N saving the sharded-update schedule claims — a live delta,
    not a divided-by-hand estimate."""
    if hbm.active_ledger() is None:
        return
    hbm.register(f"{prefix}.params",
                 hbm.tree_shard_nbytes(state.params),
                 category="params")
    hbm.register(f"{prefix}.opt_state",
                 hbm.tree_shard_nbytes(state.opt_state),
                 category="opt_state")
    stats = getattr(state, "batch_stats", None)
    if stats:
        hbm.register(f"{prefix}.batch_stats",
                     hbm.tree_shard_nbytes(stats),
                     category="params")
    ema = getattr(state, "ema_params", None)
    if ema:
        hbm.register(f"{prefix}.ema_params",
                     hbm.tree_shard_nbytes(ema),
                     category="params")


def make_train_step_tp(
    model,
    optimizer: Transform,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    zero1: bool = False,
    fsdp: bool = False,
    remat: bool = False,
    grad_accum: int = 1,
    clip_grad_norm=None,
    ema_decay=None,
):
    """Build the jitted DP x TP train step (GSPMD path).

    Where :func:`make_train_step` expresses data parallelism explicitly
    (``shard_map`` + ``pmean`` — the DDP analogue), tensor parallelism is
    expressed the idiomatic XLA way: the step body is written with GLOBAL
    semantics and the *shardings* carry the parallelism — params'
    trailing (output-feature) dims live on the ``model`` axis
    (:func:`tp_param_spec`), the batch lives on ``data``, and GSPMD
    inserts the collectives. Consequences:

    - gradient averaging over ``data`` needs no explicit ``pmean``: the
      loss is a global mean, so autodiff produces the reduction;
    - sync-BN needs no axis name: batch statistics are means over the
      globally-sharded batch, which IS the cross-replica statistic
      (build the model with ``bn_axis=None`` for this path);
    - the chip-count math of the reference's ``--model_parallel`` flag
      becomes real: passing 2 halves each chip's parameter/optimizer
      footprint instead of silently replicating work (round-2 VERDICT
      weak #2).

    Returns ``step(state, images, labels) -> (state, metrics)``;
    ``state`` must be placed with :func:`shard_state` first.
    """
    _check_tp_model(model)
    body = _train_body(model, optimizer, loss_fn, axis_name=None,
                       remat=remat, grad_accum=grad_accum,
                       dp_size=mesh.shape[DATA_AXIS],
                       clip_grad_norm=clip_grad_norm, ema_decay=ema_decay)

    return lazy_gspmd_jit(
        body, mesh,
        arg_specs=(P(DATA_AXIS, None, None, None), P(DATA_AXIS)),
        returns_state=True, zero1=zero1, fsdp=fsdp,
    )


def lazy_gspmd_jit(body, mesh: Mesh, *, arg_specs, returns_state: bool,
                   zero1: bool = False, fsdp: bool = False):
    """Lazily-bound GSPMD jit: the ONE place the 'cache the jitted
    program keyed on the state's pytree structure, build in/out
    shardings from state_shardings on first call' idiom lives
    (train/eval image TP steps and the LM TP step all bind through
    here — a future change to the caching key applies everywhere).

    ``body(state, *args)``; ``arg_specs`` are the PartitionSpecs of the
    non-state args; metrics outputs are replicated.
    """
    compiled = {}

    def _bind(state):
        key = jax.tree.structure(state)
        if key not in compiled:
            state_sh = state_shardings(state, mesh, zero1=zero1,
                                       fsdp=fsdp)
            in_sh = (state_sh,) + tuple(
                NamedSharding(mesh, s) for s in arg_specs)
            repl = NamedSharding(mesh, P())
            compiled[key] = jax.jit(
                body,
                in_shardings=in_sh,
                out_shardings=(state_sh, repl) if returns_state else repl,
                donate_argnums=(0,) if returns_state else (),
            )
        return compiled[key]

    def step(state, *args):
        # in_shardings depend on the state pytree structure; bind
        # lazily on first call (and on structure change, e.g. resume)
        return _bind(state)(state, *args)

    # graftcheck's lowering handle: the underlying jax.jit program for
    # a given state structure (abstract states work — only the pytree
    # structure is read), so the donation/HLO audits interrogate the
    # EXACT program the trainer runs instead of a reconstruction
    step.jit_program = _bind
    return step


def make_eval_step_tp(model, mesh: Mesh, *, zero1: bool = False,
                      fsdp: bool = False,
                      loss_fn: Callable = cross_entropy_loss):
    """Eval twin of :func:`make_train_step_tp` (global semantics; same
    masked-validity accounting as :func:`make_eval_step`). ``zero1``
    must match the train step's so in_shardings agree with where the
    state actually lives (a mismatch would silently reshard per call).
    """
    _check_tp_model(model)
    body = _eval_body(model, axis_name=None, loss_fn=loss_fn)
    return lazy_gspmd_jit(
        body, mesh,
        arg_specs=(P(DATA_AXIS, None, None, None), P(DATA_AXIS),
                   P(DATA_AXIS)),
        returns_state=False, zero1=zero1, fsdp=fsdp,
    )


def audit_programs():
    """graftcheck registration hook (``analysis/programs.py``): the
    canonical image DP train step — the parity moment for the
    reference's DDP loop, and the program whose communication contract
    IS the design: gradients cross the wire exactly once per step, as
    ONE mesh-wide psum the size of the parameter tree (the BN
    statistic pmeans beside it are channel-sized). ``expect_grad_psums``
    pins that inline; dropping the ``pmean(grads)``, reducing twice, or
    switching to per-leaf reductions all move it. The donation audit
    (``min_donated``) pins that ``donate_argnums=(0,)`` still reaches
    the lowered module — deleting it doubles resident state HBM
    without failing a single numeric test.

    The TP/FSDP GSPMD twins register from ``train/lm.py`` on the tiny
    GPT, where compiling the partitioned HLO is cheap enough for
    tier-1."""
    def build_dp():
        import numpy as np

        from ..models import get_model
        from ..parallel.mesh import audit_mesh
        from .optim import sgd
        from .state import create_train_state

        mesh = audit_mesh(data=8)
        model = get_model("res", stem="cifar", num_classes=10,
                          bn_axis=DATA_AXIS)
        opt = sgd(learning_rate=0.1)
        state = jax.eval_shape(
            lambda: create_train_state(
                model, jax.random.PRNGKey(0),
                jnp.zeros((2, 32, 32, 3)), opt))
        step = make_train_step(model, opt, mesh)
        images = jax.ShapeDtypeStruct((16, 32, 32, 3), jnp.float32)
        labels = jax.ShapeDtypeStruct((16,), jnp.int32)
        params_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(state.params))
        return {
            "fn": step,
            "args": (state, images, labels),
            "mesh": mesh,
            "lower_fn": step,
            "params_bytes": params_bytes,
            "expect_grad_psums": 1,
            "min_donated": len(jax.tree.leaves(state.params)),
        }

    def build_dp_zero():
        """The graftzero twin: SAME model/mesh/batch as build_dp, but
        the committed communication contract is FLIPPED — zero psums
        sized like the parameter tree; the gradient exchange is
        exactly one reduce-scatter (the full padded flat buckets) plus
        one all-gather (the per-rank shard) on the data axis, byte
        volumes pinned inline AND committed. The NaN-guard's summed
        non-finite scalar psum stays (pinned separately:
        ``max_psum_bytes`` bounds every remaining psum at the BN
        statistic size — a grad-sized one reappearing fails here, not
        just in the refreshable budget)."""
        import numpy as np

        from ..models import get_model
        from ..parallel import zero as zero_mod
        from ..parallel.mesh import audit_mesh
        from .optim import sgd
        from .state import create_train_state

        mesh = audit_mesh(data=8)
        model = get_model("res", stem="cifar", num_classes=10,
                          bn_axis=DATA_AXIS)
        opt = sgd(learning_rate=0.1)
        state = jax.eval_shape(
            lambda: create_train_state(
                model, jax.random.PRNGKey(0),
                jnp.zeros((2, 32, 32, 3)), opt))
        state = zero_mod.zeroify_state(state, mesh)
        step = make_train_step(model, opt, mesh, zero=True)
        jit_fn = step.jit_program(state)
        images = jax.ShapeDtypeStruct((16, 32, 32, 3), jnp.float32)
        labels = jax.ShapeDtypeStruct((16,), jnp.int32)
        params_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(state.params))
        comm = zero_mod.static_comm_bytes(state.opt_state.plan)
        # largest surviving psum: sync-BN pmeans its batch mean AND
        # var in ONE tupled eqn, so the cap is 2x the widest [C]
        # statistic leaf — everything else (loss/correct/count
        # scalars, the guard's int32) sits far under it, and a
        # grad-sized psum creeping back is ~3 orders over
        max_bn = 2 * max(
            (int(np.prod(leaf.shape)) * leaf.dtype.itemsize
             for leaf in jax.tree.leaves(state.batch_stats)),
            default=4)
        return {
            "fn": jit_fn,
            "args": (state, images, labels),
            "mesh": mesh,
            "lower_fn": jit_fn,
            "params_bytes": params_bytes,
            "expect_grad_psums": 0,
            "expect_collective_subset": {
                "reduce_scatter@data": {"count": 1,
                                      "bytes": comm["reduce_scatter"]},
                "all_gather@data": {"count": 1,
                                    "bytes": comm["all_gather"]},
            },
            "max_psum_bytes": max_bn,
            "min_donated": len(jax.tree.leaves(state.params)),
        }

    return [{"name": "train_step_dp_resnet18", "min_devices": 8,
             "build": build_dp},
            {"name": "train_step_dp_resnet18_zero", "min_devices": 8,
             "build": build_dp_zero}]


def shard_batch(batch, mesh: Mesh, axis_name: str = DATA_AXIS):
    """Place a host array as a device array sharded over the data axis.

    The H2D boundary (reference ``input.cuda(rank)``, ``main.py:101``) —
    one call distributing per-replica slices across all local chips.
    """
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis_name, *([None] * (x.ndim - 1))))
        ),
        batch,
    )
