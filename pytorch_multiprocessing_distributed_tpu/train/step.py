"""The compiled SPMD train/eval step.

This is the parity moment for the reference's hot loop (``main.py:
101-110``): H2D copy, DDP forward (with SyncBatchNorm stat exchange),
cross-entropy, backward with bucketed NCCL all-reduce, SGD step. Here the
entire iteration is ONE jitted ``shard_map`` program over the mesh:

- the global batch arrives sharded over the ``data`` axis (per-replica
  slice = ``batch // world_size``, reference ``data.py:39``);
- params/optimizer state are replicated; the model's BatchNorm binds the
  ``data`` axis name, so batch statistics are ``pmean``-synced in-step
  (== SyncBatchNorm, reference ``main.py:43``);
- gradients are ``pmean``-ed over ``data`` — DDP averages gradients by
  world size, and XLA lowers this to the same ring all-reduce NCCL would
  run, but fused into the step and riding ICI;
- loss / prec@1 / correct counts are reduced in-step, so the host reads
  back three scalars instead of shipping logits (the reference pays a
  device->host sync per batch for ``.item()`` at ``main.py:113-115``).

State is donated: params are updated in place in HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.losses import cross_entropy_loss, cross_entropy_per_sample
from ..parallel.mesh import DATA_AXIS
from .optim import Transform, apply_updates
from .state import TrainState


def make_train_step(
    model,
    optimizer: Transform,
    mesh: Mesh,
    *,
    loss_fn: Callable = cross_entropy_loss,
    axis_name: str = DATA_AXIS,
):
    """Build the jitted DP train step.

    Returns ``step(state, images, labels) -> (state, metrics)`` where
    ``metrics = {loss, prec1, correct, count}`` are already globally
    reduced (scalars, replicated).
    """

    def shard_body(state: TrainState, images, labels):
        def compute_loss(params):
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            return loss_fn(logits, labels), (logits, mutated["batch_stats"])

        grad_fn = jax.value_and_grad(compute_loss, has_aux=True)
        (loss, (logits, new_stats)), grads = grad_fn(state.params)

        # The DDP all-reduce moment (reference main.py:109): average
        # gradients across the data axis. BN stats were already pmean-ed
        # inside the forward (axis bound by shard_map).
        grads = jax.lax.pmean(grads, axis_name)

        if getattr(optimizer, "apply", None) is not None:
            # fused whole-update path (e.g. the Pallas single-pass SGD)
            new_params, new_opt = optimizer.apply(
                grads, state.opt_state, state.params, lr_step=state.epoch
            )
        else:
            updates, new_opt = optimizer.update(
                grads, state.opt_state, state.params, lr_step=state.epoch
            )
            new_params = apply_updates(state.params, updates)

        pred = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((pred == labels).astype(jnp.int32))
        count = jnp.asarray(labels.shape[0], jnp.int32)
        metrics = {
            "loss": jax.lax.pmean(loss, axis_name),
            "correct": jax.lax.psum(correct, axis_name),
            "count": jax.lax.psum(count, axis_name),
        }
        metrics["prec1"] = 100.0 * metrics["correct"] / metrics["count"]

        new_state = state.replace(
            params=new_params, batch_stats=new_stats, opt_state=new_opt
        )
        return new_state, metrics

    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_eval_step(
    model,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
):
    """Build the jitted eval step (reference ``validate`` inner loop,
    ``main.py:144-151``): forward in eval mode (running BN stats), loss +
    correct-count, globally reduced.

    Two fixes over the reference's eval semantics:
    - the correct count is ``psum``-ed across the data axis (the
      reference divides a per-rank count by the FULL dataset size,
      ``main.py:151,168`` — wrong by ~world_size; its ``reduce_tensor``
      fix is dead code);
    - a per-sample validity mask excludes the sampler's wraparound-
      padding duplicates, so accuracy is exact even when the dataset
      size is not divisible by world_size (SURVEY.md §3.5.3).

    Returns ``step(state, images, labels, valid) -> metrics`` with
    ``metrics = {loss, correct, count, prec1}``; loss/correct/count are
    masked sums over REAL samples only.
    """

    def shard_body(state: TrainState, images, labels, valid):
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images,
            train=False,
        )
        w = valid.astype(jnp.float32)
        per_sample = cross_entropy_per_sample(logits, labels)
        pred = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((pred == labels).astype(jnp.float32) * w)
        metrics = {
            "loss_sum": jax.lax.psum(jnp.sum(per_sample * w), axis_name),
            "correct": jax.lax.psum(correct, axis_name).astype(jnp.int32),
            "count": jax.lax.psum(jnp.sum(w), axis_name).astype(jnp.int32),
        }
        count = jnp.maximum(metrics["count"], 1)
        metrics["loss"] = metrics["loss_sum"] / count
        metrics["prec1"] = 100.0 * metrics["correct"] / count
        return metrics

    sharded = jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_batch(batch, mesh: Mesh, axis_name: str = DATA_AXIS):
    """Place a host array as a device array sharded over the data axis.

    The H2D boundary (reference ``input.cuda(rank)``, ``main.py:101``) —
    one call distributing per-replica slices across all local chips.
    """
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis_name, *([None] * (x.ndim - 1))))
        ),
        batch,
    )
