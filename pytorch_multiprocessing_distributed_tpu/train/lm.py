"""Language-model training step: next-token loss, DP x SP sharding.

The image trainer's step (``train/step.py``) is classification-shaped
(``[B, C]`` logits, ``[B]`` labels); LM training needs the next-token
objective over ``[B, S, V]`` logits, and — under sequence parallelism —
a label shift that CROSSES shard boundaries: with contiguous sequence
sharding, the target for shard ``i``'s last position is the FIRST token
of shard ``i+1``. :func:`make_lm_train_step` handles both:

- DP only (1-D ``data`` mesh): standard shift, final position masked;
- DP x SP (``(data, seq)`` mesh): tokens arrive ``P(data, seq)``;
  each shard ``ppermute``s its first token column back to its left
  neighbor to complete the shift locally, and only the GLOBAL final
  position is masked. Attention is the causal ring; grads are
  ``pmean``-ed over both axes via the exact masked-sum/count ratio.

No reference counterpart (the reference trains ConvNets only); built to
the same conventions as ``train/step.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax.traverse_util import flatten_dict
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.losses import cross_entropy_per_sample
from ..utils.compat import shard_map
from ..parallel.mesh import DATA_AXIS
from .optim import Transform, apply_updates
from .state import TrainState


def _next_token_targets(tokens, seq_axis: Optional[str],
                        zigzag: bool = False):
    """(targets, valid) for the next-token objective.

    ``targets[:, j]`` is the token following position ``j`` (globally);
    ``valid`` masks the one global position with no successor.
    """
    b, s = tokens.shape
    if seq_axis is None:
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1
        )
        valid = jnp.concatenate(
            [jnp.ones((b, s - 1), bool), jnp.zeros((b, 1), bool)], axis=1
        )
        return targets, valid

    axis_size = jax.lax.psum(1, seq_axis)
    idx = jax.lax.axis_index(seq_axis)
    if zigzag:
        # shard i holds chunks (i, 2N-1-i): chunk-internal positions
        # shift locally; each chunk's LAST position needs the first
        # token of the globally-next chunk:
        # - chunk i's successor is chunk i+1 = shard i+1's first half
        #   (except i = N-1, whose successor chunk N is this shard's
        #   OWN second half);
        # - chunk 2N-1-i's successor is chunk 2N-i = shard i-1's second
        #   half (except i = 0, whose chunk 2N-1 ends the sequence).
        c = s // 2
        ta, tb = tokens[:, :c], tokens[:, c:]
        recv_a = jax.lax.ppermute(  # shard i <- shard i+1's ta[:, 0]
            ta[:, 0], seq_axis,
            [((i + 1) % axis_size, i) for i in range(axis_size)],
        )
        recv_b = jax.lax.ppermute(  # shard i <- shard i-1's tb[:, 0]
            tb[:, 0], seq_axis,
            [(i, (i + 1) % axis_size) for i in range(axis_size)],
        )
        next_a = jnp.where(idx == axis_size - 1, tb[:, 0], recv_a)
        targets = jnp.concatenate(
            [ta[:, 1:], next_a[:, None], tb[:, 1:], recv_b[:, None]],
            axis=1,
        )
        valid = jnp.ones((b, s), bool)
        # global last position = chunk 2N-1's last col = shard 0's tb end
        valid = valid.at[:, -1].set(idx != 0)
        return targets, valid
    # contiguous: right neighbor's first column completes the shift
    # (perm sends shard i+1's value to shard i)
    perm = [((i + 1) % axis_size, i) for i in range(axis_size)]
    next_first = jax.lax.ppermute(tokens[:, 0], seq_axis, perm)
    targets = jnp.concatenate(
        [tokens[:, 1:], next_first[:, None]], axis=1
    )
    # only the global last position (last shard's last column) is invalid
    valid = jnp.ones((b, s), bool)
    valid = valid.at[:, -1].set(idx != axis_size - 1)
    return targets, valid


def _collect_moe_losses(mut):
    """(aux, z) layer-means from a ``mutable=['losses']`` apply result.

    sow appends ``(scalar,)`` tuples keyed moe_aux/moe_z, one path per
    MoE layer; the mean over layers keeps the loss weights
    geometry-independent. Zeros when the model has no MoE blocks.
    """
    flat = flatten_dict(mut.get("losses", {}))
    aux_terms = [v for path, vals in flat.items()
                 if path[-1] == "moe_aux"
                 for v in jax.tree_util.tree_leaves(vals)]
    z_terms = [v for path, vals in flat.items()
               if path[-1] == "moe_z"
               for v in jax.tree_util.tree_leaves(vals)]
    aux = (sum(aux_terms) / len(aux_terms)
           if aux_terms else jnp.zeros((), jnp.float32))
    z = (sum(z_terms) / len(z_terms)
         if z_terms else jnp.zeros((), jnp.float32))
    return aux, z


def _checked_token_entry(sharded, mesh, axis_name, seq_axis, zigzag,
                         grad_accum: int = 1):
    """Shared train/eval entry wrapper: trace-time shape validation (a
    mismatched global batch must raise a framework-style error, not an
    opaque shard_map sharding failure — mirrors the image path's and
    TokenLoader's checks) plus the transparent zigzag token permutation
    (callers keep passing natural-order global tokens; the loss is a
    masked mean — permutation-invariant)."""
    dp = int(mesh.shape[axis_name])
    sp = int(mesh.shape[seq_axis]) if seq_axis is not None else 1

    def checked(state, tokens):
        b, s = tokens.shape
        if b % (dp * grad_accum):
            need = (f"data-axis size x grad_accum = {dp} x {grad_accum}"
                    if grad_accum > 1 else f"data-axis size {dp}")
            raise ValueError(
                f"global batch {b} must divide by {need} "
                f"(mesh axis {axis_name!r})"
            )
        if seq_axis is not None and s % sp:
            raise ValueError(
                f"seq_len {s} is not divisible by the sequence-axis "
                f"size {sp} (mesh axis {seq_axis!r})"
            )
        if zigzag:
            from ..parallel.ring_attention import zigzag_indices

            perm = zigzag_indices(s, sp).reshape(-1)
            tokens = tokens[:, perm]
        return sharded(state, tokens)

    return checked


def make_lm_train_step(
    model,
    optimizer: Transform,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    seq_axis: Optional[str] = None,
    remat: bool = False,
    grad_accum: int = 1,
    moe_aux_weight: float = 0.01,
    moe_z_weight: float = 1e-3,
    vocab_chunks: int = 0,
    zero: bool = False,
    zero_overlap: bool = True,
):
    """Build the jitted LM train step.

    Args:
      model: a :class:`..models.gpt.GPT`-like module (``[B, S] ->
        [B, S, V]``), built with the SAME ``seq_axis``.
      mesh: 1-D ``(data,)`` mesh, or 2-D ``(data, seq)`` when
        ``seq_axis`` is set.
      grad_accum: microbatches per update over the batch dim (activation
        memory of one microbatch — the long-context memory knob beside
        ``remat``); exact same update as the single-shot step.
      vocab_chunks: > 1 streams the head matmul + CE over this many
        vocab slices (:func:`..ops.losses.chunked_lm_ce`): the
        ``[B, S, V]`` logits never materialize in either pass — the
        big-vocab memory knob. Exactly the dense objective (parity
        test-pinned); 0/1 = dense path.

    Returns ``step(state, tokens) -> (state, metrics)``; ``tokens`` is
    the global ``[B, S]`` int array, ``metrics = {loss, count}`` (loss =
    exact mean next-token CE over all predictable positions). MoE models
    (``n_experts > 0``) additionally train against the Switch
    load-balancing aux loss and the ST-MoE router z-loss the layer sows
    into its ``losses`` collection (``moe_aux_weight`` /
    ``moe_z_weight``; metrics gain ``moe_aux``).

    ``zero=True`` (graftzero): the per-leaf grad psums become one
    bucketed reduce-scatter, the update runs on local shards (moments
    sharded — the state must carry a
    :class:`..parallel.zero.ZeroOptState`; build it with
    ``zero.zeroify_state``), params all-gather back. DP only
    (``seq_axis`` must be None — the cross-shard label shift lives on
    the SP path).
    """
    if grad_accum < 1:
        raise ValueError(
            f"grad_accum must be >= 1, got {grad_accum} (1 = no "
            "accumulation; 0/negative would silently disable it)"
        )
    if zero and seq_axis is not None:
        raise ValueError(
            "zero=True shards the update over the data axis only; "
            "combine it with DP (seq_axis=None), not sequence "
            "parallelism")
    axes = (axis_name,) if seq_axis is None else (axis_name, seq_axis)
    is_moe = getattr(model, "n_experts", 0) > 0
    # zigzag SP: the model was built with sp_mode="zigzag", so tokens
    # must arrive in the zigzag_indices layout (handled transparently
    # below — callers keep passing natural-order global tokens) and the
    # label shift crosses chunk boundaries instead of shard boundaries
    zigzag = (seq_axis is not None
              and getattr(model, "sp_mode", "ring") == "zigzag")

    def make_body(zero_plan=None):
        def body(state: TrainState, tokens):
            return _body(state, tokens, zero_plan)
        return body

    def _body(state: TrainState, tokens, zero_plan):
        targets, valid = _next_token_targets(tokens, seq_axis, zigzag)
        w = valid.astype(jnp.float32)
        # Constants wrt params, computed before differentiation: global
        # predictable-position count and shard count (for layer-mean
        # normalization of the per-shard aux losses).
        count = jax.lax.psum(jnp.sum(w), axes)
        world = jax.lax.psum(1, axes)

        # Differentiate a LOCAL objective — deliberately no collective
        # inside the differentiated function (transposing through psum
        # under shard_map is a notorious factor-of-N trap; ring
        # attention's own custom VJP handles its internal comms). The
        # local objective is pre-normalized (CE by the global count, aux
        # by shard count x microbatch count) so ONE psum of the summed
        # local grads outside is exactly the global-mean gradient.
        def local_obj(params, tok, tgt, ww):
            if vocab_chunks > 1:
                from ..ops.losses import chunked_lm_ce

                hidden, mut = model.apply(
                    {"params": params}, tok, train=True,
                    return_hidden=True, mutable=["losses"]
                )
                ce_sum = chunked_lm_ce(
                    hidden, params["head"]["kernel"],
                    params["head"].get("bias"), tgt, ww, vocab_chunks,
                )
            else:
                logits, mut = model.apply(
                    {"params": params}, tok, train=True,
                    mutable=["losses"]
                )
                flat_ce = cross_entropy_per_sample(
                    logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1)
                ).reshape(tgt.shape)
                ce_sum = jnp.sum(flat_ce * ww)
            aux, z = _collect_moe_losses(mut)
            obj = ce_sum / count + (
                moe_aux_weight * aux + moe_z_weight * z
            ) / (world * grad_accum)
            return obj, (ce_sum, aux)

        if remat:
            local_obj = jax.checkpoint(local_obj)

        if grad_accum == 1:
            (_, (loss_sum, aux)), grads = jax.value_and_grad(
                local_obj, has_aux=True
            )(state.params, tokens, targets, w)
        else:
            b = tokens.shape[0]
            if b % grad_accum:
                raise ValueError(
                    f"per-device batch {b} is not divisible by "
                    f"grad_accum={grad_accum}"
                )

            from .step import strided_microbatches

            def to_micro(x):
                return strided_microbatches(x, grad_accum)

            def micro(carry, mb):
                gsum, lsum, asum = carry
                (_, (ce, aux_mb)), g = jax.value_and_grad(
                    local_obj, has_aux=True
                )(state.params, *mb)
                return (jax.tree.map(jnp.add, gsum, g),
                        lsum + ce, asum + aux_mb), None

            carry0 = (
                jax.tree.map(jnp.zeros_like, state.params),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (grads, loss_sum, aux_sum), _ = jax.lax.scan(
                micro, carry0,
                (to_micro(tokens), to_micro(targets), to_micro(w)),
            )
            aux = aux_sum / grad_accum
        loss = jax.lax.psum(loss_sum, axes) / count
        from .step import finite_grads, guard_nonfinite

        if zero_plan is not None:
            # graftzero: the per-leaf grad psums become ONE bucketed
            # reduce-scatter (sum semantics — the local objective is
            # already globally pre-normalized), the update runs on
            # local shards, params all-gather back; the guard counts
            # non-finites on the scattered shards with one summed
            # scalar psum
            from ..parallel import zero as zero_mod

            g_shards = zero_mod.reduce_scatter_grads(
                grads, zero_plan, axis_name, mean=False,
                overlap=zero_overlap)
            finite = zero_mod.finite_shards(g_shards, axis_name)
            new_params, new_opt = zero_mod.apply_sharded_update(
                optimizer, state.opt_state, g_shards, state.params,
                axis_name, lr_step=state.epoch, overlap=zero_overlap)
        else:
            grads = jax.tree.map(lambda g: jax.lax.psum(g, axes), grads)

            # NaN/inf skip-and-count guard off the globally-summed
            # grads (replicated — every shard agrees): see
            # step.guard_nonfinite
            finite = finite_grads(grads)
            updates, new_opt = optimizer.update(
                grads, state.opt_state, state.params, lr_step=state.epoch
            )
            new_params = apply_updates(state.params, updates)
        new_state = state.replace(params=new_params, opt_state=new_opt)
        metrics = {"loss": loss, "count": count}
        if is_moe:
            metrics["moe_aux"] = jax.lax.psum(aux, axes) / world
        new_state, metrics = guard_nonfinite(finite, new_state, state,
                                             metrics)
        return new_state, metrics

    if zero:
        from .step import _lazy_zero_step

        return _lazy_zero_step(
            make_body, mesh, axis_name, n_batch_args=1,
            entry=lambda sharded: _checked_token_entry(
                sharded, mesh, axis_name, None, False, grad_accum))

    if seq_axis is None:
        in_specs = (P(), P(axis_name))
    else:
        in_specs = (P(), P(axis_name, seq_axis))
    sharded = shard_map(
        make_body(),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(
        _checked_token_entry(sharded, mesh, axis_name, seq_axis, zigzag,
                             grad_accum),
        donate_argnums=(0,),
    )


def make_lm_train_step_tp(
    model,
    optimizer: Transform,
    mesh: Mesh,
    *,
    zero1: bool = False,
    fsdp: bool = False,
    remat: bool = False,
    moe_aux_weight: float = 0.01,
    moe_z_weight: float = 1e-3,
):
    """Build the jitted DP x TP LM train step (GSPMD path).

    The LM twin of :func:`..train.step.make_train_step_tp`: the body is
    written with GLOBAL semantics and the shardings carry the
    parallelism — the generic trailing-dim rule
    (:func:`..train.step.tp_param_spec`) puts every Dense output-feature
    dim (wqkv/fc1 columns, wo/fc2 via their own trailing dims, the
    vocab head) and the embedding hidden dim on the ``model`` axis,
    tokens live on ``data``, and GSPMD inserts the Megatron-style
    collectives. ``zero1``/``fsdp`` compose exactly as on the image
    path. ``state`` must be placed with
    :func:`..train.step.shard_state` first.

    Requires a model built WITHOUT ``seq_axis`` (TP x SP composition
    runs through the shard_map path, not GSPMD).
    """
    if getattr(model, "seq_axis", None) is not None:
        raise ValueError(
            "make_lm_train_step_tp requires a model built with "
            "seq_axis=None: under GSPMD the sequence stays unsharded "
            "(use make_lm_train_step(seq_axis=...) for SP)"
        )
    is_moe = getattr(model, "n_experts", 0) > 0

    def body(state: TrainState, tokens):
        targets, valid = _next_token_targets(tokens, None)
        w = valid.astype(jnp.float32)
        count = jnp.sum(w)

        def obj(params):
            logits, mut = model.apply(
                {"params": params}, tokens, train=True, mutable=["losses"]
            )
            flat_ce = cross_entropy_per_sample(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
            ).reshape(targets.shape)
            ce_mean = jnp.sum(flat_ce * w) / count
            aux, z = _collect_moe_losses(mut)
            total = ce_mean + moe_aux_weight * aux + moe_z_weight * z
            return total, (ce_mean, aux)

        if remat:
            obj = jax.checkpoint(obj)
        (_, (loss, aux)), grads = jax.value_and_grad(
            obj, has_aux=True
        )(state.params)
        from .step import finite_grads, guard_nonfinite

        finite = finite_grads(grads)
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params, lr_step=state.epoch
        )
        new_state = state.replace(
            params=apply_updates(state.params, updates), opt_state=new_opt
        )
        metrics = {"loss": loss, "count": count}
        if is_moe:
            metrics["moe_aux"] = aux
        new_state, metrics = guard_nonfinite(finite, new_state, state,
                                             metrics)
        return new_state, metrics

    from .step import lazy_gspmd_jit

    return lazy_gspmd_jit(
        body, mesh, arg_specs=(P(DATA_AXIS),), returns_state=True,
        zero1=zero1, fsdp=fsdp,
    )


def make_lm_eval_step(
    model,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    seq_axis: Optional[str] = None,
    vocab_chunks: int = 0,
):
    """Forward-only next-token CE over held-out tokens (DP x SP paths).

    The LM twin of the image :func:`..train.step.make_eval_step`: same
    mesh/axis conventions as :func:`make_lm_train_step` (including the
    zigzag token permutation and the cross-shard label shift), eval-mode
    apply (MoE aux sows are discarded — flax drops non-mutable
    collections), exact masked-mean accounting via a psum-ed global
    count. Returns ``eval_step(state, tokens) -> {loss, count}``.

    ``vocab_chunks`` streams the head+CE exactly like the train step —
    a run that only fits BECAUSE of chunking must not OOM at its first
    validation pass.
    """
    axes = (axis_name,) if seq_axis is None else (axis_name, seq_axis)
    zigzag = (seq_axis is not None
              and getattr(model, "sp_mode", "ring") == "zigzag")

    def body(state: TrainState, tokens):
        targets, valid = _next_token_targets(tokens, seq_axis, zigzag)
        w = valid.astype(jnp.float32)
        count = jax.lax.psum(jnp.sum(w), axes)
        if vocab_chunks > 1:
            from ..ops.losses import chunked_lm_ce

            hidden = model.apply({"params": state.params}, tokens,
                                 train=False, return_hidden=True)
            ce_sum = chunked_lm_ce(
                hidden, state.params["head"]["kernel"],
                state.params["head"].get("bias"), targets, w,
                vocab_chunks,
            )
        else:
            logits = model.apply({"params": state.params}, tokens,
                                 train=False)
            flat_ce = cross_entropy_per_sample(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
            ).reshape(targets.shape)
            ce_sum = jnp.sum(flat_ce * w)
        loss = jax.lax.psum(ce_sum, axes) / count
        return {"loss": loss, "count": count}

    if seq_axis is None:
        in_specs = (P(), P(axis_name))
    else:
        in_specs = (P(), P(axis_name, seq_axis))
    sharded = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False,
    )
    return jax.jit(
        _checked_token_entry(sharded, mesh, axis_name, seq_axis, zigzag)
    )


def make_lm_eval_step_tp(model, mesh: Mesh, *, zero1: bool = False,
                         fsdp: bool = False):
    """Eval twin of :func:`make_lm_train_step_tp` (GSPMD path).

    ``zero1``/``fsdp`` must match the train step's so in_shardings
    agree with where the state actually lives.
    """
    if getattr(model, "seq_axis", None) is not None:
        raise ValueError(
            "make_lm_eval_step_tp requires a model built with "
            "seq_axis=None (use make_lm_eval_step(seq_axis=...) for SP)"
        )

    def body(state: TrainState, tokens):
        targets, valid = _next_token_targets(tokens, None)
        w = valid.astype(jnp.float32)
        count = jnp.sum(w)
        logits = model.apply({"params": state.params}, tokens,
                             train=False)
        flat_ce = cross_entropy_per_sample(
            logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
        ).reshape(targets.shape)
        return {"loss": jnp.sum(flat_ce * w) / count, "count": count}

    from .step import lazy_gspmd_jit

    return lazy_gspmd_jit(
        body, mesh, arg_specs=(P(DATA_AXIS),), returns_state=False,
        zero1=zero1, fsdp=fsdp,
    )


def create_lm_train_state(model, rng, sample_tokens,
                          optimizer: Transform) -> TrainState:
    """LM twin of :func:`..train.create_train_state` (no batch stats).

    Accepts a sequence-parallel model directly: ``seq_axis`` changes no
    parameter shapes but DOES make the forward call collectives
    (``axis_index``/``psum``) that have no bound axis at init time, so
    initialization runs on an axis-free clone. ``sample_tokens`` is the
    GLOBAL ``[B, S]`` batch either way.
    """
    if getattr(model, "seq_axis", None) is not None:
        model = model.clone(seq_axis=None)
    variables = model.init(rng, sample_tokens, train=False)
    params = variables["params"]
    return TrainState(
        params=params,
        batch_stats={},
        opt_state=optimizer.init(params),
        epoch=jnp.ones((), jnp.int32),
    )


# ----------------------------------------------------------- graftcheck

def _audit_gpt(**kw):
    """The shared tiny audit GPT (ONE geometry across the LM-family
    hooks — see :func:`...analysis.programs.audit_tiny_gpt`)."""
    from ..analysis.programs import audit_tiny_gpt

    return audit_tiny_gpt(**kw)


def _audit_lm_pieces(model, mesh_data=1, mesh_model=1):
    """(mesh, abstract state, abstract tokens, optimizer) for one LM
    audit program."""
    from ..parallel.mesh import audit_mesh
    from .optim import sgd

    mesh = audit_mesh(data=mesh_data, model=mesh_model)
    opt = sgd(learning_rate=0.1)
    state = jax.eval_shape(
        lambda: create_lm_train_state(
            model, jax.random.PRNGKey(0),
            jnp.zeros((2, 16), jnp.int32), opt))
    tokens = jax.ShapeDtypeStruct((8, 16), jnp.int32)
    return mesh, state, tokens, opt


def audit_programs():
    """graftcheck registration hook: the LM train steps across the
    parallelism modes whose communication the compiler owns.

    - ``lm_step_dp``: shard_map DP — grads psum per leaf (the LM body
      deliberately reduces OUTSIDE the differentiated function); the
      committed budget pins total psum volume = params + metrics.
    - ``lm_step_tp`` / ``lm_step_fsdp``: GSPMD — the jaxpr shows only
      sharding constraints, so these compile (CPU, partitioned) and
      pin the HLO collective set: TP must all-reduce, FSDP must
      all-gather params and reduce-scatter grads (``require_hlo``) —
      the ZeRO-3 schedule as a checkable artifact, per
      arXiv:2004.13336's framing of the weight-update sharding.
    - ``lm_step_moe``: the MoE objective through the DP step (aux/z
      losses included) — fingerprint + budget over the routed FFN.
    """
    def build_dp():
        model = _audit_gpt()
        mesh, state, tokens, opt = _audit_lm_pieces(model, mesh_data=8)
        step = make_lm_train_step(model, opt, mesh)
        return {
            "fn": step, "args": (state, tokens), "mesh": mesh,
            "lower_fn": step,
            "min_donated": len(jax.tree.leaves(state.params)),
        }

    def build_tp(fsdp=False):
        model = _audit_gpt()
        mesh, state, tokens, opt = _audit_lm_pieces(
            model, mesh_data=2, mesh_model=2)
        step = make_lm_train_step_tp(model, opt, mesh, fsdp=fsdp)
        jit_fn = step.jit_program(state)
        spec = {
            "fn": jit_fn, "args": (state, tokens), "mesh": mesh,
            "lower_fn": jit_fn, "compile": True,
            "min_donated": len(jax.tree.leaves(state.params)),
            # FSDP's defining exchange is all-gather(params) +
            # reduce-scatter(grads); XLA:CPU's partitioner lowers the
            # reduce-scatter half as all-reduce(+slice), so the
            # portable requirement is gather + reduce — the committed
            # HLO budget pins the exact op set this jax emits
            "require_hlo": (("all-gather", "all-reduce") if fsdp
                            else ("all-reduce",)),
        }
        return spec

    def build_moe():
        model = _audit_gpt(n_experts=4, moe_capacity_factor=4.0)
        mesh, state, tokens, opt = _audit_lm_pieces(model, mesh_data=8)
        step = make_lm_train_step(model, opt, mesh)
        return {
            "fn": step, "args": (state, tokens), "mesh": mesh,
            "lower_fn": step,
            "min_donated": len(jax.tree.leaves(state.params)),
        }

    def build_dp_zero():
        """graftzero twin of ``lm_step_dp``: the ~30 per-leaf grad
        psums collapse into ONE bucketed reduce-scatter + ONE
        all-gather on the data axis (byte volumes pinned inline and
        committed); the only psums left are the loss/count scalars and
        the NaN-guard's summed non-finite int32 — ``max_psum_bytes=4``
        pins them separately (any grad-sized psum creeping back fails
        live, no refresh can launder it)."""
        import numpy as np

        from ..parallel import zero as zero_mod

        model = _audit_gpt()
        mesh, state, tokens, opt = _audit_lm_pieces(model, mesh_data=8)
        state = zero_mod.zeroify_state(state, mesh)
        step = make_lm_train_step(model, opt, mesh, zero=True)
        jit_fn = step.jit_program(state)
        comm = zero_mod.static_comm_bytes(state.opt_state.plan)
        params_bytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(state.params))
        return {
            "fn": jit_fn, "args": (state, tokens), "mesh": mesh,
            "lower_fn": jit_fn,
            "params_bytes": params_bytes,
            "expect_grad_psums": 0,
            "expect_collective_subset": {
                "reduce_scatter@data": {"count": 1,
                                      "bytes": comm["reduce_scatter"]},
                "all_gather@data": {"count": 1,
                                    "bytes": comm["all_gather"]},
            },
            "max_psum_bytes": 4,
            "min_donated": len(jax.tree.leaves(state.params)),
        }

    return [
        {"name": "lm_step_dp", "min_devices": 8, "build": build_dp},
        {"name": "lm_step_tp", "min_devices": 4, "build": build_tp},
        {"name": "lm_step_fsdp", "min_devices": 4,
         "build": lambda: build_tp(fsdp=True)},
        {"name": "lm_step_moe", "min_devices": 8, "build": build_moe},
        {"name": "lm_step_dp_zero", "min_devices": 8,
         "build": build_dp_zero},
    ]
