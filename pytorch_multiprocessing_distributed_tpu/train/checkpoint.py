"""Checkpoint save/load.

Artifact parity target: the reference saves the (DDP-wrapped) state dict
on rank 0 at the final epoch only, named ``model_{epoch}.pth``
(``main.py:75-77``), and has NO load/resume path. Here:

- :func:`save_checkpoint` writes the full :class:`..train.TrainState`
  (params, BN running stats, optimizer buffers, epoch) as msgpack bytes
  under the same ``model_{epoch}.pth`` name, single-writer (primary host);
- :func:`load_checkpoint` restores it — the resume path the reference
  lacks (SURVEY.md §5 "Checkpoint / resume").

msgpack via ``flax.serialization`` rather than pickle: deterministic,
framework-neutral bytes, no arbitrary-code-execution on load.

Durability + integrity (graftfault hardening):

- the write path is fsync'd on BOTH sides of the atomic rename (file
  before ``os.replace``, parent directory after) — ``os.replace``
  alone orders nothing on power loss, so "atomic" used to overpromise;
- every checkpoint carries a sha256 sidecar (``model_N.pth.sha256``)
  written from the exact bytes handed to the OS; :func:`load_checkpoint`
  verifies it and a truncated/bit-flipped file raises
  :class:`CheckpointCorruptError` NAMING the file and both digests
  instead of failing deep inside msgpack (or worse, resuming from
  garbage weights);
- :func:`load_with_fallback` is the resume path that survives it:
  newest checkpoint corrupt -> warn with the digest mismatch, fall
  back to the previous valid epoch, resume there.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

import jax
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import dist
from ..runtime import scope as graftscope
from ..runtime.faults import GraftFaultError, maybe_fault, register_site
from .state import TrainState

# the torn/corrupt-artifact hazard the fault matrix sweeps: fires on
# the serialized payload right before it reaches the OS, so an
# injected corruption is caught by the digest verification exactly
# like real bit rot would be
_SITE_WRITE = register_site(
    "train.checkpoint_write",
    "msgpack checkpoint payload write + fsync + atomic rename")


class CheckpointCorruptError(GraftFaultError):
    """A checkpoint's bytes do not match its recorded sha256 digest
    (torn write, bit rot, truncation). Names the file and both
    digests; resume paths fall back to the previous valid epoch."""


def _gather_for_host(tree):
    """Make every leaf fully host-addressable before serialization.

    Under ``--zero1`` (and multi-host TP) state leaves are sharded
    across hosts, so a bare ``jax.device_get`` would raise
    "spans non-addressable devices". A jitted identity with replicated
    ``out_shardings`` all-gathers such a leaf onto every device of its
    mesh. This is a COLLECTIVE: every host must call it, so it runs
    BEFORE any primary-host gating. Single-host states pass through
    untouched (everything is already addressable).
    """

    def fix(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            mesh = leaf.sharding.mesh
            return jax.jit(
                lambda x: x, out_shardings=NamedSharding(mesh, P())
            )(leaf)
        return leaf

    return jax.tree.map(fix, tree)


def checkpoint_path(save_path: str, epoch: int) -> str:
    """``{save_path}/model_{epoch}.pth`` (reference ``main.py:77``)."""
    return os.path.join(save_path, "model_{0}.pth".format(epoch))


def digest_path(path: str) -> str:
    """Sidecar holding the checkpoint's sha256 (hex)."""
    return path + ".sha256"


def _fsync_dir(dirname: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives power loss
    (the rename itself lives in the directory's metadata). Platforms
    whose dirfds reject fsync (some network filesystems) degrade to
    the rename-only guarantee."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # EINVAL on fsync-less dirfds: keep rename-only
        pass
    finally:
        os.close(fd)


def write_atomic_durable(path: str, payload: bytes) -> None:
    """tmp-write -> fsync(file) -> atomic rename -> fsync(parent dir).

    ``os.replace`` alone is atomic against CONCURRENT readers but
    orders nothing against power loss: the data blocks and the rename
    can reach disk in either order, so the old comment's "no torn
    checkpoints" only held for clean exits. Both fsyncs make the
    rename a real durability barrier."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def save_checkpoint(save_path: str, state: TrainState, epoch: int) -> Optional[str]:
    """Write the state on the primary host; returns the path (None on
    non-primary hosts, which mirror the reference's rank-gating at
    ``main.py:75``).

    The sha256 of the serialized payload is written alongside
    (``model_N.pth.sha256``), AFTER the checkpoint itself is durable —
    a crash between the two leaves a valid checkpoint with no digest
    (verified loads treat a missing sidecar as legacy, not corrupt),
    never a digest pointing at torn bytes.

    graftzero: a state carrying a sharded
    :class:`~..parallel.zero.ZeroOptState` saves GATHER-ON-SAVE — the
    moments are unflattened back to the replicated format, so the
    artifact is mode-portable: ``--resume auto`` round-trips between
    ``--zero`` and plain runs (the CLIs load into the replicated
    template and re-shard with ``zero.zeroify_state`` when ``--zero``
    is set). The digest sidecar and ``load_with_fallback`` are
    untouched."""
    # Collective leaf replication first — ALL hosts participate even
    # though only the primary writes (see _gather_for_host). It also
    # makes the zero moment buckets host-addressable for the gather
    # below.
    state = _gather_for_host(state)
    if not dist.is_primary():
        return None
    from ..parallel.zero import ZeroOptState, gather_opt_state

    if isinstance(state.opt_state, ZeroOptState):
        # graftzero gather-on-save: host-local unflatten (no
        # collective — safe after the primary gate), so the artifact
        # is always the replicated, mode-portable format
        state = state.replace(
            opt_state=gather_opt_state(state.opt_state, state.params))
    path = checkpoint_path(save_path, epoch)
    with graftscope.span("checkpoint.write", cat="train", epoch=epoch,
                         path=os.path.basename(path)) as ckpt_span:
        # Pull fully-addressable host copies off the devices.
        host_state = jax.device_get(state)
        payload = serialization.to_bytes(host_state)
        digest = hashlib.sha256(payload).hexdigest()
        # injected fault point: "corrupt" flips a payload byte AFTER
        # the digest was computed — exactly what bit rot / a torn
        # write does
        written = maybe_fault(_SITE_WRITE, payload)
        # re-save of the same epoch (preemption re-save, torn-epoch
        # redo): drop the stale sidecar BEFORE replacing the
        # checkpoint, so a crash between the two replaces degrades to
        # "valid checkpoint, no digest" — never the old digest paired
        # with the new payload
        dpath = digest_path(path)
        if os.path.exists(dpath):
            os.remove(dpath)
        write_atomic_durable(path, written)
        write_atomic_durable(dpath, digest.encode("ascii"))
        ckpt_span.note(bytes=len(payload))
    return path


def verify_checkpoint(path: str, payload: Optional[bytes] = None) -> bool:
    """Check ``path`` against its sha256 sidecar. True when they
    match OR no sidecar exists (legacy checkpoint — nothing to verify
    against); raises :class:`CheckpointCorruptError` on a mismatch.

    ``payload``: the file's already-read bytes, so a verified load
    hashes the SAME buffer it deserializes instead of reading a
    multi-GB checkpoint twice (``load_with_fallback`` walks N
    candidates per host)."""
    dpath = digest_path(path)
    if not os.path.exists(dpath):
        return True
    with open(dpath, "rb") as f:
        expected = f.read().decode("ascii").strip()
    if payload is None:
        with open(path, "rb") as f:
            payload = f.read()
    actual = hashlib.sha256(payload).hexdigest()
    if actual != expected:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is corrupt: sha256 {actual} does not "
            f"match the recorded digest {expected} ({dpath}) — torn "
            "write, truncation, or bit rot; falling back to the "
            "previous checkpoint is the intended recovery")
    return True


def load_checkpoint(path: str, template: TrainState,
                    verify: bool = True) -> TrainState:
    """Restore a checkpoint into the structure of ``template``
    (a freshly-initialized state with the same model/optimizer).

    ``verify`` (default) checks the sha256 sidecar first: corrupt
    bytes raise :class:`CheckpointCorruptError` naming the file and
    digests instead of a cryptic msgpack unpack error (or a silent
    garbage restore). Checkpoints without a sidecar load unverified.

    Forward-compatible with checkpoints written before a TrainState
    field existed (e.g. ``ema_params``): missing top-level fields keep
    the template's value instead of failing the restore.

    EMA resume semantics: when the template tracks EMA (``--ema``) but
    the checkpoint has none (missing key OR the empty ``{}`` every
    non-EMA checkpoint serializes), the EMA is seeded from the
    checkpoint's TRAINED params — never from the template's fresh
    random init, which would poison every eval for ~1/(1-decay) steps.

    Torch interop: a reference-trained ``model_{epoch}.pth`` is a torch
    zip archive, not msgpack. Detected by magic and routed through
    :mod:`..utils.torch_interop` — params + BN stats load, the
    optimizer starts fresh (torch SGD momentum buffers don't map onto
    this optimizer's tree), and the epoch keeps the template's value.
    """
    # Sniff the torch-zip magic from the FIRST 4 BYTES before
    # committing to a full read: load_torch_checkpoint re-reads from
    # disk itself, so buffering a multi-GB archive here would double
    # the I/O and transiently hold an extra copy. A msgpack state dict
    # starts with a map-header byte, never ``PK\x03\x04``, so the
    # prefix discriminates unambiguously. msgpack checkpoints are read
    # ONCE: the digest check and the deserializer share the buffer.
    with open(path, "rb") as f:
        head = f.read(4)
        is_torch_zip = head == b"PK\x03\x04"
        payload = None if is_torch_zip else head + f.read()
    if verify:
        # torch zips never get a sidecar written (reference artifacts);
        # verify_checkpoint re-reads the file only when one exists.
        verify_checkpoint(path, payload=payload)
    if is_torch_zip:
        from ..utils.torch_interop import load_torch_checkpoint

        params, stats = load_torch_checkpoint(
            path, template.params, template.batch_stats
        )
        state = template.replace(params=params, batch_stats=stats)
        if getattr(template, "ema_params", None):
            state = state.replace(ema_params=params)
        return state
    state_dict = serialization.msgpack_restore(payload)
    template_dict = serialization.to_state_dict(template)
    if template_dict.get("ema_params") and not state_dict.get("ema_params"):
        state_dict["ema_params"] = state_dict["params"]
    for key, value in template_dict.items():
        state_dict.setdefault(key, value)
    return serialization.from_state_dict(template, state_dict)


def _checkpoint_epochs(save_path: str):
    """``[(epoch, filename), ...]`` for every parseable ``model_*.pth``
    under ``save_path`` — the ONE place the naming scheme is decoded
    (prune/latest/auto-resume all consume this)."""
    found = []
    if not os.path.isdir(save_path):
        return found
    for name in os.listdir(save_path):
        if name.startswith("model_") and name.endswith(".pth"):
            try:
                found.append((int(name[len("model_"):-len(".pth")]), name))
            except ValueError:
                continue
    return found


def prune_checkpoints(save_path: str, keep: int) -> None:
    """Delete all but the ``keep`` highest-epoch ``model_*.pth`` files.

    Primary-host-only callers (the trainer gates this like the writes);
    ``keep <= 0`` disables pruning. Removes the LISTED filename (never a
    reconstructed one — ``model_007.pth`` parses to epoch 7 but is not
    named ``model_7.pth``).
    """
    if keep <= 0:
        return
    for _, name in sorted(_checkpoint_epochs(save_path))[:-keep]:
        path = os.path.join(save_path, name)
        os.remove(path)
        # the digest sidecar lives and dies with its checkpoint
        if os.path.exists(digest_path(path)):
            os.remove(digest_path(path))


def latest_checkpoint(save_path: str) -> Optional[str]:
    """Highest-epoch ``model_*.pth`` under ``save_path``, if any."""
    found = _checkpoint_epochs(save_path)
    return os.path.join(save_path, max(found)[1]) if found else None


def checkpoint_epoch(path: str) -> Optional[int]:
    """Epoch parsed from a ``model_<epoch>.pth`` path, else ``None``.

    The inverse of the naming scheme :func:`_checkpoint_epochs`
    decodes; ``--resume auto`` callers use it to turn the
    primary-resolved path back into the ``anchor`` epoch for
    :func:`load_with_fallback`."""
    name = os.path.basename(path)
    if name.startswith("model_") and name.endswith(".pth"):
        try:
            return int(name[len("model_"):-len(".pth")])
        except ValueError:
            pass
    return None


def load_with_fallback(save_path: str, template: TrainState, *,
                       anchor: Optional[int] = None,
                       ) -> Tuple[TrainState, str]:
    """Resume from the newest VALID checkpoint under ``save_path``.

    ``anchor``: cap the walk at this epoch (checkpoints newer than it
    are ignored, not treated as candidates). ``--resume auto`` passes
    the primary-resolved epoch here, so a STALE extra checkpoint on
    one host (newer than what the primary resolved) cannot shift that
    host's walk and get misdiagnosed as cross-host divergence.

    The corrupt-checkpoint recovery path: walk checkpoints newest to
    oldest, verify each digest, restore the first that passes —
    reporting (stderr, primary host) every corrupt artifact skipped,
    with its digest mismatch. Training then resumes at the fallback's
    epoch (the restored ``state.epoch``; the torn epoch is redone,
    exactly like a preemption resume). Raises the LAST
    :class:`CheckpointCorruptError` when every checkpoint is corrupt,
    ``FileNotFoundError`` when there are none.

    Multi-host: digests verify against HOST-LOCAL bytes, so a corrupt
    copy on one host must not silently shift just that host to an
    older epoch — the split-brain :func:`resolve_auto_resume` exists
    to prevent. After the walk, every host — including one whose walk
    found nothing valid — reaches ONE agreement collective with its
    verified epoch (``-1`` = exhausted), and on any divergence EVERY
    host raises: an asymmetric check (peer dies, primary proceeds)
    would leave the survivors wedged forever at their next training
    collective instead of failing loudly.

    Returns ``(state, path_loaded)``."""
    found = _checkpoint_epochs(save_path)
    if anchor is not None:
        found = [(e, n) for e, n in found if e <= anchor]
    last_err: Optional[CheckpointCorruptError] = None
    chosen = None  # (epoch, path, state)
    for epoch, name in sorted(found, reverse=True):
        path = os.path.join(save_path, name)
        try:
            state = load_checkpoint(path, template)
        except CheckpointCorruptError as e:
            last_err = e
            if dist.is_primary():
                import sys

                print(f"[pmdt] {e}\n[pmdt] falling back to the "
                      "previous checkpoint", file=sys.stderr)
            continue
        chosen = (epoch, path, state)
        break
    _require_fallback_agreement(
        -1 if chosen is None else chosen[0],
        save_path if chosen is None else chosen[1])
    if chosen is None:
        if last_err is not None:
            raise last_err
        raise FileNotFoundError(
            f"no model_*.pth checkpoints under {save_path!r}")
    return chosen[2], chosen[1]


def _require_fallback_agreement(epoch: int, path: str) -> None:
    """Every host must fall back to the SAME epoch, or ALL die loudly.

    Symmetric by construction: each host contributes its verified
    epoch (``-1`` = walk exhausted) to one all-gather that every host
    reaches exactly once, then applies the same unanimity check — so
    divergence kills the whole job with a named error on every rank,
    never a survivor hanging at its next collective."""
    if jax.process_count() == 1:
        return
    import numpy as _np
    from jax.experimental import multihost_utils

    epochs = _np.asarray(
        multihost_utils.process_allgather(_np.int32(epoch)))
    if int(epochs.min()) == int(epochs.max()):
        return
    raise CheckpointCorruptError(
        f"--resume auto fallback diverged across hosts: per-host "
        f"verified epochs {epochs.tolist()} (this host, rank "
        f"{dist.get_rank()}: epoch {epoch}, {path}; -1 = every local "
        "copy corrupt). A newer checkpoint copy is corrupt on some "
        "host — restore/re-sync save_path across hosts instead of "
        "resuming split-brain (epoch-skewed save collectives "
        "deadlock). Raised on EVERY rank so no host survives to hang")


def resolve_auto_resume(save_path: str) -> Optional[str]:
    """Multi-host-safe ``--resume auto``: the PRIMARY host's latest
    checkpoint decides for everyone.

    Resolving independently per host can silently disagree (workers with
    a host-local save_path see no files, start at epoch 1, and the
    per-epoch save collectives then deadlock against the primary's
    shifted epoch range). The primary's epoch is broadcast; every other
    host must find the same file locally or fail loudly — ``--resume
    auto`` across hosts requires a shared filesystem.
    """
    found = _checkpoint_epochs(save_path)
    # -1 = no checkpoint: epoch 0 is LEGAL (the preemption handler saves
    # model_0.pth when interrupted during epoch 1)
    my_epoch = max(found)[0] if found else -1
    if jax.process_count() == 1:
        return latest_checkpoint(save_path) if found else None
    from jax.experimental import multihost_utils

    epoch = int(multihost_utils.broadcast_one_to_all(my_epoch))
    if epoch < 0:
        return None
    match = [name for e, name in found if e == epoch]
    # symmetric presence check: EVERY host reaches this one all-gather
    # and every host applies the same test, so a missing file kills
    # the whole job loudly — a host raising alone (while the others
    # proceed into load_with_fallback's agreement collective) would
    # leave them wedged forever instead
    import numpy as _np

    has = _np.asarray(
        multihost_utils.process_allgather(_np.int32(bool(match))))
    if int(has.min()) == 0:
        raise FileNotFoundError(
            f"--resume auto: primary host resolved epoch {epoch} but "
            f"{int((has == 0).sum())} host(s) have no matching "
            f"model_*.pth under {save_path} (this host, rank "
            f"{dist.get_rank()}: "
            f"{'found' if match else 'missing'}) — auto-resume across "
            "hosts requires save_path on a SHARED filesystem (or pass "
            "an explicit --resume path). Raised on EVERY rank so no "
            "host survives to hang at the next collective"
        )
    return os.path.join(save_path, match[0])
