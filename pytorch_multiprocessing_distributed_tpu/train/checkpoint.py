"""Checkpoint save/load.

Artifact parity target: the reference saves the (DDP-wrapped) state dict
on rank 0 at the final epoch only, named ``model_{epoch}.pth``
(``main.py:75-77``), and has NO load/resume path. Here:

- :func:`save_checkpoint` writes the full :class:`..train.TrainState`
  (params, BN running stats, optimizer buffers, epoch) as msgpack bytes
  under the same ``model_{epoch}.pth`` name, single-writer (primary host);
- :func:`load_checkpoint` restores it — the resume path the reference
  lacks (SURVEY.md §5 "Checkpoint / resume").

msgpack via ``flax.serialization`` rather than pickle: deterministic,
framework-neutral bytes, no arbitrary-code-execution on load.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import dist
from .state import TrainState


def _gather_for_host(tree):
    """Make every leaf fully host-addressable before serialization.

    Under ``--zero1`` (and multi-host TP) state leaves are sharded
    across hosts, so a bare ``jax.device_get`` would raise
    "spans non-addressable devices". A jitted identity with replicated
    ``out_shardings`` all-gathers such a leaf onto every device of its
    mesh. This is a COLLECTIVE: every host must call it, so it runs
    BEFORE any primary-host gating. Single-host states pass through
    untouched (everything is already addressable).
    """

    def fix(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            mesh = leaf.sharding.mesh
            return jax.jit(
                lambda x: x, out_shardings=NamedSharding(mesh, P())
            )(leaf)
        return leaf

    return jax.tree.map(fix, tree)


def checkpoint_path(save_path: str, epoch: int) -> str:
    """``{save_path}/model_{epoch}.pth`` (reference ``main.py:77``)."""
    return os.path.join(save_path, "model_{0}.pth".format(epoch))


def save_checkpoint(save_path: str, state: TrainState, epoch: int) -> Optional[str]:
    """Write the state on the primary host; returns the path (None on
    non-primary hosts, which mirror the reference's rank-gating at
    ``main.py:75``)."""
    # Collective leaf replication first — ALL hosts participate even
    # though only the primary writes (see _gather_for_host).
    state = _gather_for_host(state)
    if not dist.is_primary():
        return None
    # Pull fully-addressable host copies off the devices.
    host_state = jax.device_get(state)
    payload = serialization.to_bytes(host_state)
    path = checkpoint_path(save_path, epoch)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)  # atomic: no torn checkpoints on preemption
    return path


def load_checkpoint(path: str, template: TrainState) -> TrainState:
    """Restore a checkpoint into the structure of ``template``
    (a freshly-initialized state with the same model/optimizer).

    Forward-compatible with checkpoints written before a TrainState
    field existed (e.g. ``ema_params``): missing top-level fields keep
    the template's value instead of failing the restore.

    EMA resume semantics: when the template tracks EMA (``--ema``) but
    the checkpoint has none (missing key OR the empty ``{}`` every
    non-EMA checkpoint serializes), the EMA is seeded from the
    checkpoint's TRAINED params — never from the template's fresh
    random init, which would poison every eval for ~1/(1-decay) steps.

    Torch interop: a reference-trained ``model_{epoch}.pth`` is a torch
    zip archive, not msgpack. Detected by magic and routed through
    :mod:`..utils.torch_interop` — params + BN stats load, the
    optimizer starts fresh (torch SGD momentum buffers don't map onto
    this optimizer's tree), and the epoch keeps the template's value.
    """
    import zipfile

    if zipfile.is_zipfile(path):
        from ..utils.torch_interop import load_torch_checkpoint

        params, stats = load_torch_checkpoint(
            path, template.params, template.batch_stats
        )
        state = template.replace(params=params, batch_stats=stats)
        if getattr(template, "ema_params", None):
            state = state.replace(ema_params=params)
        return state
    with open(path, "rb") as f:
        payload = f.read()
    state_dict = serialization.msgpack_restore(payload)
    template_dict = serialization.to_state_dict(template)
    if template_dict.get("ema_params") and not state_dict.get("ema_params"):
        state_dict["ema_params"] = state_dict["params"]
    for key, value in template_dict.items():
        state_dict.setdefault(key, value)
    return serialization.from_state_dict(template, state_dict)


def _checkpoint_epochs(save_path: str):
    """``[(epoch, filename), ...]`` for every parseable ``model_*.pth``
    under ``save_path`` — the ONE place the naming scheme is decoded
    (prune/latest/auto-resume all consume this)."""
    found = []
    if not os.path.isdir(save_path):
        return found
    for name in os.listdir(save_path):
        if name.startswith("model_") and name.endswith(".pth"):
            try:
                found.append((int(name[len("model_"):-len(".pth")]), name))
            except ValueError:
                continue
    return found


def prune_checkpoints(save_path: str, keep: int) -> None:
    """Delete all but the ``keep`` highest-epoch ``model_*.pth`` files.

    Primary-host-only callers (the trainer gates this like the writes);
    ``keep <= 0`` disables pruning. Removes the LISTED filename (never a
    reconstructed one — ``model_007.pth`` parses to epoch 7 but is not
    named ``model_7.pth``).
    """
    if keep <= 0:
        return
    for _, name in sorted(_checkpoint_epochs(save_path))[:-keep]:
        os.remove(os.path.join(save_path, name))


def latest_checkpoint(save_path: str) -> Optional[str]:
    """Highest-epoch ``model_*.pth`` under ``save_path``, if any."""
    found = _checkpoint_epochs(save_path)
    return os.path.join(save_path, max(found)[1]) if found else None


def resolve_auto_resume(save_path: str) -> Optional[str]:
    """Multi-host-safe ``--resume auto``: the PRIMARY host's latest
    checkpoint decides for everyone.

    Resolving independently per host can silently disagree (workers with
    a host-local save_path see no files, start at epoch 1, and the
    per-epoch save collectives then deadlock against the primary's
    shifted epoch range). The primary's epoch is broadcast; every other
    host must find the same file locally or fail loudly — ``--resume
    auto`` across hosts requires a shared filesystem.
    """
    found = _checkpoint_epochs(save_path)
    # -1 = no checkpoint: epoch 0 is LEGAL (the preemption handler saves
    # model_0.pth when interrupted during epoch 1)
    my_epoch = max(found)[0] if found else -1
    if jax.process_count() == 1:
        return latest_checkpoint(save_path) if found else None
    from jax.experimental import multihost_utils

    epoch = int(multihost_utils.broadcast_one_to_all(my_epoch))
    if epoch < 0:
        return None
    match = [name for e, name in found if e == epoch]
    if not match:
        raise FileNotFoundError(
            f"--resume auto: primary host resolved epoch {epoch} but "
            f"this host (rank {dist.get_rank()}) has no matching "
            f"model_*.pth under {save_path} — auto-resume across hosts "
            "requires save_path on a SHARED filesystem (or pass an "
            "explicit --resume path)"
        )
    return os.path.join(save_path, match[0])
