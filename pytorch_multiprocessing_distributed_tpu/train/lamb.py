"""LAMB optimizer — large-batch training (BASELINE.md config #5:
ConvNeXt-L / ImageNet-21k large-batch stress).

You, Li et al., "Large Batch Optimization for Deep Learning: Training
BERT in 76 minutes" (layerwise adaptive moments). Pure transform with the
same ``Transform`` interface as :func:`.optim.sgd` so the trainer and
train step are unchanged — the extension seam the reference's optimizer
block (``main.py:51-55``) never had.

Update rule (per layer/leaf):
  m = b1 m + (1-b1) g            v = b2 v + (1-b2) g^2
  mhat = m / (1-b1^t)            vhat = v / (1-b2^t)
  u = mhat / (sqrt(vhat)+eps) + wd * p
  r = ||p|| / ||u||  (trust ratio; 1 where either norm is 0)
  p <- p - lr * r * u
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optim import ScalarOrSchedule, Transform


class LambState(NamedTuple):
    mu: Any
    nu: Any
    count: jax.Array


def lamb(
    learning_rate: ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.0,
) -> Transform:
    def init(params) -> LambState:
        return LambState(
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
            count=jnp.zeros((), jnp.int32),
        )

    def update(grads, state: LambState, params, lr_step=None):
        if callable(learning_rate):
            lr = learning_rate(lr_step)
        else:
            lr = jnp.asarray(learning_rate, jnp.float32)
        count = state.count + 1
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(g), state.nu, grads
        )

        def one(p, m, v):
            mhat = m / c1
            vhat = v / c2
            u = mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p
            p_norm = jnp.linalg.norm(p)
            u_norm = jnp.linalg.norm(u)
            # trust ratio, guarded exactly as in the paper/optax: 1 when
            # either norm vanishes
            r = jnp.where(
                p_norm > 0, jnp.where(u_norm > 0, p_norm / u_norm, 1.0), 1.0
            )
            return -lr * r * u

        updates = jax.tree.map(one, params, mu, nu)
        return updates, LambState(mu=mu, nu=nu, count=count)

    return Transform(init, update)
