"""LAMB optimizer — large-batch training (BASELINE.md config #5:
ConvNeXt-L / ImageNet-21k large-batch stress).

You, Li et al., "Large Batch Optimization for Deep Learning: Training
BERT in 76 minutes" (layerwise adaptive moments). Pure transform with the
same ``Transform`` interface as :func:`.optim.sgd` so the trainer and
train step are unchanged — the extension seam the reference's optimizer
block (``main.py:51-55``) never had.

Update rule (per layer/leaf):
  m = b1 m + (1-b1) g            v = b2 v + (1-b2) g^2
  mhat = m / (1-b1^t)            vhat = v / (1-b2^t)
  u = mhat / (sqrt(vhat)+eps) + wd * p
  r = ||p|| / ||u||  (trust ratio; 1 where either norm is 0)
  p <- p - lr * r * u
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optim import ScalarOrSchedule, Transform


class LambState(NamedTuple):
    mu: Any
    nu: Any
    count: jax.Array


def lamb(
    learning_rate: ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.0,
) -> Transform:
    def init(params) -> LambState:
        return LambState(
            mu=jax.tree.map(jnp.zeros_like, params),
            nu=jax.tree.map(jnp.zeros_like, params),
            count=jnp.zeros((), jnp.int32),
        )

    def _lr(lr_step):
        if callable(learning_rate):
            return learning_rate(lr_step)
        return jnp.asarray(learning_rate, jnp.float32)

    def shard_update(grads, state: LambState, params, lr_step=None):
        """The ELEMENTWISE phase: moment updates + bias-corrected
        direction ``u`` (pre-trust-ratio). Runs identically on full
        leaves and on graftzero's flat 1-D shards — the trust ratio is
        the only per-leaf reduction, split into ``shard_finish``."""
        count = state.count + 1
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(g), state.nu, grads
        )
        u = jax.tree.map(
            lambda p, m, v: (m / c1) / (jnp.sqrt(v / c2) + eps)
            + weight_decay * p,
            params, mu, nu,
        )
        return u, LambState(mu=mu, nu=nu, count=count)

    def shard_finish(updates, params, lr_step=None):
        """The PER-LEAF phase: trust ratio + LR, on full leaves (under
        graftzero this runs after the direction's all-gather, so the
        norms see exactly what the replicated update sees).

        The direction is materialized at this boundary
        (``optimization_barrier`` — graftzero's all-gather already
        does this implicitly): without it XLA fuses ``u`` separately
        into each of its three consumers (norm, scale, apply) with
        per-site FMA contraction, and the replicated trajectory
        drifts 1 ulp from the sharded one once the moments are
        nonzero. The barrier pins one evaluation in both programs —
        bit-identical by construction, at the cost of one
        param-sized buffer XLA would likely materialize anyway."""
        leaves = jax.lax.optimization_barrier(
            tuple(jax.tree.leaves(updates)))
        updates = jax.tree.unflatten(jax.tree.structure(updates),
                                     list(leaves))
        lr = _lr(lr_step)

        def one(u, p):
            p_norm = jnp.linalg.norm(p)
            u_norm = jnp.linalg.norm(u)
            # trust ratio, guarded exactly as in the paper/optax: 1 when
            # either norm vanishes
            r = jnp.where(
                p_norm > 0, jnp.where(u_norm > 0, p_norm / u_norm, 1.0), 1.0
            )
            return -lr * r * u

        return jax.tree.map(one, updates, params)

    def update(grads, state: LambState, params, lr_step=None):
        # the replicated update IS the two phases composed — one copy
        # of the math, so sharded == replicated by construction
        u, new_state = shard_update(grads, state, params, lr_step=lr_step)
        return shard_finish(u, params, lr_step=lr_step), new_state

    return Transform(init, update, shard_update=shard_update,
                     shard_finish=shard_finish)
