"""Epoch-level train/validate loops.

Behavioral parity with the reference's ``train`` (``main.py:87-131``) and
``validate`` (``main.py:134-171``): same meters, same stdout line formats,
same ``[epoch, loss.avg, acc]`` log rows, primary-host gating everywhere
the reference gates on rank 0.

Two deliberate fixes of record (SURVEY.md §3.5):
- eval accuracy uses the globally ``psum``-ed correct count (the
  reference divides a per-rank count by the full dataset size,
  ``main.py:151,168`` — wrong by ~world_size);
- the LR schedule is a pure function of the epoch evaluated on every
  replica (the reference steps it on rank 0 only, ``main.py:69-70``).

Timing note: XLA dispatch is asynchronous — ``time.time()`` around the
step call measures nothing (SURVEY.md §5 "Tracing"). The hot loop
therefore keeps the step's scalar metrics ON DEVICE and fetches them only
at ``print_freq`` boundaries (and at epoch end): between fetches the
steps pipeline freely (async dispatch overlaps H2D, compute and the next
dispatch), and each fetch is a real synchronization point, so the
window's wall-clock divided by its step count is honest per-step time.
The reference pays a device->host sync EVERY iteration for ``.item()``
(``main.py:113-115``); VERDICT r1 measured that pattern costing real
throughput here, so the meters take the same values in windowed batches
instead (identical averages, identical printed lines).
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..data.pipeline import ShardedLoader, prefetch_to_device
from ..parallel import dist
from ..runtime import scope as graftscope
from ..parallel.mesh import MODEL_AXIS
from ..utils import AverageMeter, Logger
from ..utils.plotting import draw_plot
from .checkpoint import prune_checkpoints, save_checkpoint
from .state import TrainState
from .step import (
    make_eval_step,
    make_eval_step_tp,
    make_train_step,
    make_train_step_tp,
    register_state_hbm,
    shard_state,
)


_HANDLER_NOT_INSTALLED = object()  # signal handler sentinel (see fit)


class Trainer:
    """Drives the compiled steps over epochs, reproducing the reference CLI
    trainer's observable behavior (``main.py:32-84``)."""

    def __init__(
        self,
        *,
        model,
        optimizer,
        mesh,
        state: TrainState,
        train_loader: ShardedLoader,
        test_loader: ShardedLoader,
        save_path: str,
        epochs: int,
        print_freq: int = 10,
        start_epoch: int = 1,
        zero: bool = False,
        zero1: bool = False,
        fsdp: bool = False,
        remat: bool = False,
        grad_accum: int = 1,
        loss_fn=None,
        clip_grad_norm=None,
        ema_decay=None,
        save_every: int = 0,
        keep_checkpoints: int = 0,
        ckpt_backend: str = "msgpack",
        ckpt_async: bool = False,
    ):
        self.mesh = mesh
        self.state = state
        self.train_loader = train_loader
        self.test_loader = test_loader
        self.save_path = save_path
        self.epochs = epochs
        self.print_freq = print_freq
        # resume continues the epoch series (and thus the LR schedule and
        # the log-row numbering) instead of restarting at 1 — the resume
        # path the reference lacks entirely.
        self.start_epoch = start_epoch
        # periodic checkpointing (0 = reference behavior: final epoch
        # only, main.py:75-77) with optional keep-K retention
        self.save_every = save_every
        self.keep_checkpoints = keep_checkpoints
        # "msgpack" = reference-parity model_{epoch}.pth (host-gathered,
        # torch-interoperable); "orbax" = sharded per-host OCDBT writes
        # under {save_path}/orbax/ — no gather, scales with the model
        # (requires save_path on SHARED storage across hosts)
        if ckpt_backend == "orbax":
            from .orbax_ckpt import OrbaxCheckpointer

            self._orbax = OrbaxCheckpointer(
                save_path, keep=keep_checkpoints or None,
                async_=ckpt_async,
            )
        elif ckpt_async:
            raise ValueError(
                "ckpt_async requires ckpt_backend='orbax' (the msgpack "
                "writer is synchronous by design)"
            )
        elif ckpt_backend != "msgpack":
            raise ValueError(
                f"ckpt_backend must be 'msgpack' or 'orbax', "
                f"got {ckpt_backend!r}"
            )
        self.ckpt_backend = ckpt_backend
        # evaluate/checkpoint with EMA weights when tracking is on
        self.ema_decay = ema_decay
        from ..ops.losses import cross_entropy_loss

        loss_fn = loss_fn or cross_entropy_loss
        # graftzero: the shard_map-DP sharded weight update. Distinct
        # from --zero1 (the GSPMD zero1 placement): this mode rewrites
        # the explicit DP step's communication schedule, so it
        # composes with pure DP only.
        self._zero = zero
        if zero:
            if dict(mesh.shape).get(MODEL_AXIS, 1) > 1 or zero1 or fsdp:
                raise ValueError(
                    "zero=True is the explicit shard_map-DP sharded "
                    "update; under --model_parallel/--zero1/--fsdp the "
                    "GSPMD path already owns the state placement — use "
                    "zero1/fsdp there instead")
            if ckpt_backend == "orbax":
                raise ValueError(
                    "zero=True checkpoints via the msgpack "
                    "gather-on-save path (mode-portable artifacts); "
                    "--ckpt_backend orbax would persist the sharded "
                    "layout and break --resume round-trips — use "
                    "msgpack with --zero")
        if dict(mesh.shape).get(MODEL_AXIS, 1) > 1 or zero1 or fsdp:
            # the GSPMD step: real tensor parallelism (params sharded
            # over the model axis), ZeRO-1 (optimizer moments sharded
            # over the data axis) and/or FSDP/ZeRO-3 (params + stats +
            # moments all sharded over data). The model must carry
            # ``bn_axis=None`` — BN stats are global by construction
            # there; main.py builds it accordingly.
            self.state = shard_state(state, mesh, zero1=zero1, fsdp=fsdp)
            self.train_step = make_train_step_tp(
                model, optimizer, mesh, zero1=zero1, fsdp=fsdp,
                remat=remat, grad_accum=grad_accum, loss_fn=loss_fn,
                clip_grad_norm=clip_grad_norm, ema_decay=ema_decay,
            )
            self.eval_step = make_eval_step_tp(
                model, mesh, zero1=zero1, fsdp=fsdp, loss_fn=loss_fn
            )
        else:
            self.train_step = make_train_step(
                model, optimizer, mesh, remat=remat, grad_accum=grad_accum,
                loss_fn=loss_fn, clip_grad_norm=clip_grad_norm,
                ema_decay=ema_decay, zero=zero,
            )
            self.eval_step = make_eval_step(model, mesh, loss_fn=loss_fn)
            if zero:
                # moments sharded from step one: the replicated tree
                # (fresh init or a restored checkpoint) is flattened
                # into P(data) buckets and never materializes again
                from ..parallel.zero import zeroify_state

                self.state = zeroify_state(self.state, mesh)
        self.train_logger = Logger(os.path.join(save_path, "train.log"))
        self.test_logger = Logger(os.path.join(save_path, "test.log"))
        # graftmeter: resident-state footprint on the armed ledger
        # (the GSPMD branch already registered inside shard_state —
        # same entries, same bytes; the DP branch registers here), and
        # the live throughput gauges main.py --stats_port serves —
        # updated at the windowed fetch the loop already pays
        register_state_hbm(self.state)
        self.live = {}

    # ------------------------------------------------------------- epochs

    def _install_preemption_handler(self):
        """SIGTERM -> checkpoint-and-exit at the next metrics window.

        TPU preemptions/maintenance deliver SIGTERM to every host of the
        slice; instead of dying mid-step, the hot loop notices the flag
        at its next fetch boundary, saves a checkpoint that resumes at
        the INTERRUPTED epoch (the partial epoch is redone — its steps
        are not individually recoverable), and exits cleanly. Installed
        only in the main thread of the main interpreter; a prior handler
        is chained so external supervisors still see the signal.
        """
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return _HANDLER_NOT_INSTALLED
        self._preempted = False
        # NB getsignal() returns None for a handler installed from C —
        # still a value we must RESTORE (hence the distinct sentinel
        # for the not-installed case above)
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self._preempted = True
            if callable(prev) and prev not in (
                signal.SIG_IGN, signal.SIG_DFL, handler
            ):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, handler)
        return prev

    def _checkpoint_if_preempted(self, epoch: int) -> None:
        """Called at metrics-window boundaries inside the hot loop.

        Multi-host: the local SIGTERM flag is AGREED across hosts first
        (signal delivery skews by milliseconds; a host branching into
        the save collectives while another dispatches the next train
        step would deadlock the slice — the exact failure this feature
        exists to avoid). Any host's flag preempts everyone.
        """
        preempted = bool(getattr(self, "_preempted", False))
        if jax.process_count() > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            flags = multihost_utils.process_allgather(
                _np.int32(preempted)
            )
            preempted = bool(flags.max())
        if not preempted:
            return
        graftscope.emit("train.preempted", cat="train", epoch=epoch)
        if dist.is_primary():
            print(
                f"SIGTERM received: checkpointing at epoch {epoch} "
                f"(resume redoes the interrupted epoch) and exiting"
            )
        # resume continues AT `epoch`: load_checkpoint restores
        # state.epoch and main.py starts from state.epoch + 1. If a
        # REAL end-of-epoch checkpoint for epoch-1 already exists
        # (--save_every), keep it: overwriting it with mid-epoch state
        # would destroy a clean artifact for zero resume benefit.
        from .checkpoint import checkpoint_path

        if self.ckpt_backend == "orbax":
            target = os.path.join(self._orbax.directory, str(epoch - 1))
            exists = self._orbax.has_epoch(epoch - 1)
        else:
            target = checkpoint_path(self.save_path, epoch - 1)
            exists = os.path.exists(target)
        # The skip-vs-save decision must be UNIFORM across hosts: only
        # the primary writes msgpack checkpoints, so with a non-shared
        # save_path the file exists only there — a per-host
        # os.path.exists would send the primary down the skip branch
        # while workers enter save_checkpoint's gather collective,
        # deadlocking the slice. The primary's verdict is broadcast
        # (same pattern as resolve_auto_resume).
        if jax.process_count() > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            exists = bool(
                multihost_utils.broadcast_one_to_all(_np.int32(exists))
            )
        if exists:
            if dist.is_primary():
                print(f"keeping existing {target} (same resume point)")
        else:
            self._save_state(
                self.state.replace(epoch=jnp.asarray(epoch - 1, jnp.int32)),
                epoch - 1,
            )
        raise SystemExit(0)

    def _save_state(self, state: TrainState, epoch: int,
                    wait: bool = True) -> None:
        """One checkpoint write through the configured backend. EVERY
        host calls this: the msgpack path's sharded-leaf gather is a
        collective (the write itself is primary-gated inside), and the
        orbax path has every host writing its own shards.

        ``wait=False`` (async orbax) lets a periodic mid-training save
        overlap serialization with the next epochs; callers that rely
        on the artifact existing when they move on (final epoch,
        preemption exit) keep the default."""
        with graftscope.span("train.checkpoint", cat="train",
                             epoch=epoch, backend=self.ckpt_backend,
                             wait=wait):
            if self.ckpt_backend == "orbax":
                self._orbax.save(state, epoch)
                if wait:
                    self._orbax.wait()
            else:
                save_checkpoint(self.save_path, state, epoch)
                if dist.is_primary():
                    prune_checkpoints(self.save_path,
                                      self.keep_checkpoints)

    def fit(self) -> TrainState:
        """The reference's epoch loop (``main.py:67-82``)."""
        prev_handler = self._install_preemption_handler()
        try:
            # an unhandled exception unwinding the epoch loop dumps
            # the flight ring first (preemption's SystemExit is exempt
            # — that exit is the graceful path, not a crash)
            with graftscope.flight_recorder("trainer loop"):
                self._fit_epochs()
        finally:
            try:
                if self.ckpt_backend == "orbax":
                    # an async periodic save may still be in flight
                    # (e.g. when an exception unwinds the epoch loop) —
                    # make it durable before the process can exit
                    self._orbax.wait()
            finally:
                # a caller's process must not permanently swallow
                # SIGTERM after training ends — restore EVEN IF the
                # wait above raises (failed async commit)
                self._restore_handler(prev_handler)
        if dist.is_primary():
            draw_plot(self.save_path)
        return self.state

    def _fit_epochs(self) -> None:
        for epoch in range(self.start_epoch, self.epochs + 1):
            # LR schedule is a function of the epoch carried in the
            # state (uniform across replicas — fixed vs reference
            # main.py:69-70).
            self.state = self.state.replace(
                epoch=jnp.asarray(epoch, jnp.int32)
            )
            self.train_epoch(epoch)
            self.validate(epoch, mode="test")
            periodic = self.save_every and epoch % self.save_every == 0
            if epoch == self.epochs or periodic:
                # mid-training periodic saves may overlap with the
                # next epochs (async orbax); the final one is durable
                # before fit returns
                self._save_state(self.state, epoch,
                                 wait=epoch == self.epochs)

    @staticmethod
    def _restore_handler(prev_handler) -> None:
        if prev_handler is not _HANDLER_NOT_INSTALLED:
            import signal

            # None = prior handler lives in C and is invisible to
            # Python; SIG_DFL at least lets TERM terminate again
            signal.signal(
                signal.SIGTERM,
                signal.SIG_DFL if prev_handler is None else prev_handler,
            )

    # -------------------------------------------------------------- train

    def train_epoch(self, epoch: int) -> None:
        batch_time = AverageMeter()
        data_time = AverageMeter()
        losses = AverageMeter()
        top1 = AverageMeter()

        self.train_loader.set_epoch(epoch)
        n_batches = len(self.train_loader)
        skipped = 0  # steps the NaN/inf grad guard refused to apply
        pending = []  # device-resident metric dicts since the last fetch
        window_start = time.time()
        end = time.time()
        for i, (images, labels) in enumerate(
            prefetch_to_device(self.train_loader, self.mesh)
        ):
            data_time.update(time.time() - end)
            # data-wait span, recorded retroactively from the meter's
            # own measurement — graftscope adds NO clock reads or
            # syncs to the hot loop, only an append when armed
            graftscope.emit_span("train.data", data_time.val,
                                 cat="train", batch=i)
            self.state, metrics = self.train_step(self.state, images, labels)
            # NO host sync here: the scalars stay on device and the next
            # step's dispatch overlaps this one's execution.
            pending.append(metrics)
            if i % self.print_freq == 0 or i == n_batches - 1:
                # graftheal: the liveness gate sits at the SAME window
                # boundary as the preemption check — a dead peer
                # raises a named PeerLostError here, before this host
                # dispatches more steps whose collectives would hang
                # on it (one global read when no monitor is armed)
                dist.gate_collectives()
                self._checkpoint_if_preempted(epoch)
                with graftscope.span("train.metrics_fetch", cat="train",
                                     epoch=epoch, steps=len(pending)):
                    fetched = jax.device_get(pending)  # the sync point
                for m in fetched:
                    # the guard's skip indicator rides the same windowed
                    # fetch — a skipped step is VISIBLE, never silent,
                    # and its metrics (the poisoned batch's, possibly
                    # NaN) stay out of every meter
                    if int(m.get("skipped", 0)):
                        skipped += 1
                        continue
                    losses.update(float(m["loss"]), int(m["count"]))
                    top1.update(float(m["prec1"]), int(m["count"]))
                now = time.time()
                batch_time.update(
                    (now - window_start) / len(pending), len(pending)
                )
                # the fetch boundary is the ONE honest per-window
                # timing point under async dispatch: the window span
                # covers its steps' wall clock, attributed here
                graftscope.emit_span(
                    "train.window", now - window_start, cat="train",
                    epoch=epoch, steps=len(pending),
                    step_avg_s=batch_time.val)
                window_start = now
                # live gauges for --stats_port: host values already in
                # hand at this (the loop's one) sync boundary
                global_batch = getattr(self.train_loader,
                                       "batch_size", 0)
                self.live.update(
                    epoch=epoch, batch=i, loss=losses.avg,
                    prec1=top1.avg, step_time_s=batch_time.val,
                    images_per_sec=(0.0 if not batch_time.val else
                                    global_batch / batch_time.val),
                    steps_skipped=skipped)
                pending = []
                if dist.is_primary() and i % self.print_freq == 0:
                    print(
                        "Epoch: [{0}][{1}/{2}]\t"
                        "Time {batch_time.val:.3f} ({batch_time.avg:.3f})\t"
                        "Data {data_time.val:.3f} ({data_time.avg:.3f})\t"
                        "Loss {loss.val:.4f} ({loss.avg:.4f})\t"
                        "Prec {top1.val:.3f}% ({top1.avg:.3f}%)".format(
                            epoch, i, n_batches,
                            batch_time=batch_time, data_time=data_time,
                            loss=losses, top1=top1,
                        )
                    )
            end = time.time()
        if dist.is_primary():
            if skipped:
                print(
                    f"Epoch [{epoch}]: NaN/inf grad guard skipped "
                    f"{skipped}/{n_batches} step(s) (params carried "
                    "through unchanged)"
                )
            self.train_logger.write([epoch, losses.avg, top1.avg])

    # ---------------------------------------------------------------- eval

    def validate(self, epoch: int, mode: str = "test") -> float:
        batch_time = AverageMeter()
        losses = AverageMeter()
        total_correct = 0

        self.test_loader.set_epoch(epoch)
        # EMA evaluation: swap the averaged weights in (standard EMA
        # practice; BN running stats are already their own EMA).
        eval_state = self.state
        if self.ema_decay and getattr(self.state, "ema_params", None):
            eval_state = self.state.replace(params=self.state.ema_params)
        if self._zero:
            # the eval step reads params/stats only; its replicated
            # state spec would silently all-gather the sharded moment
            # buckets per batch — hand it a state without them
            eval_state = eval_state.replace(opt_state={})
        n_batches = len(self.test_loader)
        pending = []
        window_start = time.time()
        for i, batch in enumerate(
            prefetch_to_device(self.test_loader, self.mesh)
        ):
            if len(batch) == 3:
                images, labels, valid = batch
            else:  # loader without validity info: everything counts
                images, labels = batch
                valid = jnp.ones(labels.shape, bool)
            pending.append(self.eval_step(eval_state, images, labels, valid))
            if i % self.print_freq == 0 or i == n_batches - 1:
                with graftscope.span("train.eval_fetch", cat="train",
                                     epoch=epoch, steps=len(pending)):
                    fetched = jax.device_get(pending)
                for m in fetched:
                    losses.update(float(m["loss"]), int(m["count"]))
                    total_correct += int(m["correct"])  # GLOBAL (psum-ed)
                now = time.time()
                batch_time.update(
                    (now - window_start) / len(pending), len(pending)
                )
                window_start = now
                pending = []
                if dist.is_primary() and i % self.print_freq == 0:
                    print(
                        mode,
                        ": [{0}/{1}]\t"
                        "Time {batch_time.val:.3f} ({batch_time.avg:.3f})\t"
                        "Loss {loss.val:.4f} ({loss.avg:.4f})".format(
                            i, n_batches, batch_time=batch_time, loss=losses
                        ),
                    )
        total_acc = 100.0 * total_correct / self.test_loader.dataset_size
        if dist.is_primary():
            print("Accuracy {:.2f}".format(total_acc))
            self.test_logger.write([epoch, losses.avg, float(total_acc)])
        return total_acc
