"""graftmeter: static cost/memory model + capacity planner.

graftcheck (PR 5) pins what the canonical programs *are* (structure,
collectives, donation); graftmeter pins what they *cost*: FLOPs, bytes
accessed, arithmetic intensity, and the compiled memory breakdown
(argument/output/temp/generated-code bytes) from XLA's own analyses of
the EXACT lowered executable — the shared
``utils.compile_cache.lowered_program_analysis`` path the bench's MFU
math already reads, so the budgeted program, the benched program and
the audited program are one program.

Three pieces:

- **committed cost budgets** (``analysis/costs.json``): every program
  in the graftcheck registry (``analysis/programs.py``) carries a
  committed ``{flops, bytes_accessed, arithmetic_intensity, memory}``
  record, compared field-by-field by ``make check`` exactly like
  fingerprints — a program that silently grows its temp HBM (lost
  rematerialization, an accidental f32 copy of the cache) fails tier-1
  with a readable "+N MiB temp_bytes" diff naming program and field;
  deliberate changes re-baseline via ``make check-update``.
- **capacity planner** (:func:`plan_capacity`): inverts the HBM ledger
  arithmetic — given a model, a sequence capacity, and a per-chip HBM
  budget, how many KV slots / how large a decode batch actually fit
  beside the parameters. Exact by construction (the same shape x dtype
  products the allocations use), validated against real CPU-backend
  allocation in the tier-1 meter smoke.
- **roofline helpers** (:func:`roofline`): classify a measured point
  as compute- or bandwidth-bound against per-chip peak FLOP/s and HBM
  bandwidth; ``bench.py`` / ``serving_bench.py`` stamp every record
  with the join (achieved FLOP/s, MFU, achieved bytes/s vs the static
  model).

CLI::

    python -m pytorch_multiprocessing_distributed_tpu.analysis.meter
        [--programs NAME ...] [--update] [--json]
    python -m ...analysis.meter --plan gpt_small --s_max 2048 \
        --hbm_gb 16

Rule table (GM — meter-level, disjoint from GL/GC):
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

RULES_GM: Dict[str, str] = {
    "GM100": "program failed to compile for cost/memory metering",
    "GM101": "compute budget drift: FLOPs / bytes-accessed / "
             "arithmetic intensity differ from the committed budget",
    "GM102": "memory budget drift: argument/output/temp/generated-code "
             "bytes differ from the committed budget (temp growth = "
             "lost remat or an accidental resident copy)",
    "GM103": "cost coverage: program has no committed cost entry (or a "
             "committed entry names no registered program)",
}

# a compiled program whose backend exposes no cost/memory model still
# gets a committed entry with explicit nulls — absence must be loud,
# not a skipped comparison
_MEMORY_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                  "alias_bytes", "generated_code_bytes", "peak_bytes")


def costs_record(cost: Optional[dict],
                 memory: Optional[dict]) -> dict:
    """Assemble one program's cost budget from the shared lowering
    path's ``(cost, memory)`` analyses. FLOPs/bytes come from XLA's
    cost model (``flops`` / ``bytes accessed``); intensity is their
    quotient (FLOP per HBM byte — the roofline x-coordinate)."""
    flops = None
    bytes_accessed = None
    if cost:
        f = cost.get("flops")
        b = cost.get("bytes accessed")
        flops = int(f) if f is not None and f >= 0 else None
        bytes_accessed = int(b) if b is not None and b >= 0 else None
    intensity = None
    if flops and bytes_accessed:
        intensity = round(flops / bytes_accessed, 4)
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": intensity,
        "memory": ({k: int(memory[k]) for k in _MEMORY_FIELDS}
                   if memory else None),
    }


# ------------------------------------------------ committed budgets

def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_costs_path() -> str:
    return os.path.join(package_root(), "analysis", "costs.json")


def load_costs(path: Optional[str] = None) -> Dict[str, dict]:
    path = path or default_costs_path()
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        return dict(json.load(fh).get("programs", {}))


def write_costs(records: Dict[str, dict], path: Optional[str] = None,
                *, keep: Optional[Dict[str, dict]] = None) -> None:
    """Snapshot ``records`` (merging ``keep`` for programs outside a
    partial-scope run — same discipline as ``check.write_fingerprints``:
    a laptop refresh must not drop entries it could not re-measure)."""
    import jax

    path = path or default_costs_path()
    programs = dict(keep or {})
    programs.update(records)
    payload = {
        "comment": "graftmeter committed cost/memory budgets (FLOPs, "
                   "bytes accessed, arithmetic intensity, compiled "
                   "argument/output/temp/generated-code bytes) per "
                   "canonical program — refresh deliberately via "
                   "`make check-update` and review the diff; temp "
                   "growth here is lost rematerialization or a new "
                   "resident copy in a hot program.",
        "jax": jax.__version__,
        "programs": {k: programs[k] for k in sorted(programs)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


def _mib(delta: int) -> str:
    sign = "+" if delta >= 0 else "-"
    return f"{sign}{abs(delta) / (1 << 20):.2f} MiB"


def compare_costs(records: Dict[str, dict],
                  committed: Dict[str, dict], *,
                  full_scope: bool,
                  failed: frozenset = frozenset()) -> List:
    """Field-by-field budget comparison; each drift is a rule-tagged
    finding with the delta spelled out in MiB where bytes are
    involved. Returns ``programs.Finding``s (the check CLI renders
    GM findings beside GC ones)."""
    from .programs import Finding

    findings: List = []
    for name, rec in records.items():
        want = committed.get(name)
        if want is None:
            findings.append(Finding(
                name, "GM103",
                "no committed cost budget — run `make check-update` "
                "and review the new analysis/costs.json entry"))
            continue
        # arithmetic_intensity is DERIVED from flops/bytes — compare
        # the components so one real drift reports once, and flag an
        # intensity-only divergence as the tamper it is (the same
        # discipline GM102 applies to peak_bytes below)
        compute_diffs = [f for f in ("flops", "bytes_accessed")
                         if want.get(f) != rec.get(f)]
        for field in compute_diffs:
            findings.append(Finding(
                name, "GM101",
                f"{field}: committed {want.get(field)} -> traced "
                f"{rec.get(field)}"))
        if (not compute_diffs
                and want.get("arithmetic_intensity")
                != rec.get("arithmetic_intensity")):
            findings.append(Finding(
                name, "GM101",
                f"arithmetic_intensity: committed "
                f"{want.get('arithmetic_intensity')} -> traced "
                f"{rec.get('arithmetic_intensity')} — the derived "
                "field disagrees while flops/bytes match (a tampered "
                "entry)"))
        w_mem, g_mem = want.get("memory"), rec.get("memory")
        if w_mem != g_mem:
            if not w_mem or not g_mem:
                findings.append(Finding(
                    name, "GM102",
                    f"memory budget: committed {w_mem} -> traced "
                    f"{g_mem} (None = the backend lost its memory "
                    "model, or the entry was tampered)"))
            else:
                # peak_bytes is DERIVED from the other five — compare
                # the components so one real drift reports once, and
                # flag a peak-only divergence as the tamper it is
                diffs = [f for f in _MEMORY_FIELDS
                         if f != "peak_bytes"
                         and w_mem.get(f) != g_mem.get(f)]
                for field in diffs:
                    w, g = w_mem.get(field), g_mem.get(field)
                    findings.append(Finding(
                        name, "GM102",
                        f"memory.{field}: committed {w} -> traced "
                        f"{g} ({_mib((g or 0) - (w or 0))} "
                        f"{field.replace('_bytes', '')})"))
                if not diffs:
                    findings.append(Finding(
                        name, "GM102",
                        f"memory.peak_bytes: committed "
                        f"{w_mem.get('peak_bytes')} -> traced "
                        f"{g_mem.get('peak_bytes')} — the derived "
                        "field disagrees while its components match "
                        "(a tampered entry)"))
    if full_scope:
        for name in sorted(set(committed) - set(records) - set(failed)):
            findings.append(Finding(
                name, "GM103",
                "committed cost budget names no registered program — "
                "stale entry; `make check-update` prunes it"))
    return findings


# ------------------------------------------------ capacity planner

def plan_capacity(model, s_max: int, hbm_budget: int, *,
                  params=None, optimizer_moments: int = 0,
                  zero_shards: int = 1,
                  reserved_bytes: int = 0,
                  page_size: Optional[int] = None,
                  length_dist: Optional[Sequence[int]] = None,
                  kv_dtype: str = "model") -> dict:
    """Invert the HBM ledger: how much serving capacity fits a chip.

    Args:
      model: the ``GPT`` to plan for (geometry + dtype).
      s_max: per-slot token capacity (prompt + generated).
      hbm_budget: per-chip HBM bytes available to this workload.
      params: optional real/abstract param tree — its exact bytes are
        used; otherwise the tree is shaped with ``jax.eval_shape``
        (zero FLOPs, no allocation).
      optimizer_moments: moment buffers per parameter the resident
        optimizer keeps (serving: 0; SGD+momentum: 1; Adam/LAMB: 2) —
        each costs another ``params_bytes``.
      zero_shards: graftzero DP degree (``--zero`` on the trainer
        CLIs): optimizer moments are sharded into flat buckets over
        this many ranks, so each chip pays ``shard_bytes`` (the exact
        padded-bucket math of ``parallel.zero.plan_buckets`` — ONE
        copy of the layout, byte-exact vs the real
        :class:`~..parallel.zero.ZeroOptState` allocation) per moment
        instead of ``params_bytes``. The freed ``(N-1)/N`` of the
        optimizer state is exactly what this planner re-spends on
        slots/batch. 1 = replicated (the default).
      reserved_bytes: extra fixed reservation (decode-program temps,
        runtime overhead) charged before slots are counted.
      page_size: PAGED mode (graftpage): plan a
        :class:`~..serving.kv_pages.PagePool` instead of dense slots.
        Adds ``page_bytes`` (the exact per-page shape x dtype product
        the pool allocates — byte-exact against a real allocation, the
        same pin style as the dense planner), ``max_pages`` (pages the
        budget holds BESIDE the scratch page; pass
        ``num_pages=plan["max_pages"] + 1`` to ``PagePool`` and its
        ``hbm_bytes`` matches the planned KV bytes exactly),
        ``pages_per_slot_worst`` and — with ``length_dist`` —
        ``expected_pages_per_request`` / ``expected_resident_requests``.
      length_dist: per-request TOTAL token counts (prompt + generated)
        of the traffic to plan for; paged mode averages their page
        demand to predict resident requests at the budget.
      kv_dtype: ``"model"`` or ``"int8"`` (graftquant) — the pool's
        element layout; int8 charges 1 byte per KV element plus the
        4-byte f32 per-token-per-head scale, the exact bytes the
        quantized ``SlotPool``/``PagePool`` allocates, so the
        inversion stays byte-exact in BOTH modes (meter smoke pins
        it against a real pool).

    Returns the plan dict: ``params_bytes``, ``opt_state_bytes``,
    ``per_slot_bytes`` (dense worst-case KV + per-slot scalar state —
    the exact bytes ``SlotPool`` allocates, validated against a real
    CPU-backend pool in the meter smoke), ``max_slots``,
    ``kv_bytes_at_max`` and ``headroom_bytes`` (what is left after
    params + optimizer + reserved + max_slots slots),
    ``max_generate_batch`` (the one-shot ``generate`` twin: rows of a
    ``[L, B, s_max, H, Dh]`` prefill cache instead of pool slots).
    """
    import jax
    import jax.numpy as jnp

    from ..serving.kv_slots import SlotPool

    if hbm_budget <= 0:
        raise ValueError(f"hbm_budget must be > 0, got {hbm_budget}")
    if params is None:
        params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 1), jnp.int32),
                               train=False))["params"]
    from ..runtime.hbm import tree_nbytes

    params_bytes = tree_nbytes(params)
    if int(zero_shards) > 1:
        # the SAME bucket layout the trainer allocates: per-chip
        # moment cost = the padded flat shard, never an estimate
        from ..parallel.zero import plan_buckets

        per_moment = plan_buckets(params, int(zero_shards)).shard_bytes
    else:
        per_moment = params_bytes
    opt_bytes = int(optimizer_moments) * per_moment
    per_slot = (SlotPool.per_slot_kv_bytes(model, s_max, kv_dtype)
                + SlotPool.per_slot_state_bytes())
    fixed = params_bytes + opt_bytes + int(reserved_bytes)
    free = hbm_budget - fixed
    max_slots = max(0, free // per_slot)
    per_row = SlotPool.per_slot_kv_bytes(model, s_max, kv_dtype)
    plan = {
        "hbm_budget": int(hbm_budget),
        "params_bytes": params_bytes,
        "opt_state_bytes": opt_bytes,
        "reserved_bytes": int(reserved_bytes),
        "per_slot_bytes": per_slot,
        "max_slots": int(max_slots),
        "kv_bytes_at_max": int(max_slots * per_slot),
        "headroom_bytes": int(free - max_slots * per_slot),
        "max_generate_batch": int(max(0, free // per_row)),
        "s_max": int(s_max),
        "zero_shards": int(zero_shards),
        "kv_dtype": kv_dtype,
        "fits": fixed <= hbm_budget,
    }
    if page_size is None:
        return plan
    # ---- paged mode (graftpage): same inversion, page-granular.
    # page_bytes is the ONE shape x dtype product PagePool allocates,
    # so planner == allocator byte-for-byte (pinned in the meter
    # smoke); the scratch page is charged before pages are counted.
    from ..serving.kv_pages import PagePool

    page_bytes = PagePool.page_kv_bytes(model, page_size, kv_dtype)
    max_pages = max(0, (free - page_bytes) // page_bytes)  # - scratch
    plan.update({
        "page_size": int(page_size),
        "page_bytes": int(page_bytes),
        "max_pages": int(max_pages),
        "pages_per_slot_worst": PagePool.pages_for(s_max, page_size),
        "paged_kv_bytes_at_max": int((max_pages + 1) * page_bytes),
    })
    if length_dist:
        demand = [PagePool.pages_for(t, page_size)
                  for t in length_dist]
        expected = sum(demand) / len(demand)
        plan["expected_pages_per_request"] = expected
        plan["expected_resident_requests"] = int(max_pages // expected)
    return plan


# --------------------------------------------------- roofline join

def roofline(flops: Optional[float], bytes_accessed: Optional[float],
             step_seconds: float, peak_flops: Optional[float],
             peak_bw: Optional[float]) -> dict:
    """Measured-vs-model efficiency attribution for one timed program.

    Returns achieved FLOP/s and bytes/s, MFU, the roofline ceiling the
    program's arithmetic intensity allows (``min(peak_flops,
    intensity * peak_bw)``), which resource bounds it, and the
    fraction of that ceiling actually achieved. Null-safe: any missing
    input nulls the dependent outputs (a CPU run or a backend without
    a cost model must never fake an efficiency number)."""
    out = {
        "achieved_flops_per_sec": None,
        "achieved_bytes_per_sec": None,
        "mfu": None,
        "arithmetic_intensity": None,
        "roofline_flops_per_sec": None,
        "roofline_bound": None,
        "roofline_frac": None,
    }
    if not step_seconds or step_seconds <= 0:
        return out
    if flops:
        out["achieved_flops_per_sec"] = flops / step_seconds
    if bytes_accessed:
        out["achieved_bytes_per_sec"] = bytes_accessed / step_seconds
    if flops and bytes_accessed:
        out["arithmetic_intensity"] = round(flops / bytes_accessed, 4)
    if flops and peak_flops:
        out["mfu"] = round(flops / step_seconds / peak_flops, 4)
    if (flops and bytes_accessed and peak_flops and peak_bw):
        ceiling = min(peak_flops, (flops / bytes_accessed) * peak_bw)
        out["roofline_flops_per_sec"] = ceiling
        out["roofline_bound"] = ("compute"
                                 if ceiling >= peak_flops else "memory")
        out["roofline_frac"] = round(flops / step_seconds / ceiling, 4)
    return out


# ------------------------------------------------------------- CLI

def run_meter(names: Optional[Sequence[str]] = None, *,
              update: bool = False,
              costs: Optional[str] = None
              ) -> Tuple[List, Dict[str, dict], List[str]]:
    """Measure the registry (full graftcheck audit pass — builds and
    compiles are shared with the budget audits) and compare/refresh
    ``analysis/costs.json`` ONLY. The ``make check`` gate runs both
    comparisons in one pass through ``check.run_check``; this entry is
    the meter-scoped view."""
    from .programs import run_audits

    path = costs or default_costs_path()
    records, audit_findings, skipped = run_audits(names)
    cost_records = {name: rec["costs"] for name, rec in records.items()
                    if "costs" in rec}
    findings = [f for f in audit_findings
                if f.rule.startswith("GM")]
    failed = frozenset(f.program for f in audit_findings
                       if f.rule in ("GC100", "GM100"))
    committed = load_costs(path)
    if update:
        full = not names and not skipped and not failed
        keep = {} if full else {k: v for k, v in committed.items()
                                if k not in cost_records}
        write_costs(cost_records, path, keep=keep)
        return findings, cost_records, skipped
    findings = findings + compare_costs(
        cost_records, committed,
        full_scope=not names and not skipped, failed=failed)
    return findings, cost_records, skipped


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="graftmeter",
        description="static cost/memory model per compiled program + "
                    "HBM capacity planner")
    parser.add_argument("--programs", nargs="*", default=None,
                        metavar="NAME",
                        help="measure only these registry programs")
    parser.add_argument("--update", action="store_true",
                        help="refresh analysis/costs.json from the "
                             "current compile and exit")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--costs", default=None, metavar="FILE",
                        help="budget file (default: analysis/costs.json)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--plan", default=None, metavar="MODEL",
                        help="capacity-plan this models.registry name "
                             "instead of auditing (with --s_max/"
                             "--hbm_gb)")
    parser.add_argument("--s_max", default=2048, type=int)
    parser.add_argument("--hbm_gb", default=16.0, type=float,
                        help="per-chip HBM budget in GiB for --plan")
    parser.add_argument("--page_size", default=None, type=int,
                        help="--plan in PAGED mode: pages-per-chip at "
                             "this page size (graftpage)")
    parser.add_argument("--optimizer_moments", default=0, type=int,
                        help="--plan: resident moment buffers per "
                             "parameter (SGD+momentum 1, LAMB 2)")
    parser.add_argument("--zero_shards", default=1, type=int,
                        help="--plan: graftzero DP degree — moments "
                             "sharded over N ranks cost shard_bytes "
                             "per chip instead of params_bytes")
    parser.add_argument("--kv_dtype", default="model",
                        choices=("model", "int8"),
                        help="--plan: KV-pool element layout — int8 "
                             "(graftquant) charges 1 byte/element + "
                             "the f32 per-token-per-head scale")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES_GM):
            print(f"{rid}  {RULES_GM[rid]}")
        return 0

    if args.plan:
        from ..models import get_model

        model = get_model(args.plan)
        plan = plan_capacity(model, min(args.s_max, model.max_seq_len),
                             int(args.hbm_gb * (1 << 30)),
                             optimizer_moments=args.optimizer_moments,
                             zero_shards=args.zero_shards,
                             page_size=args.page_size,
                             kv_dtype=args.kv_dtype)
        if args.as_json:
            print(json.dumps(plan, indent=2, sort_keys=True))
        else:
            print(f"model={args.plan} s_max={plan['s_max']} "
                  f"budget={plan['hbm_budget'] / (1 << 30):.1f} GiB")
            print(f"  params            "
                  f"{plan['params_bytes'] / (1 << 20):10.1f} MiB")
            if args.optimizer_moments:
                print(f"  optimizer state   "
                      f"{plan['opt_state_bytes'] / (1 << 20):10.1f} MiB"
                      + (f" (zero_shards={plan['zero_shards']})"
                         if args.zero_shards > 1 else ""))
            print(f"  per KV slot       "
                  f"{plan['per_slot_bytes'] / (1 << 20):10.1f} MiB"
                  + (" (int8 + f32 scales)"
                     if args.kv_dtype == "int8" else ""))
            print(f"  max resident slots {plan['max_slots']:9d}")
            print(f"  max generate batch {plan['max_generate_batch']:9d}")
            print(f"  headroom          "
                  f"{plan['headroom_bytes'] / (1 << 20):10.1f} MiB")
            if args.page_size:
                print(f"  per KV page       "
                      f"{plan['page_bytes'] / (1 << 20):10.3f} MiB "
                      f"(page_size={plan['page_size']})")
                print(f"  pages per chip     {plan['max_pages']:9d}")
        return 0

    try:
        findings, records, skipped = run_meter(
            args.programs, update=args.update, costs=args.costs)
    except KeyError as e:
        print(f"graftmeter: {e.args[0]}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "findings": [{"program": f.program, "rule": f.rule,
                          "message": f.message} for f in findings],
            "programs": {k: records[k] for k in sorted(records)},
            "skipped": skipped,
            "updated": bool(args.update),
            "ok": not findings,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for s in skipped:
            print(f"graftmeter: skipped {s}", file=sys.stderr)
        verb = "updated" if args.update else "checked"
        if findings:
            print(f"graftmeter: {len(findings)} finding(s) across "
                  f"{len(records)} program(s)")
        else:
            print(f"graftmeter: {verb} {len(records)} program(s), "
                  "clean")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    # same platform pinning as analysis.check: the meter compiles on
    # the 8-device CPU mesh, never on a live accelerator
    if "jax" not in sys.modules:  # pragma: no branch
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    sys.exit(main())
