"""Runtime jit-hygiene sentinels — the dynamic complement to graftlint.

The linter (:mod:`.rules`) proves properties the AST can show; these
sentinels pin the two properties it cannot:

- **no unexpected host transfers** on a hot path:
  :func:`guard_transfers` arms ``jax.transfer_guard`` so any *implicit*
  host<->device crossing inside the context raises. Deliberate,
  documented syncs (the serving engine's one per-step token readback)
  are marked in the code with :func:`expected_transfer` — greppable,
  and exempt under the guard.
- **bounded recompiles**: :func:`recompile_budget` wraps a code region
  and asserts a ``jax.jit``-wrapped function traced at most ``budget``
  new programs inside it, via ``utils.compile_cache.jit_cache_size``.
  Budget 0 is the steady-state claim ("this traffic pattern compiles
  nothing new"); the serving tests pin budgets equal to the decode
  bucket ladder.

Platform honesty: ``jax.transfer_guard`` reports what the backend sees.
On CPU (the tier-1 mesh) device->host reads are zero-copy and are NOT
reported, so the guard there catches implicit host->device transfers
(numpy/scalar args leaking into a jitted call per step — the expensive
class on TPU too). On real TPU the same tests additionally catch stray
device->host syncs. Compile once (warm up) BEFORE arming the guard:
trace-time constant materialization is legitimate one-off traffic.

Exposed as pytest fixtures (``transfer_sentinel``,
``recompile_sentinel``) through the root conftest; pinned on the three
hottest paths in ``tests/test_sentinels.py`` (train step,
``generate()`` decode, serving engine step).

jax is imported lazily — importing this module (e.g. during lint-gate
collection) costs nothing.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

from ..utils.compile_cache import jit_cache_keys, jit_cache_size

__all__ = [
    "RecompileBudgetExceeded", "expected_transfer", "guard_transfers",
    "recompile_budget",
]


class RecompileBudgetExceeded(AssertionError):
    """A jitted function traced more new programs than its budget."""


@contextlib.contextmanager
def guard_transfers(level: str = "disallow") -> Iterator[None]:
    """Raise on implicit host<->device transfers inside the context.

    ``level``: a ``jax.transfer_guard`` level — ``"disallow"``
    (default), ``"log"`` for a non-fatal audit, ``"disallow_explicit"``
    to also forbid explicit ``device_put``/``jnp.asarray`` staging.
    No-op (with the same interface) on a jax without transfer guards.
    """
    import jax

    guard = getattr(jax, "transfer_guard", None)
    if guard is None:  # pragma: no cover - jax too old
        yield
        return
    with guard(level):
        yield


@contextlib.contextmanager
def expected_transfer(reason: str = "") -> Iterator[None]:
    """Mark a deliberate host<->device sync so it survives an enclosing
    :func:`guard_transfers`. The ``reason`` argument is documentation
    at the call site (and greppable): every hot-path sync must say why
    it exists."""
    del reason  # call-site documentation only
    import jax

    guard = getattr(jax, "transfer_guard", None)
    if guard is None:  # pragma: no cover - jax too old
        yield
        return
    with guard("allow"):
        yield


class _BudgetProbe:
    """Handle yielded by :func:`recompile_budget` — exposes how many
    new programs compiled so far inside the context."""

    def __init__(self, fn):
        self._fn = fn
        self.before = jit_cache_size(fn)
        self.keys_before = len(jit_cache_keys(fn))

    @property
    def compiles(self) -> int:
        after = jit_cache_size(self._fn)
        if after < 0 or self.before < 0:
            return -1  # counter unavailable on this jax build
        return after - self.before

    @property
    def new_keys(self) -> tuple:
        return jit_cache_keys(self._fn)[self.keys_before:]


@contextlib.contextmanager
def recompile_budget(fn, budget: int, *,
                     label: Optional[str] = None) -> Iterator[_BudgetProbe]:
    """Assert ``fn`` (a ``jax.jit``-wrapped callable) traces at most
    ``budget`` new programs inside the context.

    Budget 0 is the steady-state pin: re-running a shape mix that
    already compiled must trace nothing. When the jax build exposes no
    ``_cache_size`` counter the assertion is skipped (never a false
    alarm on version skew) — the probe's ``compiles`` reads -1 then.
    """
    probe = _BudgetProbe(fn)
    yield probe
    used = probe.compiles
    if used < 0:
        return
    if used > budget:
        name = label or getattr(fn, "__name__", repr(fn))
        raise RecompileBudgetExceeded(
            f"{name}: {used} new compiled program(s), budget {budget}"
            + (f"; new keys {probe.new_keys!r}" if probe.new_keys else "")
        )


# ---- pytest integration (loaded as a plugin by the root conftest) ----
try:  # pragma: no cover - import guard
    import pytest
except ImportError:  # pragma: no cover
    pytest = None

if pytest is not None:
    @pytest.fixture
    def transfer_sentinel():
        """The :func:`guard_transfers` context factory:
        ``with transfer_sentinel(): step(...)``."""
        return guard_transfers

    @pytest.fixture
    def recompile_sentinel():
        """The :func:`recompile_budget` context factory:
        ``with recompile_sentinel(step, 1): step(...)``."""
        return recompile_budget
