"""Static analysis for this codebase: graftlint (source level) +
graftcheck (program level).

The paper's core obligation is that every hot path stays inside XLA:
no stray host sync, Python side effect, or silent recompile in the
step and decode loops. PR 1/2 grew the *runtime* enforcement hooks
(``utils.compile_cache`` compile counters, the one-compile serving
decode); this package makes the discipline *machine-checked on every
PR*:

- :mod:`.rules` — the AST rule engine (pure ``ast``, NO jax import:
  the tier-1 lint gate must cost milliseconds, not a backend bring-up);
- :mod:`.concurrency` — **graftrace**, the concurrency pass riding the
  same engine: a package-wide lock model (declarations keyed by
  construction site, held-sets through ``with``/acquire-release
  scopes, thread entries, the shared call-graph closure) behind GL119
  lock-order cycles, GL120 blocking-under-lock, GL121 unguarded
  thread-shared state; ``static_lock_model()`` feeds
  :mod:`..runtime.sched`'s realized-graph subgraph audit;
- :mod:`.lint` — CLI / JSON output / per-line suppressions /
  committed-baseline workflow (``python -m
  pytorch_multiprocessing_distributed_tpu.analysis.lint``);
- :mod:`.sentinels` — the runtime complement: ``jax.transfer_guard``
  context managers and recompile-budget assertions built on
  ``utils.compile_cache``, pinned in tests on the three hottest paths
  (train step, ``generate()`` decode, serving engine step);
- :mod:`.ir` / :mod:`.programs` / :mod:`.check` — **graftcheck**, the
  jaxpr-level auditor (``make check``): traces the registered
  canonical programs abstractly (DP/TP/FSDP train steps, ``generate``
  prefill+decode, the serving decode ladder, the MoE layer) and
  enforces collective budgets per mesh axis, donation aliasing,
  resharding/replication caps, dtype-promotion counts, and golden
  program fingerprints committed in ``analysis/fingerprints.json``.
  These modules DO import jax (they interrogate the tracer) — the
  lint CLI stays jax-free; import them directly, never from here;
- :mod:`.meter` — **graftmeter**, the static cost/memory model
  (``analysis/costs.json`` budgets enforced by the same ``make
  check`` pass: FLOPs, bytes accessed, arithmetic intensity,
  argument/output/temp HBM per program), the HBM capacity planner
  (``plan_capacity``), and the roofline helpers both benches stamp
  records with. Jax-importing like graftcheck.

Rule IDs are stable (graftlint ``GL1xx``, graftcheck ``GC1xx``,
graftmeter ``GM1xx``) — suppression comments, the baseline file and
the budget snapshots refer to them.
"""

from .rules import RULES, Finding, analyze_files  # noqa: F401
