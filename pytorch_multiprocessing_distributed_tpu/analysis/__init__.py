"""graftlint — JAX/TPU jit-hygiene static analysis for this codebase.

The paper's core obligation is that every hot path stays inside XLA:
no stray host sync, Python side effect, or silent recompile in the
step and decode loops. PR 1/2 grew the *runtime* enforcement hooks
(``utils.compile_cache`` compile counters, the one-compile serving
decode); this package makes the discipline *machine-checked on every
PR*:

- :mod:`.rules` — the AST rule engine (pure ``ast``, NO jax import:
  the tier-1 lint gate must cost milliseconds, not a backend bring-up);
- :mod:`.lint` — CLI / JSON output / per-line suppressions /
  committed-baseline workflow (``python -m
  pytorch_multiprocessing_distributed_tpu.analysis.lint``);
- :mod:`.sentinels` — the runtime complement: ``jax.transfer_guard``
  context managers and recompile-budget assertions built on
  ``utils.compile_cache``, pinned in tests on the three hottest paths
  (train step, ``generate()`` decode, serving engine step).

Rule IDs are stable (``GL1xx``) — suppression comments and the
baseline file refer to them.
"""

from .rules import RULES, Finding, analyze_files  # noqa: F401
