"""graftcheck IR utilities: jaxpr-level program auditing.

graftlint (:mod:`.rules`) stops at the AST: it can prove a ``print``
sits inside a traced scope, but not what the compiler actually emits.
The properties that define a distributed trainer — how many collective
bytes a step moves, whether the donated state really aliases, whether a
bf16 hot path silently upcasts — live in the traced program. This
module reads them there, three levels down:

1. **jaxpr** (``jax.make_jaxpr`` on abstract inputs — CPU-safe, no
   FLOPs, no compile): recursive equation walk through ``pjit`` /
   ``scan`` / ``cond`` / ``while`` / ``shard_map`` / ``remat`` /
   custom-derivative sub-jaxprs, with scan trip counts multiplying the
   dynamic cost of their bodies. Collectives (``psum`` & co) appear
   here EXPLICITLY for shard_map-style programs — count + byte volume
   per mesh axis is exact.
2. **lowering** (``fn.lower(...)`` — still no execution): donated
   arguments that the lowered module actually aliases carry
   ``tf.aliasing_output`` attributes in the StableHLO text; a declared
   ``donate_argnums`` the lowering dropped (shape/dtype mismatch, or
   someone deleted the declaration) is visible as a missing alias.
3. **compiled HLO** (``.compile()`` on the CPU mesh — compile only,
   never run): GSPMD-inserted collectives (the TP/FSDP programs, where
   the jaxpr shows only sharding constraints) appear as
   ``all-reduce``/``all-gather``/``reduce-scatter``/``all-to-all`` ops
   in the optimized module; counts and byte volumes are parsed from
   the text.

Fingerprints: a structural digest over the recursive equation outline
(primitive, selected static params, operand/result avals) — committed
per canonical program in ``analysis/fingerprints.json`` so semantic
drift in a hot program fails tier-1 with a readable per-primitive
histogram diff instead of a silent behavior change.

jax is imported at module top: unlike the lint gate this tool exists
to interrogate the tracer. It must still never require an accelerator
— everything here runs on the host platform.
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
from jax import core as jax_core

try:  # the ClosedJaxpr/Jaxpr types moved around across 0.4.x
    _JAXPR_TYPES = (jax_core.Jaxpr, jax_core.ClosedJaxpr)
except AttributeError:  # pragma: no cover - much older jax
    from jax._src import core as jax_core  # type: ignore

    _JAXPR_TYPES = (jax_core.Jaxpr, jax_core.ClosedJaxpr)


# collective primitives whose presence/size IS the communication budget
COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "all_gather", "psum_scatter",
    "reduce_scatter", "ppermute", "pshuffle", "all_to_all",
}

# eqn params worth fingerprinting: static semantics, stable reprs (a
# NamedSharding or jaxpr repr would drag device ids / var names in)
_FP_PARAMS = (
    "axes", "axis_name", "axis_index_groups", "length", "num_carry",
    "num_consts", "reverse", "new_dtype", "dimension_numbers",
    "dimensions", "shape", "window_strides", "feature_group_count",
    "direction", "index_dtype", "exact",
)

_F32_UP_SOURCES = ("bfloat16", "float16")


def aval_bytes(aval) -> int:
    """Byte size of a shaped abstract value (0 for non-arrays)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def abstract(tree):
    """ShapeDtypeStruct twin of an array pytree — audit inputs never
    hold real buffers."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)


def trace(fn, *args, **kwargs):
    """ClosedJaxpr of ``fn(*args, **kwargs)`` on abstract inputs.

    ``args`` may be arrays or ``ShapeDtypeStruct`` trees; keyword
    arguments are closed over (so jit-static kwargs like the serving
    decode's ``window``/``horizon`` pin one program each)."""
    if kwargs:
        return jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    return jax.make_jaxpr(fn)(*args)


def _as_jaxpr(obj):
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _sub_jaxprs(eqn) -> List[Tuple[object, int]]:
    """(sub_jaxpr, trip_multiplier) pairs under one equation. A scan
    body's dynamic cost is ``length`` executions; every other nesting
    (pjit, cond branches, while bodies, shard_map, remat, custom_*)
    multiplies by 1 — for while loops that is the STATIC count (trip
    counts are data-dependent; the budget audits what one iteration
    moves)."""
    out: List[Tuple[object, int]] = []
    name = eqn.primitive.name
    for key, val in eqn.params.items():
        if key == "branches":
            out.extend((_as_jaxpr(b), 1) for b in val)
        elif isinstance(val, _JAXPR_TYPES):
            mult = 1
            if name == "scan" and key == "jaxpr":
                mult = int(eqn.params.get("length", 1))
            out.append((_as_jaxpr(val), mult))
        elif isinstance(val, (tuple, list)) and val and all(
                isinstance(v, _JAXPR_TYPES) for v in val):
            out.extend((_as_jaxpr(v), 1) for v in val)
    return out


def iter_eqns(closed, mult: int = 1) -> Iterator[Tuple[object, int]]:
    """Depth-first ``(eqn, trip_multiplier)`` walk of a (Closed)Jaxpr,
    recursing through every sub-jaxpr-carrying equation."""
    jaxpr = _as_jaxpr(closed)
    for eqn in jaxpr.eqns:
        yield eqn, mult
        for sub, m in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, mult * m)


def _axes_of(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def collective_records(closed) -> List[Tuple[str, Tuple[str, ...], int, int]]:
    """Every collective equation in the program (recursively):
    ``(primitive, axes, bytes_per_call, trip_count)``. Bytes are the
    summed operand avals of ONE call — per-shard sizes as the body
    sees them."""
    out = []
    for eqn, mult in iter_eqns(closed):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            nbytes = sum(aval_bytes(getattr(v, "aval", None))
                         for v in eqn.invars)
            out.append((eqn.primitive.name, _axes_of(eqn), nbytes, mult))
    return out


def collective_budget(closed) -> Dict[str, Dict[str, int]]:
    """The program's jaxpr-level communication budget:
    ``{"psum@data": {"count": N, "bytes": B}, ...}`` with scan trip
    counts multiplied in (count = dynamic calls per program execution,
    bytes = total per-execution volume)."""
    budget: Dict[str, Dict[str, int]] = {}
    for prim, axes, nbytes, mult in collective_records(closed):
        key = f"{prim}@{','.join(axes) or '?'}"
        slot = budget.setdefault(key, {"count": 0, "bytes": 0})
        slot["count"] += mult
        slot["bytes"] += nbytes * mult
    return budget


def psum_sizes(closed) -> List[int]:
    """Per-call byte size of every ``psum`` equation (static list, no
    trip multiplication) — the needle for "exactly one grad-sized
    psum": callers count entries equal to the parameter-tree bytes."""
    return [nbytes for prim, _axes, nbytes, _m in collective_records(closed)
            if prim == "psum"]


def dtype_promotions(closed, min_bytes: int = 0) -> Dict[str, int]:
    """bf16/f16 -> f32 ``convert_element_type`` equations whose result
    DIRECTLY feeds a matmul-class op (``dot_general`` /
    ``conv_general_dilated``) and whose operand is at least
    ``min_bytes`` — the silent-upcast audit. Deliberate f32 islands
    (LayerNorm, softmax) don't feed matmuls and stay out; the programs
    that DO matmul in f32 on purpose (logit paths) pin their count in
    the committed budget, so an unintended new upcast moves the number
    and trips the gate. Returns ``{"count": N, "bytes": B}`` with scan
    trips multiplied in."""
    total = {"count": 0, "bytes": 0}

    def scan_level(jaxpr, mult):
        jaxpr = _as_jaxpr(jaxpr)
        matmul_operands = set()
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in ("dot_general",
                                      "conv_general_dilated"):
                for v in eqn.invars:
                    matmul_operands.add(id(v))
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "convert_element_type":
                src = getattr(eqn.invars[0], "aval", None)
                dst = eqn.params.get("new_dtype")
                nbytes = aval_bytes(src)
                if (src is not None and dst is not None
                        and str(getattr(src, "dtype", "")) in
                        _F32_UP_SOURCES
                        and str(dst) == "float32"
                        and nbytes >= min_bytes
                        and any(id(o) in matmul_operands
                                for o in eqn.outvars)):
                    total["count"] += mult
                    total["bytes"] += nbytes * mult
            for sub, m in _sub_jaxprs(eqn):
                scan_level(sub, mult * m)

    scan_level(closed, 1)
    return total


# ------------------------------------------------------------ fingerprints

def _aval_str(v) -> str:
    aval = getattr(v, "aval", None)
    if aval is None:
        return repr(getattr(v, "val", v))
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None:
        return str(aval)
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


def outline(closed) -> str:
    """Canonical human-readable structure of the program: one line per
    equation (recursive, indented), primitive + whitelisted static
    params + operand/result avals. Stable across runs (no var names,
    no device ids) — the digest input AND the thing a human diffs when
    a fingerprint moves."""
    lines: List[str] = []

    def emit(jaxpr, depth):
        jaxpr = _as_jaxpr(jaxpr)
        pad = "  " * depth
        for eqn in jaxpr.eqns:
            params = ";".join(
                f"{k}={eqn.params[k]!r}" for k in _FP_PARAMS
                if k in eqn.params)
            ins = ",".join(_aval_str(v) for v in eqn.invars)
            outs = ",".join(_aval_str(v) for v in eqn.outvars)
            lines.append(
                f"{pad}{eqn.primitive.name}[{params}] {ins} -> {outs}")
            for sub, _m in _sub_jaxprs(eqn):
                emit(sub, depth + 1)

    emit(closed, 0)
    return "\n".join(lines)


def op_histogram(closed) -> Dict[str, int]:
    """Static per-primitive equation counts (recursive, NOT trip-
    multiplied — structural, so a scan-length change shows up in the
    digest/params, not as a phantom op-count delta)."""
    hist: Dict[str, int] = {}
    for eqn, _mult in iter_eqns(closed):
        hist[eqn.primitive.name] = hist.get(eqn.primitive.name, 0) + 1
    return hist


def fingerprint(closed) -> Dict[str, object]:
    """``{"digest", "eqns", "ops"}`` for one traced program."""
    text = outline(closed)
    hist = op_histogram(closed)
    return {
        "digest": hashlib.sha256(text.encode()).hexdigest()[:16],
        "eqns": sum(hist.values()),
        "ops": hist,
    }


def diff_histograms(old: Dict[str, int], new: Dict[str, int]) -> str:
    """Readable op-count delta: ``+2 convert_element_type, -1 psum``;
    empty when the histograms agree (a pure reorder/param change)."""
    parts = []
    for prim in sorted(set(old) | set(new)):
        d = new.get(prim, 0) - old.get(prim, 0)
        if d:
            parts.append(f"{'+' if d > 0 else ''}{d} {prim}")
    return ", ".join(parts)


# ------------------------------------------------- lowering / compiled HLO

_ALIAS_ATTRS = ("tf.aliasing_output", "jax.buffer_donor")


def alias_count(lowered_text: str) -> int:
    """Input buffers a lowered module aliases to outputs
    (``tf.aliasing_output`` attrs in the StableHLO text; the
    ``jax.buffer_donor`` spelling counts too on jaxes that emit it).
    Zero with a declared ``donate_argnums`` means the donation was
    dropped — the doubled-HBM bug the donation audit exists for."""
    return sum(lowered_text.count(attr) for attr in _ALIAS_ATTRS)


def donation_aliases(jit_fn, *args, **kwargs) -> int:
    """:func:`alias_count` of ``jit_fn`` lowered on ``args`` —
    lowering only, nothing compiles or runs. (The audit runner lowers
    once and reuses the ``Lowered`` for the HLO compile; this
    convenience wrapper is for tests/one-off probes.)"""
    return alias_count(jit_fn.lower(*args, **kwargs).as_text())


_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*([^=\n]*?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_HLO_SHAPE_RE = re.compile(
    r"(pred|bf16|f16|f32|f64|s8|s16|s32|s64|u8|u16|u32|u64|c64|c128)"
    r"\[([0-9,]*)\]")
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
    "u16": 2, "f32": 4, "s32": 4, "u32": 4, "c64": 8, "f64": 8,
    "s64": 8, "u64": 8, "c128": 16,
}


def _hlo_shape_bytes(type_text: str) -> int:
    total = 0
    for dtype, dims in _HLO_SHAPE_RE.findall(type_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES[dtype]
    return total


def hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Collective ops in a compiled (post-SPMD-partitioner) HLO module:
    ``{"all-reduce": {"count": N, "bytes": B}, ...}``, bytes from each
    op's result shape. This is where GSPMD-inserted communication —
    invisible at the jaxpr level — becomes countable. Text occurrences
    = static program sites (an op inside an HLO while body counts
    once)."""
    out: Dict[str, Dict[str, int]] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        op = m.group(2)
        slot = out.setdefault(op, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += _hlo_shape_bytes(m.group(1))
    return out


def hlo_max_allgather_bytes(hlo_text: str) -> int:
    """Largest single all-gather result in the module — the
    replication audit's needle: a 'small' program whose HLO suddenly
    all-gathers a weight-sized array got its sharding dropped."""
    best = 0
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        if m.group(2) == "all-gather":
            best = max(best, _hlo_shape_bytes(m.group(1)))
    return best
