"""graftlint CLI: ``python -m pytorch_multiprocessing_distributed_tpu.analysis.lint``.

Runs the AST rule engine (:mod:`.rules`) over the package (or explicit
paths), applies per-line suppressions and the committed baseline, and
exits non-zero on any live finding — the tier-1 gate and
``benchmarks/on_grant.sh`` both call this.

Deliberately jax-free: the gate costs milliseconds of ``ast.parse``,
never a backend bring-up, so it runs first in every pipeline.

Suppression (line-scoped, rule-cited — greppable justification):

    x = float(y)  # graftlint: disable=GL101  <reason>
    x = float(y)  # graftlint: disable        (all rules on this line)

Baseline workflow (grandfathering pre-existing findings so the gate can
land red-free and ratchet):

    python -m ...analysis.lint --write-baseline   # snapshot findings
    python -m ...analysis.lint                    # exits 0; NEW findings fail

Baseline entries match on (path, rule, source-line text) — line drift
from unrelated edits doesn't churn the file; editing the offending line
surfaces the finding again (by design: touched code must be clean).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .rules import RULES, Finding, analyze_files

# rule list = comma-separated GL codes ONLY — anything after is the
# human reason and must not leak into the parsed set ("disable=GL101
# TTFT boundary" suppresses GL101, not the nonexistent rule "GL101 TTFT")
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(GL\d{3}(?:\s*,\s*GL\d{3})*))?")

_EXCLUDE_DIRS = {"__pycache__", ".git", "build"}


def package_root() -> str:
    """The pytorch_multiprocessing_distributed_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline_path() -> str:
    return os.path.join(package_root(), "analysis", "baseline.json")


def discover(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into a sorted .py file list. A path that is
    neither a directory nor an existing .py file raises — a typo'd CI
    invocation must fail loudly, never report 'clean' on nothing."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in _EXCLUDE_DIRS]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
        elif p.endswith(".py") and os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"graftlint: {p!r} is neither a directory nor an "
                "existing .py file")
    return sorted(set(out))


def _lines(path: str, line_cache: Dict[str, List[str]]) -> List[str]:
    lines = line_cache.get(path)
    if lines is None:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            lines = []
        line_cache[path] = lines
    return lines


def _suppressed(finding: Finding, line_cache: Dict[str, List[str]]) -> bool:
    lines = _lines(finding.path, line_cache)
    if not (0 < finding.line <= len(lines)):
        return False
    m = _SUPPRESS_RE.search(lines[finding.line - 1])
    if not m:
        return False
    if m.group(1) is None:
        return True
    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return finding.rule in rules


def _line_text(finding: Finding, line_cache: Dict[str, List[str]]) -> str:
    lines = _lines(finding.path, line_cache)
    if 0 < finding.line <= len(lines):
        return lines[finding.line - 1].strip()
    return ""


def _rel(path: str, base: str) -> str:
    try:
        return os.path.relpath(os.path.abspath(path), base)
    except ValueError:
        return path


def load_baseline(path: Optional[str]) -> List[dict]:
    if not path or not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("findings", []))


def run_lint(paths: Sequence[str], *, baseline: Optional[str] = None,
             base_dir: Optional[str] = None,
             ) -> Tuple[List[Finding], List[Finding]]:
    """Lint ``paths``; returns ``(live, baselined)`` findings, with
    per-line suppressions already removed from both."""
    base_dir = base_dir or os.path.dirname(package_root())
    files = discover(paths)
    findings = analyze_files(files, package_parent=base_dir)
    line_cache: Dict[str, List[str]] = {}
    findings = [f for f in findings if not _suppressed(f, line_cache)]

    allowance: Dict[Tuple[str, str, str], int] = {}
    for entry in load_baseline(baseline):
        key = (entry.get("path", ""), entry.get("rule", ""),
               entry.get("text", ""))
        allowance[key] = allowance.get(key, 0) + 1
    live: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in findings:
        key = (_rel(f.path, base_dir), f.rule, _line_text(f, line_cache))
        if allowance.get(key, 0) > 0:
            allowance[key] -= 1
            grandfathered.append(f)
        else:
            live.append(f)
    return live, grandfathered


def write_baseline(findings: Sequence[Finding], path: str,
                   base_dir: str, *,
                   keep: Optional[List[dict]] = None) -> None:
    """Snapshot ``findings`` into the baseline file. ``keep`` carries
    pre-existing entries to preserve verbatim (files outside a
    partial-scope run)."""
    line_cache: Dict[str, List[str]] = {}
    payload = {
        "comment": "graftlint grandfathered findings — shrink, never "
                   "grow. Matched on (path, rule, line text): editing a "
                   "baselined line resurfaces its finding.",
        "findings": list(keep or []) + [
            {"path": _rel(f.path, base_dir), "rule": f.rule,
             "line": f.line, "text": _line_text(f, line_cache)}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX/TPU jit-hygiene static analysis (AST-only, no "
                    "jax import)")
    parser.add_argument(
        "paths", nargs="*",
        help="files/dirs to lint (default: the package)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline file (default: analysis/baseline.json when "
             "linting the package; 'none' disables)")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="snapshot current findings into the baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid]}")
        return 0

    paths = args.paths or [package_root()]
    base_dir = os.path.dirname(package_root())
    baseline = args.baseline
    if baseline is None:
        baseline = default_baseline_path()
    elif baseline.lower() == "none":
        baseline = None

    try:
        if args.write_baseline:
            target = baseline or default_baseline_path()
            live, grandfathered = run_lint(paths, baseline=None,
                                           base_dir=base_dir)
            # partial-scope runs must not discard grandfathered entries
            # for files OUTSIDE the linted set: merge, don't overwrite
            linted = {_rel(f, base_dir) for f in discover(paths)}
            kept = [e for e in load_baseline(target)
                    if e.get("path", "") not in linted]
            write_baseline(live, target, base_dir, keep=kept)
            print(f"graftlint: baselined {len(live)} finding(s)"
                  + (f" (+{len(kept)} kept outside scope)" if kept
                     else "") + f" -> {target}")
            return 0

        live, grandfathered = run_lint(paths, baseline=baseline,
                                       base_dir=base_dir)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "findings": [
                {"path": _rel(f.path, base_dir), "line": f.line,
                 "col": f.col, "rule": f.rule, "message": f.message}
                for f in live
            ],
            "baselined": len(grandfathered),
            "ok": not live,
        }, indent=2))
    else:
        for f in live:
            print(Finding(_rel(f.path, base_dir), f.line, f.col, f.rule,
                          f.message).render())
        note = (f" ({len(grandfathered)} baselined)"
                if grandfathered else "")
        if live:
            print(f"graftlint: {len(live)} finding(s){note}")
        else:
            print(f"graftlint: clean{note}")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
