"""graftcheck CLI: ``python -m pytorch_multiprocessing_distributed_tpu.analysis.check``.

The IR-level complement to graftlint: traces the registered canonical
programs (``analysis/programs.py``) abstractly — CPU-safe, no FLOPs —
and enforces two layers of contract:

1. **inline invariants**, declared in code by each registration hook
   (exactly one grad-sized psum in the DP train step, donation reaches
   the lowered module, FSDP emits all-gather + reduce-scatter, ...) —
   live checks that no snapshot refresh can launder;
2. **committed budgets/fingerprints** (``analysis/fingerprints.json``):
   per-program collective budgets (count + bytes per mesh axis),
   dtype-promotion counts, donation alias counts, compiled-HLO
   collective sets, and a structural digest. Any drift fails with a
   readable diff naming the program and rule; deliberate changes are
   re-baselined with ``make check-update`` (and reviewed as a JSON
   diff in the PR);
3. **committed cost/memory budgets** (``analysis/costs.json``,
   graftmeter — GM rules from ``analysis/meter.py``): per-program
   FLOPs, bytes accessed, arithmetic intensity and the compiled
   argument/output/temp/generated-code HBM breakdown, measured off
   the SAME compile as the HLO audit. Temp-HBM growth fails with a
   "+N MiB temp" diff naming program + field.

Workflow::

    make check            # the tier-1 / on_grant gate
    make check-update     # refresh fingerprints after a reviewed change
    python -m ...analysis.check --programs lm_step_tp --json

Unlike the lint gate this tool imports jax (it exists to interrogate
the tracer) — it pins itself to the host platform before the backend
comes up, and the callers that must never pay a backend bring-up
(tier-1 collection, on_grant step 0) already run it under
``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

# pin the host platform BEFORE jax initializes (harmless if something
# — the axon sitecustomize, pytest — already imported jax: the config
# update below still applies when no backend is live yet)
if "jax" not in sys.modules:  # pragma: no branch
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

try:  # best-effort when jax was pre-imported with another platform
    jax.config.update("jax_platforms", "cpu")
except Exception:  # noqa: BLE001  # graftlint: disable=GL111 backend may already be live; config stays as-is
    pass

from . import ir  # noqa: E402
from .programs import Finding, RULES_GC, run_audits  # noqa: E402


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_fingerprints_path() -> str:
    return os.path.join(package_root(), "analysis", "fingerprints.json")


def load_fingerprints(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        return dict(json.load(fh).get("programs", {}))


def write_fingerprints(records: Dict[str, dict], path: str, *,
                       keep: Optional[Dict[str, dict]] = None) -> None:
    """Snapshot ``records`` (merging ``keep`` for programs outside a
    partial-scope or device-limited run — a laptop refresh must not
    drop the TP entries it could not trace)."""
    programs = dict(keep or {})
    programs.update(records)
    payload = {
        "comment": "graftcheck committed budgets/fingerprints — refresh "
                   "deliberately via `make check-update` and review the "
                   "diff; drift here is a semantic change to a hot "
                   "program.",
        "jax": jax.__version__,
        "programs": {k: programs[k] for k in sorted(programs)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")


def _diff_dict(name: str, rule: str, field: str, want, got,
               out: List[Finding]) -> None:
    if want == got:
        return
    keys = sorted(set(want or {}) | set(got or {}))
    parts = []
    for k in keys:
        w, g = (want or {}).get(k), (got or {}).get(k)
        if w != g:
            parts.append(f"{k}: committed {w} -> traced {g}")
    out.append(Finding(name, rule,
                       f"{field} drift — " + "; ".join(parts)))


def compare(records: Dict[str, dict], committed: Dict[str, dict],
            *, full_scope: bool,
            failed: frozenset = frozenset()) -> List[Finding]:
    """Snapshot comparison: every traced program against its committed
    entry, field by field, each mismatch a rule-tagged finding with
    the delta spelled out."""
    findings: List[Finding] = []
    for name, rec in records.items():
        want = committed.get(name)
        if want is None:
            findings.append(Finding(
                name, "GC106",
                "no committed fingerprint — run `make check-update` "
                "and review the new entry"))
            continue
        got_fp, want_fp = rec["fingerprint"], want.get("fingerprint", {})
        if got_fp["digest"] != want_fp.get("digest"):
            hist_diff = ir.diff_histograms(
                want_fp.get("ops", {}), got_fp["ops"])
            findings.append(Finding(
                name, "GC105",
                "program structure changed: digest "
                f"{want_fp.get('digest')} -> {got_fp['digest']}"
                + (f" (op delta: {hist_diff})" if hist_diff else
                   " (same op mix — shapes/params/order moved)")))
        _diff_dict(name, "GC101", "collective budget",
                   want.get("collectives"), rec.get("collectives"),
                   findings)
        _diff_dict(name, "GC104", "dtype-promotion budget",
                   want.get("dtype_promotions"),
                   rec.get("dtype_promotions"), findings)
        if "donation" in rec or "donation" in want:
            _diff_dict(name, "GC102", "donation aliases",
                       want.get("donation"), rec.get("donation"),
                       findings)
        if "hlo_collectives" in rec or "hlo_collectives" in want:
            _diff_dict(name, "GC103", "compiled (HLO) collectives",
                       want.get("hlo_collectives"),
                       rec.get("hlo_collectives"), findings)
        if "grad_sized_psums" in rec or "grad_sized_psums" in want:
            # presence-or, like the dict fields: the field VANISHING
            # from either side (inline declaration deleted, or the
            # committed entry tampered) must flag, not skip — the
            # invariant is only refresh-proof if its absence is loud
            got_n = rec.get("grad_sized_psums")
            want_n = want.get("grad_sized_psums")
            if got_n != want_n:
                findings.append(Finding(
                    name, "GC101",
                    f"grad-sized psum count: committed {want_n} -> "
                    f"traced {got_n} (None = the declaration/entry is "
                    "gone, which is itself a drift)"))
    if full_scope:
        # programs that FAILED to build (GC100) are registered, not
        # stale — their committed entries are deliberately kept, and a
        # second "stale entry" finding here would send the operator
        # chasing a lost hook that exists
        for name in sorted(set(committed) - set(records) - set(failed)):
            findings.append(Finding(
                name, "GC106",
                "committed fingerprint names no registered program — "
                "stale entry (or a lost registration hook); "
                "`make check-update` prunes it"))
    return findings


def run_check(names: Optional[Sequence[str]] = None, *,
              update: bool = False,
              fingerprints: Optional[str] = None,
              costs: Optional[str] = None
              ) -> Tuple[List[Finding], Dict[str, dict], List[str]]:
    """Library entry (the tier-1 gate calls this in-process): audit,
    compare (or snapshot with ``update``), return
    ``(findings, records, skipped)``. One pass enforces BOTH committed
    files: ``analysis/fingerprints.json`` (structure/collective
    budgets, GC rules) and ``analysis/costs.json`` (graftmeter
    FLOPs/bytes/memory budgets, GM rules) — the audit's one compile
    feeds both, so they can never disagree about which program ran."""
    from . import meter

    path = fingerprints or default_fingerprints_path()
    costs_path = costs or meter.default_costs_path()
    records, findings, skipped = run_audits(names)
    # split each record: "costs" is graftmeter's half, committed and
    # compared separately in costs.json
    fp_records: Dict[str, dict] = {}
    cost_records: Dict[str, dict] = {}
    for name, rec in records.items():
        rec = dict(rec)
        cost_rec = rec.pop("costs", None)
        fp_records[name] = rec
        if cost_rec is not None:
            cost_records[name] = cost_rec
    committed = load_fingerprints(path)
    committed_costs = meter.load_costs(costs_path)
    failed_fp = frozenset(f.program for f in findings
                          if f.rule == "GC100")
    # a GM100 (compile-for-metering failure) program produced no cost
    # record but its committed budget is NOT stale — keep it, like a
    # GC100's fingerprint entry
    failed_costs = frozenset(f.program for f in findings
                             if f.rule in ("GC100", "GM100"))
    if update:
        # prune stale names only on a COMPLETE clean enumeration: a
        # name-filtered, device-limited, or build-failed (GC100 — the
        # program produced no record) run must keep the entries it
        # could not re-trace, or one transient breakage would silently
        # delete a program's committed budget history
        full = not names and not skipped and not failed_fp
        keep = {} if full else {k: v for k, v in committed.items()
                                if k not in fp_records}
        write_fingerprints(fp_records, path, keep=keep)
        full_costs = not names and not skipped and not failed_costs
        keep_costs = ({} if full_costs
                      else {k: v for k, v in committed_costs.items()
                            if k not in cost_records})
        if cost_records or keep_costs != committed_costs:
            # skip the no-op rewrite (nothing measured, nothing pruned)
            meter.write_costs(cost_records, costs_path, keep=keep_costs)
        return findings, records, skipped
    findings = findings + compare(
        fp_records, committed,
        full_scope=not names and not skipped,
        failed=failed_fp)
    findings = findings + meter.compare_costs(
        cost_records, committed_costs,
        full_scope=not names and not skipped,
        failed=failed_costs)
    return findings, records, skipped


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftcheck",
        description="jaxpr-level program auditor: collective budgets, "
                    "donation/resharding/dtype audits, golden program "
                    "fingerprints")
    parser.add_argument("--programs", nargs="*", default=None,
                        metavar="NAME",
                        help="audit only these programs")
    parser.add_argument("--update", action="store_true",
                        help="refresh analysis/fingerprints.json from "
                             "the current trace and exit (inline-"
                             "invariant violations still fail)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable results on stdout")
    parser.add_argument("--fingerprints", default=None, metavar="FILE",
                        help="fingerprint file (default: "
                             "analysis/fingerprints.json)")
    parser.add_argument("--costs", default=None, metavar="FILE",
                        help="graftmeter cost-budget file (default: "
                             "analysis/costs.json)")
    parser.add_argument("--list", action="store_true", dest="list_only",
                        help="list registered programs and exit")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the GC rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .meter import RULES_GM

        for rid in sorted(RULES_GC):
            print(f"{rid}  {RULES_GC[rid]}")
        for rid in sorted(RULES_GM):
            print(f"{rid}  {RULES_GM[rid]}")
        return 0
    if args.list_only:
        from .programs import collect

        for spec in collect():
            print(f"{spec.name}  ({spec.module}, >= "
                  f"{spec.min_devices} devices)")
        return 0

    try:
        findings, records, skipped = run_check(
            args.programs, update=args.update,
            fingerprints=args.fingerprints, costs=args.costs)
    except KeyError as e:
        print(f"graftcheck: {e.args[0]}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "findings": [{"program": f.program, "rule": f.rule,
                          "message": f.message} for f in findings],
            "programs": sorted(records),
            "skipped": skipped,
            "updated": bool(args.update),
            "ok": not findings,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for s in skipped:
            print(f"graftcheck: skipped {s}", file=sys.stderr)
        verb = "updated" if args.update else "checked"
        if findings:
            print(f"graftcheck: {len(findings)} finding(s) across "
                  f"{len(records)} program(s)")
        else:
            print(f"graftcheck: {verb} {len(records)} program(s), "
                  "clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
