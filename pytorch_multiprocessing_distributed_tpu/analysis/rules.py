"""graftlint rule engine: pure-AST jit-hygiene analysis.

The linter answers one question per rule: *could this line knock a hot
path out of XLA?* — a host sync mid-step, a Python side effect baked
into a trace, a silent recompile per iteration. The hard part is
scoping: ``print`` in the trainer's host loop is fine, ``print`` in the
jitted step body is a trace-time landmine. So the engine first infers
which functions are **jit-scoped** (traced by jax), then applies the
line rules only inside those.

Jit-scope inference (two passes over the whole linted file set):

1. per-file: parse, track import aliases, index every function (incl.
   nested and methods), and mark *roots* — functions decorated with or
   passed to ``jax.jit`` / ``shard_map`` / ``pmap`` / ``vmap`` /
   ``grad`` / ``checkpoint`` / ``lax.scan``-family wrappers (the
   control-flow primitives trace their bodies from ANY caller, jitted
   or not — a ``lax.scan`` body in a host function is still traced).
   A wrapper whose argument is a *call* of a local function (the
   factory idiom this codebase uses everywhere:
   ``jax.jit(self._make_decode_horizon())``,
   ``jax.shard_map(_train_body(...))``) marks the factory's *nested*
   functions as traced — the factory body itself runs at build time —
   and a body reaching the wrapper through a local variable
   (``body = make_body(...); lax.scan(body, ...)``) resolves through
   the assignment.
2. global: propagate scope through the call graph — a traced function's
   callees are traced too, resolved through module-level names and
   intra-package ``from``-imports (``serving.engine`` calling
   ``inference.generate._block_decode_slots`` is resolved across
   files).

This is deliberately static and approximate: no jax import, no
execution, milliseconds over the whole package. Known limits are
documented per rule; escape hatches are per-line suppressions and the
committed baseline (see :mod:`.lint`).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "GL000": "file does not parse (syntax error)",
    "GL101": "host sync inside jit-traced code (.item(), float()/int() on "
             "a traced value, np.asarray/np.array, jax.device_get, "
             "block_until_ready)",
    "GL102": "print/logging side effect inside jit-traced code (runs at "
             "trace time only, or crashes on tracers — use "
             "jax.debug.print)",
    "GL103": "wall clock or host RNG inside jit-traced code (time.*, "
             "stdlib random.*, np.random.* — baked in at trace time; use "
             "jax.random)",
    "GL104": "mutation of enclosing-scope state inside jit-traced code "
             "(global/nonlocal or captured-container mutation — silent "
             "under jit: runs once at trace time)",
    "GL105": "jax.jit constructed inside a loop body (a fresh jit wrapper "
             "per iteration retraces/recompiles every time — hoist it)",
    "GL106": "Python branch on a traced argument of a jitted function "
             "(TracerBoolConversionError, or a recompile per value if "
             "made static — use lax.cond/lax.select or static_argnames)",
    "GL107": "mutable (unhashable) default on a static jit argument "
             "(TypeError at call time, or identity-keyed retraces)",
    "GL108": "train-step-shaped jit (state in, updated state out) without "
             "donate_argnums — the old state stays resident, doubling "
             "state HBM",
    "GL109": "PartitionSpec axis name not declared by any mesh in the "
             "linted files (typo'd axis names fail far from here, at "
             "sharding time)",
    "GL110": "device scalar built from a Python value inside a "
             "lax.scan/cond/while body (jnp.int32(i), jnp.asarray(c) — "
             "the body retraces per host call and each constant is an "
             "implicit H2D the transfer sentinel only catches at "
             "runtime; stage it outside, or thread it through the "
             "carry)",
    "GL111": "broad except (bare, Exception, BaseException) that "
             "swallows the error — no re-raise, the bound exception "
             "unused, nothing logged: a fault domain that eats its "
             "faults cannot be recovered OR debugged (record the "
             "error, re-raise, or narrow the except)",
    "GL112": "graftscope emission or datetime wall-clock read inside "
             "jit-traced code — the timestamp is a trace-time "
             "constant and the event records ONCE, at trace time: a "
             "silent lie on the timeline (emit at host boundaries — "
             "drain, admission, metric fetch; bare time.* reads are "
             "GL103's)",
    "GL113": "profiler misuse: jax.profiler.start_trace with no "
             "reachable stop_trace (an unstopped trace buffers "
             "forever and the .xplane.pb never flushes — the grant "
             "window ends with NO artifact), or profiler trace "
             "control (utils.profiler.trace / jax.profiler.start_"
             "trace) inside jit-traced code (runs once at trace "
             "time; the profiled region is a lie)",
    "GL114": "signal.signal installing a fresh handler without "
             "capturing the previous one (no signal.getsignal in "
             "scope) — the displaced handler is DISCARDED: a second "
             "registrant (preemption checkpointing, drain, an "
             "external supervisor's hook) silently stops firing; "
             "capture with getsignal and CHAIN it, as the trainer's "
             "_install_preemption_handler does",
    "GL115": "wall-clock timing around a dispatch-only jitted call "
             "with no block_until_ready/device sync between the "
             "start and the closing clock read — jax dispatch is "
             "async, so the stopwatch measures ENQUEUE latency, not "
             "execution: the reported number is a lie that gets "
             "faster the less the host waits (sync the result — "
             "block_until_ready / device_get / profiler.sync — "
             "inside the timed region, the bench.py readback "
             "discipline)",
    "GL116": "Python control flow coercing a traced array to bool "
             "inside jit-traced code (`if accepted:`, `while mask:`, "
             "`bool(tracer)` on a jnp/jax-produced value — the "
             "accept-mask bug class: TracerBoolConversionError at "
             "trace time, which only explodes when the branch is "
             "finally traced; keep acceptance/freeze logic as array "
             "masking — jnp.where/lax.select/lax.cond)",
    "GL117": "blocking socket op with no timeout/deadline in scope "
             "(.recv/.recv_into/.recvfrom/.accept/.makefile, a "
             "sock.connect, or socket.create_connection without a "
             "timeout, in a scope — function, class, or module top "
             "level — with no settimeout/setdefaulttimeout/"
             "create_connection(timeout=)/run_with_timeout/"
             "*ensure_timeout establishing a bound): the "
             "distributed-hang class — a silent peer parks the "
             "process forever, with no named error and no timeline "
             "(graftwire's sockets are all deadline-bounded; keep it "
             "that way)",
    "GL118": "child-process spawn with no reaping evidence in scope "
             "(subprocess.Popen or multiprocessing.Process in a "
             "scope — function, class, or module top level — with no "
             ".wait/.join/.kill/.terminate/.communicate anywhere in "
             "that scope chain): the orphan-child class — a spawned "
             "replica/worker that nothing ever reaps leaks a zombie "
             "on every crash path and outlives the run holding "
             "ports, devices and file locks (graftscale's "
             "ProcessReplicaSpawner discipline: every Popen has a "
             "wait-then-kill release in the same class; "
             "subprocess.run/check_call/check_output self-reap)",
    "GL119": "lock-order cycle across the package lock graph (lock B "
             "acquired while holding A at one site, A while holding B "
             "at another — directly or through the resolved call "
             "graph; re-acquiring a non-reentrant threading.Lock "
             "already held reports as a one-lock cycle): two threads "
             "entering in opposite order deadlock permanently with no "
             "named error — pick ONE global acquisition order "
             "(graftrace reports the full cycle with every "
             "acquisition site)",
    "GL120": "blocking operation under a held lock (socket recv/"
             "accept/connect/sendall, time.sleep, subprocess run/"
             "wait/communicate, os.fsync, Thread.join, wire RPC "
             ".call — direct, through resolved callees, or through a "
             "function passed as an argument inside the lock scope): "
             "every thread contending that lock parks behind one "
             "slow peer/disk/child for the full wait — the exact "
             "class PR 15's review fixed by hand in WireServer "
             "(kill_connections queued behind a drain handler "
             "holding the verb lock); move the slow work outside "
             "the lock or give it its own lock",
    "GL121": "thread-shared mutable attribute with no common lock in "
             "evidence (attribute written outside __init__ from a "
             "Thread(target=...) entry point's reachable body and "
             "accessed from methods outside that closure, with no "
             "single lock held at every involved site): the lost-"
             "update / torn-read class that only surfaces under "
             "load — guard every access with ONE shared lock, or "
             "confine the attribute to a single thread",
    "GL122": "copy-on-send in a wire path (``.tobytes()``, "
             "``b''.join(...)``, or ``bytes(buf)`` inside a scope "
             "that also calls ``.sendall``/``.sendmsg``): the frame "
             "was about to be handed to the kernel, and this call "
             "duplicated the payload in Python first — at KV-block "
             "size that is a second multi-MB copy per RPC on the "
             "PageTransfer hot path (graftlink's discipline: the "
             "header prefix plus raw numpy memoryview segments ride "
             "a scatter-gather sendmsg; nothing is assembled)",
    "GL123": "resource acquired with an escaping path that skips its "
             "release (pool grant / socket / thread / file / "
             "PageTransfer still owned at an early return, an "
             "unwinding raise, a risky call with no try/finally, or "
             "a loop iteration end): the leaked grant is capacity "
             "another request never gets back — release it, move "
             "ownership explicitly (return / store-into-owner / "
             "consuming call), or guard the gap",
    "GL124": "double-release: a release of a resource that EVERY "
             "path already released (straight-line repeat, a finally "
             "duplicating the body's release, a release after both "
             "branches released): the pool free list corrupts (or "
             "another holder's live grant is freed under it) with no "
             "named error at the true culprit — release exactly "
             "once, on exactly one path",
    "GL125": "ownership ambiguity: a pooled resource (slot/page/"
             "buffer) stored into the same self.<attr> from two or "
             "more call paths while NO method of the class releases "
             "through that attribute — every path assumes another "
             "is the owner and nobody frees; give the attribute one "
             "releasing owner or release before storing",
}

# wrappers that COMPILE (jit family) — GL105/106/107/108 anchor on these
_JIT_DOTTED = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
}
# wrappers that TRACE their function argument(s)
_TRACE_DOTTED = _JIT_DOTTED | {
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.map", "jax.lax.switch",
}
_TIME_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
               "sleep", "time_ns", "perf_counter_ns", "monotonic_ns"}
# graftscope emission helpers (GL112): timestamps read at trace time
# record one constant event — never inside traced scope
_SCOPE_EMITTERS = {"emit", "emit_span", "span", "flight_dump"}
_DATETIME_CLOCKS = {"now", "utcnow", "today"}
_LOG_ATTRS = {"debug", "info", "warning", "warn", "error", "critical",
              "exception", "log"}
_LOG_BASES = {"logger", "log", "LOG", "logging"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "setdefault", "remove", "discard", "clear", "popitem"}
# Pallas kernel refs: subscript-STORES to `*_ref` names are the Pallas
# memory model (o_ref[...] = acc), not a Python side effect
_REF_NAME = re.compile(r"(^|_)refs?$")
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "sharding"}
_AXIS_KWARGS = {"axis_name", "seq_axis", "pipe_axis", "bn_axis"}
_STATE_PARAMS = {"state", "train_state"}


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class _Func:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    file: "_File"
    qual: str
    parent: Optional["_Func"]
    params: List[str] = field(default_factory=list)
    pos_params: List[str] = field(default_factory=list)
    calls: Set[str] = field(default_factory=set)
    nested: Dict[str, "_Func"] = field(default_factory=dict)
    jit_scoped: bool = False
    # body of a control-flow primitive (lax.scan/cond/while/fori/
    # switch/map) — traced from ANY caller, jitted or not (GL110)
    ctrl_body: bool = False
    # direct jit root: (statics, donate_seen, site_line) — only set when
    # the function NAME is wrapped/decorated by jax.jit itself, so its
    # static_argnames/argnums are knowable (GL106/107/108 need this)
    root_statics: Optional[Set[str]] = None
    root_donate: bool = False
    root_line: int = 0

    @property
    def name(self) -> str:
        return self.node.name


class _File:
    def __init__(self, path: str, modkey: Tuple[str, ...], tree: ast.AST,
                 lines: List[str]):
        self.path = path
        self.modkey = modkey
        self.tree = tree
        self.lines = lines
        self.origins: Dict[str, str] = {}  # local name -> dotted origin
        # local name -> (modkey, original name) for intra-package imports
        self.pkg_imports: Dict[str, Tuple[Tuple[str, ...], str]] = {}
        self.funcs: List[_Func] = []
        self.by_name: Dict[str, _Func] = {}  # module+method level defs
        self.owner: Dict[int, Optional[_Func]] = {}  # id(node) -> func


def _dotted(expr: ast.AST, file: _File) -> Optional[str]:
    """Resolve an expression to a dotted origin path: ``np.asarray`` ->
    ``numpy.asarray`` (through import aliases), bare names through
    ``from x import y`` origins. None when not a name/attribute chain."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = file.origins.get(node.id, node.id)
    parts.append(base)
    return ".".join(reversed(parts))


def _iter_own(func_node: ast.AST):
    """Yield every node lexically in ``func_node``'s body but not inside
    a nested def/class (those have their own _Func entries)."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _const_str_seq(node: ast.AST) -> Optional[List[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
            else:
                return None
        return out
    return None


def _const_int_seq(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
            else:
                return None
        return out
    return None


def _modkey_for(path: str, root_parent: Optional[str]) -> Tuple[str, ...]:
    import os

    if root_parent:
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root_parent))
    else:
        rel = os.path.basename(path)
    parts = rel.replace("\\", "/").split("/")
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return tuple(p for p in parts if p and p != ".")


# --------------------------------------------------------------- pass 1

def _collect_file(path: str, src: str, modkey: Tuple[str, ...]) -> _File:
    tree = ast.parse(src, filename=path)
    f = _File(path, modkey, tree, src.splitlines())

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                f.origins[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = modkey[:-node.level] if node.level <= len(modkey) \
                    else ()
                mod = base + tuple((node.module or "").split(".")
                                   if node.module else ())
                mod = tuple(p for p in mod if p)
                for alias in node.names:
                    f.pkg_imports[alias.asname or alias.name] = (
                        mod, alias.name)
                    f.origins[alias.asname or alias.name] = ".".join(
                        mod + (alias.name,))
            else:
                mod = node.module or ""
                for alias in node.names:
                    f.origins[alias.asname or alias.name] = (
                        f"{mod}.{alias.name}" if mod else alias.name)
                    if mod:
                        f.pkg_imports[alias.asname or alias.name] = (
                            tuple(mod.split(".")), alias.name)

    # function index with lexical parents
    def index(node: ast.AST, parent: Optional[_Func], prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                fn = _Func(child, f, qual, parent)
                a = child.args
                fn.pos_params = [x.arg for x in a.posonlyargs + a.args]
                fn.params = list(fn.pos_params) + \
                    [x.arg for x in a.kwonlyargs]
                f.funcs.append(fn)
                if parent is None:
                    f.by_name.setdefault(child.name, fn)
                else:
                    parent.nested[child.name] = fn
                index(child, fn, qual + ".")
            elif isinstance(child, ast.ClassDef):
                # methods register at module visibility by simple name
                # (resolves the ``jax.jit(self._insert_fn)`` idiom)
                index(child, parent, f"{prefix}{child.name}.")
            else:
                index(child, parent, prefix)

    index(tree, None, "")
    # methods (parent None but nested in classes) land in by_name via
    # the parent-None branch above; also make every top-level-class
    # method resolvable
    for fn in f.funcs:
        if fn.parent is None:
            f.by_name.setdefault(fn.name, fn)

    # per-func call sets (own body only)
    for fn in f.funcs:
        for node in _iter_own(fn.node):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name):
                    fn.calls.add(node.func.id)
                elif (isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id in ("self", "cls")):
                    fn.calls.add(node.func.attr)
    return f


# ------------------------------------------------------- root detection

def _is_trace_wrapper(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    return (dotted in _TRACE_DOTTED
            or dotted.endswith(".compat.shard_map"))


def _is_jit(dotted: Optional[str]) -> bool:
    return dotted in _JIT_DOTTED


def _resolve_local(file: _File, name: str,
                   scope: Optional[_Func]) -> Optional[_Func]:
    fn = scope
    while fn is not None:
        if name in fn.nested:
            return fn.nested[name]
        fn = fn.parent
    return file.by_name.get(name)


def _descendants(fn: _Func) -> List[_Func]:
    out = []
    stack = list(fn.nested.values())
    while stack:
        x = stack.pop()
        out.append(x)
        stack.extend(x.nested.values())
    return out


def _jit_statics(call_kwargs, target: Optional[_Func]) -> Set[str]:
    statics: Set[str] = set()
    for kw in call_kwargs:
        if kw.arg == "static_argnames":
            names = _const_str_seq(kw.value)
            if names:
                statics.update(names)
        elif kw.arg == "static_argnums" and target is not None:
            nums = _const_int_seq(kw.value)
            if nums:
                for i in nums:
                    if 0 <= i < len(target.pos_params):
                        statics.add(target.pos_params[i])
    return statics


def _donate_seen(call_kwargs) -> bool:
    return any(kw.arg in ("donate_argnums", "donate_argnames")
               for kw in call_kwargs)


def _mark_root(target: _Func, statics: Set[str], donate: bool, line: int):
    target.jit_scoped = True
    if target.root_statics is None:
        target.root_statics = statics
        target.root_donate = donate
        target.root_line = line


def _scan_roots(files: Sequence[_File], index) -> List[_Func]:
    """Find every jit/trace root; returns the seed list for the global
    closure. ``index[(modkey, name)]`` resolves cross-file targets."""
    seeds: List[_Func] = []

    def resolve_arg(file: _File, scope: Optional[_Func], arg: ast.AST,
                    *, factories: bool = True,
                    seen: Optional[Set[str]] = None) -> List[_Func]:
        """Functions a wrapper argument refers to. A direct Name/self
        attr resolves to its def; a Call of a local def is the factory
        idiom — the factory's nested defs are the traced ones; a Name
        bound by a local assignment (``body = make_body(...)`` before
        ``lax.scan(body, ...)``) resolves through the assignment's
        value (``seen`` breaks self-referential chains)."""
        if isinstance(arg, ast.Name):
            t = _resolve_local(file, arg.id, scope)
            if t is None and arg.id in file.pkg_imports:
                t = index.get(file.pkg_imports[arg.id])
            if t is not None:
                return [t]
            if not factories or (seen and arg.id in seen):
                return []
            # control-flow-primitive bodies often reach the wrapper
            # through a local variable; chase the assignment(s)
            seen = (seen or set()) | {arg.id}
            space = (_iter_own(scope.node) if scope is not None
                     else ast.iter_child_nodes(file.tree))
            out: List[_Func] = []
            for node in space:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t_, ast.Name) and t_.id == arg.id
                        for t_ in node.targets):
                    out.extend(resolve_arg(file, scope, node.value,
                                           seen=seen))
            return out
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id in ("self", "cls")):
            t = file.by_name.get(arg.attr)
            return [t] if t is not None else []
        if factories and isinstance(arg, ast.Call):
            inner = resolve_arg(file, scope, arg.func, factories=False,
                                seen=seen)
            out = []
            for fac in inner:
                out.extend(_descendants(fac))
            return out
        return []

    for file in files:
        # decorators
        for fn in file.funcs:
            for dec in fn.node.decorator_list:
                d = _dotted(dec, file)
                if _is_trace_wrapper(d):
                    if _is_jit(d):
                        _mark_root(fn, set(), False, fn.node.lineno)
                    fn.jit_scoped = True
                    seeds.append(fn)
                elif isinstance(dec, ast.Call):
                    dc = _dotted(dec.func, file)
                    if _is_jit(dc):
                        _mark_root(fn, _jit_statics(dec.keywords, fn),
                                   _donate_seen(dec.keywords),
                                   fn.node.lineno)
                        seeds.append(fn)
                    elif (dc == "functools.partial" and dec.args
                          and _is_jit(_dotted(dec.args[0], file))):
                        _mark_root(fn, _jit_statics(dec.keywords, fn),
                                   _donate_seen(dec.keywords),
                                   fn.node.lineno)
                        seeds.append(fn)
                    elif _is_trace_wrapper(dc):
                        fn.jit_scoped = True
                        seeds.append(fn)
        # wrapper call sites
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func, file)
            if not _is_trace_wrapper(d):
                continue
            scope = file.owner.get(id(node))
            func_args = node.args
            is_ctrl = bool(d) and d.endswith(
                ("scan", "while_loop", "fori_loop", "cond", "switch",
                 "map"))
            if is_ctrl:
                candidates = func_args  # body position varies — take all
            else:
                candidates = func_args[:1]
            for arg in candidates:
                for target in resolve_arg(file, scope, arg):
                    if (_is_jit(d) and isinstance(
                            arg, (ast.Name, ast.Attribute))):
                        _mark_root(target,
                                   _jit_statics(node.keywords, target),
                                   _donate_seen(node.keywords),
                                   node.lineno)
                    target.jit_scoped = True
                    if is_ctrl:
                        target.ctrl_body = True
                    seeds.append(target)
    return seeds


def _fill_owners(file: _File):
    def walk(node: ast.AST, owner: Optional[_Func]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = next((x for x in file.funcs if x.node is child), None)
                file.owner[id(child)] = owner
                walk(child, fn)
            else:
                file.owner[id(child)] = owner
                walk(child, owner)

    walk(file.tree, None)


def _propagate(files: Sequence[_File], index, seeds: List[_Func]):
    """Call-graph closure: a traced function's callees are traced."""
    work = list(seeds)
    while work:
        fn = work.pop()
        for name in fn.calls:
            t = _resolve_local(fn.file, name, fn)
            if t is None and name in fn.file.pkg_imports:
                t = index.get(fn.file.pkg_imports[name])
            if t is not None and not t.jit_scoped:
                t.jit_scoped = True
                work.append(t)


# --------------------------------------------------------------- rules

def _is_shape_static(expr: ast.AST) -> bool:
    """True when the expression is trace-time static by construction:
    a constant, a len() call, or anything reading .shape/.ndim/etc."""
    if isinstance(expr, ast.Constant):
        return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return True
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "len"):
            return True
    return not any(isinstance(n, (ast.Name, ast.Subscript, ast.Call))
                   for n in ast.walk(expr))


def _local_names(fn: _Func) -> Set[str]:
    names = set(fn.params)
    a = fn.node.args
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for node in _iter_own(fn.node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in ast.walk(node.target):
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for t in ast.walk(node.optional_vars):
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.NamedExpr) and isinstance(
                node.target, ast.Name):
            names.add(node.target.id)
    names.update(fn.nested)
    return names


def _traced_names_in_test(test: ast.AST, traced: Set[str]) -> List[str]:
    """Names from ``traced`` whose VALUE the test depends on — skipping
    is/is-not None checks, .shape/.ndim/.dtype reads, isinstance, len."""
    hits: List[str] = []

    def visit(node: ast.AST):
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return
            visit(node.value)
            return
        if isinstance(node, ast.Call):
            d = node.func
            if isinstance(d, ast.Name) and d.id in ("isinstance", "len",
                                                    "getattr", "hasattr"):
                return
            for a in node.args:
                visit(a)
            return
        if isinstance(node, ast.Name):
            if node.id in traced:
                hits.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits


def _check_jit_scoped_body(fn: _Func, out: List[Finding]):
    file = fn.file
    path = file.path

    def add(node, rule, msg):
        out.append(Finding(path, node.lineno, node.col_offset, rule, msg))

    locals_ = None  # computed lazily for GL104
    for node in _iter_own(fn.node):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            add(node, "GL104",
                f"{kind} statement in jit-traced `{fn.qual}` — the "
                "rebinding happens once at trace time, not per step")
            continue
        if isinstance(node, ast.Call):
            d = _dotted(node.func, file)
            # ---- GL101: host syncs
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "item" and not node.args:
                    add(node, "GL101",
                        f".item() in jit-traced `{fn.qual}` forces a "
                        "device->host sync (trace error under jit)")
                    continue
                if node.func.attr == "block_until_ready":
                    add(node, "GL101",
                        f".block_until_ready() in jit-traced `{fn.qual}`"
                        " — a host sync; jit output is already async")
                    continue
            if d in ("jax.device_get", "jax.block_until_ready"):
                add(node, "GL101",
                    f"{d} in jit-traced `{fn.qual}` forces a device->"
                    "host sync")
                continue
            if d in ("numpy.asarray", "numpy.array"):
                add(node, "GL101",
                    f"{d.replace('numpy', 'np')} in jit-traced "
                    f"`{fn.qual}` materializes on host (use jnp, or "
                    "hoist to the caller)")
                continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int", "bool")
                    and len(node.args) == 1 and not node.keywords
                    and not _is_shape_static(node.args[0])):
                arg = node.args[0]
                # a bare Name is only knowably traced when it is a
                # non-static param of a DIRECT jit root; in closure-
                # propagated functions plain names are usually Python
                # config captured at build time (e.g. int(block_k))
                name_traced = (
                    isinstance(arg, ast.Name)
                    and fn.root_statics is not None
                    and arg.id in set(fn.params) - fn.root_statics)
                if name_traced or not isinstance(arg, ast.Name):
                    add(node, "GL101",
                        f"{node.func.id}() on a traced value in "
                        f"`{fn.qual}` is a host sync "
                        "(ConcretizationTypeError under jit)")
                    continue
            # ---- GL102: print / logging
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                add(node, "GL102",
                    f"print() in jit-traced `{fn.qual}` fires at trace "
                    "time only — use jax.debug.print")
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LOG_ATTRS
                    and isinstance(node.func.value, ast.Name)
                    and (node.func.value.id in _LOG_BASES
                         or (file.origins.get(node.func.value.id, "")
                             .split(".")[0] == "logging"))):
                add(node, "GL102",
                    f"logging call in jit-traced `{fn.qual}` fires at "
                    "trace time only — use jax.debug.print")
                continue
            # ---- GL103: wall clock / host RNG
            if d:
                root = d.split(".")[0]
                if root == "time" and d.split(".")[-1] in _TIME_ATTRS:
                    add(node, "GL103",
                        f"{d} in jit-traced `{fn.qual}` is baked in as "
                        "a constant at trace time")
                    continue
                if root == "random" and any(
                        v == "random" or v.startswith("random.")
                        for v in file.origins.values()):
                    # d is already alias-resolved ("import random as
                    # rnd" and "from random import randint" both land
                    # here); the origins scan rules out a mere local
                    # variable that happens to be NAMED random
                    add(node, "GL103",
                        f"stdlib {d} in jit-traced `{fn.qual}` draws "
                        "once at trace time — use jax.random")
                    continue
                if d.startswith("numpy.random."):
                    add(node, "GL103",
                        f"np.random in jit-traced `{fn.qual}` draws "
                        "once at trace time — use jax.random")
                    continue
                # ---- GL112: graftscope emission / datetime clocks —
                # the silent-lie class GL103's time.* check cannot
                # see (the clock read hides inside the emit helper,
                # or behind the datetime module)
                parts = d.split(".")
                if (len(parts) >= 2 and parts[-2] == "scope"
                        and parts[-1] in _SCOPE_EMITTERS):
                    add(node, "GL112",
                        f"graftscope {parts[-1]}() in jit-traced "
                        f"`{fn.qual}` stamps a trace-time constant "
                        "and records ONE event, at trace time — a "
                        "silent lie on the timeline; emit at a host "
                        "boundary instead")
                    continue
                if (root == "datetime"
                        and parts[-1] in _DATETIME_CLOCKS):
                    add(node, "GL112",
                        f"{d} in jit-traced `{fn.qual}` is baked in "
                        "as a trace-time constant (the datetime "
                        "spelling of GL103's wall-clock rule)")
                    continue
                # ---- GL113: profiler control from inside the trace —
                # start/stop_trace and the utils.profiler.trace ctx
                # manager run ONCE at trace time, so the "profiled"
                # region covers tracing, not execution
                if (d in ("jax.profiler.start_trace",
                          "jax.profiler.stop_trace")
                        or (len(parts) >= 2 and parts[-2] == "profiler"
                            and parts[-1] == "trace")):
                    add(node, "GL113",
                        f"profiler trace control ({parts[-1]}) in "
                        f"jit-traced `{fn.qual}` runs once at trace "
                        "time — profile around the jitted call, not "
                        "inside it")
                    continue
            continue
        # ---- GL104: captured-container mutation. Only BARE statement
        # calls (result discarded) — a used return value means a
        # functional API like optimizer.update(grads, ...), not a
        # container mutation (dict.update/list.append return None).
        if (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in _MUTATORS
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id not in ("self", "cls")):
            call = node.value
            if locals_ is None:
                locals_ = _local_names(fn)
            if call.func.value.id not in locals_:
                add(call, "GL104",
                    f"`{call.func.value.id}.{call.func.attr}(...)` "
                    f"in jit-traced `{fn.qual}` mutates enclosing-"
                    "scope state once at trace time, not per step")
            continue
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and not _REF_NAME.search(t.value.id)):
                    if locals_ is None:
                        locals_ = _local_names(fn)
                    if t.value.id not in locals_ | {"self", "cls"}:
                        add(node, "GL104",
                            f"subscript-assign to captured "
                            f"`{t.value.id}` in jit-traced `{fn.qual}` "
                            "mutates enclosing-scope state at trace "
                            "time")


def _check_traced_branches(fn: _Func, out: List[Finding]):
    """GL106 — only on DIRECT jit roots, whose static_argnames/argnums
    are parseable (closure-propagated functions receive values whose
    staticness is unknowable statically: skipping them keeps the rule
    high-precision)."""
    if fn.root_statics is None:
        return
    traced = set(fn.params) - fn.root_statics - {"self", "cls"}
    if not traced:
        return
    for node in _iter_own(fn.node):
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            test = node.test
            hits = _traced_names_in_test(test, traced)
            if hits:
                out.append(Finding(
                    fn.file.path, node.lineno, node.col_offset, "GL106",
                    f"branch on traced argument(s) {sorted(set(hits))} "
                    f"of jitted `{fn.qual}` — TracerBoolConversionError "
                    "at trace time (use lax.cond/lax.select, or declare "
                    "the arg in static_argnames)"))


# GL116: jax/jnp calls whose RESULT is host metadata, not a traced
# array — branching on these is ordinary Python (keep the rule
# high-precision; anything else under the jax/jnp namespaces is
# assumed array-valued)
_GL116_STATIC_TAILS = {
    "ShapeDtypeStruct", "dtype", "device_count", "local_device_count",
    "default_backend", "devices", "process_index", "process_count",
    "tree_structure", "eval_shape", "named_scope",
}


def _gl116_array_call(node: ast.AST, file: _File) -> bool:
    """Is ``node`` a call into the jax/jnp namespaces that returns a
    traced array (by the static-tail allowlist)?"""
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func, file)
    if not d:
        return False
    parts = d.split(".")
    if parts[0] != "jax":  # jnp resolves to jax.numpy via origins
        return False
    return parts[-1] not in _GL116_STATIC_TAILS


def _check_traced_bool_coercion(fn: _Func, out: List[Finding]):
    """GL116 — Python `if`/`while`/`bool()` on a LOCAL value produced
    by a jnp/jax call inside jit-traced code. Complements GL106 (which
    covers branches on traced PARAMS of direct jit roots): the
    accept-mask bug class builds the mask locally (`accepted =
    jnp.logical_and(...)`) and branches on it — invisible to GL106,
    and it only explodes at trace time. High-precision by
    construction: only bare names assigned from jax/jnp array calls
    (or direct jnp calls in the test) are flagged."""
    file = fn.file
    traced_locals: Set[str] = set()
    for node in _iter_own(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (isinstance(t, ast.Name)
                    and _gl116_array_call(node.value, file)):
                traced_locals.add(t.id)

    def name_hits(test) -> List[str]:
        if isinstance(test, ast.Name):
            return [test.id] if test.id in traced_locals else []
        if (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)):
            return name_hits(test.operand)
        if isinstance(test, ast.BoolOp):
            hits: List[str] = []
            for v in test.values:
                hits.extend(name_hits(v))
            return hits
        return []

    def add(node, what):
        out.append(Finding(
            fn.file.path, node.lineno, node.col_offset, "GL116",
            f"{what} in jit-traced `{fn.qual}` coerces a traced "
            "array to a Python bool — TracerBoolConversionError at "
            "trace time (the accept-mask bug class); keep it as "
            "array masking (jnp.where/lax.select) or lax.cond"))

    for node in _iter_own(fn.node):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            kind = ("while" if isinstance(node, ast.While) else "if")
            hits = name_hits(node.test)
            if hits:
                add(node, f"`{kind} {'/'.join(sorted(set(hits)))}:` "
                          "branch on a jnp-produced value")
                continue
            if _gl116_array_call(node.test, file):
                add(node, f"`{kind}` on a jnp/jax call result")
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Name)
              and node.func.id == "bool" and len(node.args) == 1
              and not node.keywords
              and isinstance(node.args[0], ast.Name)
              and node.args[0].id in traced_locals):
            add(node, f"bool({node.args[0].id}) on a jnp-produced "
                      "value")


def _check_static_defaults(fn: _Func, out: List[Finding]):
    """GL107: a static jit arg whose default is a mutable literal."""
    if fn.root_statics is None or not fn.root_statics:
        return
    a = fn.node.args
    pos = a.posonlyargs + a.args
    defaults: Dict[str, ast.AST] = {}
    for arg, dflt in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        defaults[arg.arg] = dflt
    for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
        if dflt is not None:
            defaults[arg.arg] = dflt
    for name in sorted(fn.root_statics):
        dflt = defaults.get(name)
        if isinstance(dflt, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(dflt, ast.Call)
                and isinstance(dflt.func, ast.Name)
                and dflt.func.id in ("list", "dict", "set")):
            out.append(Finding(
                fn.file.path, fn.node.lineno, fn.node.col_offset, "GL107",
                f"static jit argument `{name}` of `{fn.qual}` has a "
                "mutable (unhashable) default — jit statics must hash "
                "(use a tuple / frozenset / None)"))


def _check_missing_donate(fn: _Func, out: List[Finding]):
    """GL108: jitted state-in/state-out function without donation."""
    if fn.root_statics is None or fn.root_donate:
        return
    params = [p for p in fn.params if p not in ("self", "cls")]
    if not params or params[0] not in _STATE_PARAMS:
        return
    state = params[0]
    replaces = any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "replace"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == state
        for node in _iter_own(fn.node))
    if replaces:
        out.append(Finding(
            fn.file.path, fn.root_line, 0, "GL108",
            f"jit of `{fn.qual}` takes `{state}` and returns an updated "
            "copy but declares no donate_argnums — the old state stays "
            "resident, doubling state HBM (donate_argnums=(0,))"))


_JNP_SCALAR_CTORS = {
    "asarray", "array", "int8", "int16", "int32", "int64", "uint8",
    "uint16", "uint32", "uint64", "float16", "bfloat16", "float32",
    "float64",
}


def _module_numeric_const(file: _File, name: str) -> bool:
    """True when ``name`` is assigned a numeric literal at MODULE
    level (``EPS = 1e-6``) — the module-scope half of GL110's
    'Python scalar captured from a host scope'."""
    for node in ast.iter_child_nodes(file.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, (int, float, bool)))
    return False


def _check_ctrl_body_scalars(fn: _Func, out: List[Finding]):
    """GL110 — only in control-flow bodies (``lax.scan``/``cond``/
    ``while``/``fori``/``switch``), which jax re-traces on EVERY host
    call when the wrapper runs outside jit: a ``jnp.int32(chunk)`` /
    ``jnp.asarray(0.5)`` built from a Python value there materializes
    a fresh device constant per call — the implicit H2D class the
    runtime sentinel (``guard_transfers``) catches only when traffic
    actually hits it. Flags numeric literals and names captured from
    HOST scopes; operands that are body parameters/locals, captured
    from an enclosing TRACED function (tracers), or shape-derived are
    exempt — and so is the WHOLE body when any lexical ancestor is
    itself jit-traced (the wrapper then runs under jit: the body
    traces once per compile and its constants bake into the
    executable — no per-call H2D)."""
    if not fn.ctrl_body:
        return
    ancestor = fn.parent
    while ancestor is not None:
        if ancestor.jit_scoped:
            return
        ancestor = ancestor.parent
    file = fn.file
    locals_ = _local_names(fn)
    for node in _iter_own(fn.node):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        d = _dotted(node.func, file)
        if (not d or not d.startswith("jax.numpy.")
                or d.split(".")[-1] not in _JNP_SCALAR_CTORS):
            continue
        arg = node.args[0]
        flagged = False
        if isinstance(arg, ast.Constant) and isinstance(
                arg.value, (int, float, bool)):
            flagged = True
        elif (isinstance(arg, ast.Name) and arg.id not in locals_
                and not _is_shape_static(arg)):
            parent = fn.parent
            while parent is not None:
                if (arg.id in _local_names(parent)
                        or arg.id in parent.nested):
                    # bound by an enclosing fn: a tracer when that fn
                    # is itself traced, a Python scalar when it is a
                    # host factory/driver
                    flagged = not parent.jit_scoped
                    break
                parent = parent.parent
            else:
                # no enclosing fn binds it: a module-level NUMERIC
                # constant (EPS = 1e-6) is a host scalar too — same
                # fresh-device-constant-per-trace hazard; anything
                # else at module scope (arrays, config objects) is
                # not knowably a Python scalar, so it stays exempt
                flagged = _module_numeric_const(file, arg.id)
        if flagged:
            out.append(Finding(
                file.path, node.lineno, node.col_offset, "GL110",
                f"`{ast.unparse(node) if hasattr(ast, 'unparse') else d}"
                f"` builds a device scalar from a Python value inside "
                f"control-flow body `{fn.qual}` — re-traced per host "
                "call, an implicit H2D each time (stage it outside the "
                "body or thread it through the carry)"))


_BROAD_EXC = {"Exception", "BaseException"}


def _is_broad_handler(handler: ast.ExceptHandler, file: _File) -> bool:
    """Bare ``except:``, ``except Exception``, ``except BaseException``
    (alone or anywhere in a tuple)."""
    t = handler.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for el in elts:
        d = _dotted(el, file)
        if d and d.split(".")[-1] in _BROAD_EXC:
            return True
    return False


def _handler_records(handler: ast.ExceptHandler, file: _File) -> bool:
    """Does the handler re-raise, use the bound exception (format it,
    store it, wrap it), or at least emit through a logging-ish call?
    Any of these makes the swallow deliberate and observable."""
    bound = handler.name
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if (bound and isinstance(node, ast.Name)
                    and node.id == bound):
                return True
            if isinstance(node, ast.Call):
                d = _dotted(node.func, file)
                last = d.split(".")[-1] if d else ""
                if (last in _LOG_ATTRS or last in ("print", "warn")
                        or d == "warnings.warn"):
                    return True
    return False


def _check_swallowed_except(file: _File, out: List[Finding]):
    """GL111 — a broad except whose handler swallows the error: no
    re-raise, the bound exception never read, nothing logged. Silent
    fault-swallowing is the anti-pattern the graftfault layer exists
    to kill: a retry path can only recover what it can SEE, and a
    fleet can only page on what is recorded. The optional-dependency
    probe idiom (a ``try`` whose body is imports only) is exempt —
    there the absence of the module IS the information."""
    for node in ast.walk(file.tree):
        if not isinstance(node, ast.Try):
            continue
        import_probe = bool(node.body) and all(
            isinstance(s, (ast.Import, ast.ImportFrom))
            for s in node.body)
        if import_probe:
            continue
        for handler in node.handlers:
            if not _is_broad_handler(handler, file):
                continue
            if _handler_records(handler, file):
                continue
            shown = ("except:" if handler.type is None else
                     f"except {ast.unparse(handler.type)}:"
                     if hasattr(ast, "unparse") else "except ...:")
            out.append(Finding(
                file.path, handler.lineno, handler.col_offset, "GL111",
                f"`{shown}` swallows the error — no re-raise, the "
                "exception unused, nothing logged; record it, re-raise "
                "it, or narrow the except (silent fault-swallowing "
                "hides exactly the failures graftfault injects)"))


def _check_unpaired_trace(file: _File, out: List[Finding]):
    """GL113 (host half) — ``jax.profiler.start_trace`` in a file with
    NO reachable ``stop_trace``. Reachability is approximated at file
    granularity (a paired stop in the same function, a finally block,
    or a sibling wrapper method all count): the bug class this catches
    is the stop being FORGOTTEN entirely, which leaves the trace
    buffering until process exit and never flushes an .xplane.pb —
    a whole grant window's profiling silently lost. Starts inside
    jit-traced scope are the trace-time-misuse half's (skipped here
    so one line never double-reports)."""
    stop_seen = False
    starts = []
    for node in ast.walk(file.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func, file)
        if d == "jax.profiler.stop_trace":
            stop_seen = True
        elif d == "jax.profiler.start_trace":
            owner = file.owner.get(id(node))
            if owner is None or not owner.jit_scoped:
                starts.append(node)
    if stop_seen:
        return
    for node in starts:
        out.append(Finding(
            file.path, node.lineno, node.col_offset, "GL113",
            "jax.profiler.start_trace with no reachable stop_trace in "
            "this file — an unstopped trace buffers until process "
            "exit and never flushes its .xplane.pb (use "
            "utils.profiler.trace, a try/finally, or call stop_trace)"))


# GL115: host clocks that start/stop a stopwatch, and the calls that
# actually force device completion inside a timed region
_GL115_CLOCKS = {"time.perf_counter", "time.monotonic", "time.time"}
_GL115_SYNC_ATTRS = {"block_until_ready", "item"}
_GL115_SYNC_DOTTED = {"jax.block_until_ready", "jax.device_get",
                      "jax.effects_barrier", "numpy.asarray",
                      "numpy.array"}


def _is_gl115_sync(node: ast.Call, file: _File) -> bool:
    if (isinstance(node.func, ast.Attribute)
            and node.func.attr in _GL115_SYNC_ATTRS):
        return True
    d = _dotted(node.func, file)
    if not d:
        return False
    # utils.profiler.sync (the framework's one D2H-forcing readback —
    # what bench.py's window discipline uses) counts however imported
    return d in _GL115_SYNC_DOTTED or d.endswith("profiler.sync")


def _check_unsynced_timing(file: _File, out: List[Finding]):
    """GL115 (host half) — per HOST function scope (and module scope),
    the stopwatch idiom ``t0 = clock(); ... jitted(...) ...;
    dt = clock() - t0`` with NO device sync between the start and the
    closing read. jax dispatch is asynchronous: the jitted call
    returns the moment the work is enqueued, so the measured interval
    is dispatch overhead, not execution — serving_bench's round-1
    class of lie. Deliberately precise over complete: only bare-name
    clock starts (``t0 = time.perf_counter()``), only closes that
    subtract a tracked start (a fresh clock read, or another tracked
    clock name, minus it), and only dispatch calls the file can prove
    are jitted (a direct jit root, or a name assigned from
    ``jax.jit(...)``). A sync anywhere in [start, close] — including
    the trainer's ``device_get`` windowed fetch and bench.py's
    ``profiler.sync`` readback — silences the finding."""
    module_jit_names = {
        t.id for node in ast.iter_child_nodes(file.tree)
        if isinstance(node, ast.Assign)
        and isinstance(node.value, ast.Call)
        and _is_jit(_dotted(node.value.func, file))
        for t in node.targets if isinstance(t, ast.Name)}

    def is_jit_dispatch(node: ast.Call, scope: Optional[_Func],
                        jit_names: Set[str]) -> bool:
        f = node.func
        if isinstance(f, ast.Call):  # jax.jit(f)(x) inline
            return _is_jit(_dotted(f.func, file))
        if not isinstance(f, ast.Name):
            return False
        if f.id in jit_names or f.id in module_jit_names:
            return True
        target = _resolve_local(file, f.id, scope)
        return target is not None and target.root_statics is not None

    scopes: List[Optional[_Func]] = [None] + [
        fn for fn in file.funcs if not fn.jit_scoped]
    for scope in scopes:
        nodes = list(_iter_own(scope.node) if scope is not None
                     else _iter_own(file.tree))
        # local names bound from jax.jit(...) in this scope
        jit_names = {
            t.id for node in nodes
            if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and _is_jit(_dotted(node.value.func, file))
            for t in node.targets if isinstance(t, ast.Name)}
        # clock-start bindings: name -> lines it was bound at
        starts: Dict[str, List[int]] = {}
        sync_lines: List[int] = []
        dispatch_lines: List[int] = []
        closes: List[Tuple[ast.AST, str]] = []  # (sub node, start name)
        for node in nodes:
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and _dotted(
                    node.value.func, file) in _GL115_CLOCKS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        starts.setdefault(t.id, []).append(node.lineno)
            if isinstance(node, ast.Call):
                if _is_gl115_sync(node, file):
                    sync_lines.append(node.lineno)
                elif is_jit_dispatch(node, scope, jit_names):
                    dispatch_lines.append(node.lineno)
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)):
                # candidate close; judged after the loop, once every
                # start binding is known (_iter_own's visit order is
                # not source order)
                closes.append((node, node.right.id))
        for node, start_name in closes:
            if start_name not in starts:
                continue
            left = node.left
            left_is_clock = (
                (isinstance(left, ast.Call)
                 and _dotted(left.func, file) in _GL115_CLOCKS)
                or (isinstance(left, ast.Name) and left.id in starts
                    and left.id != start_name))
            if not left_is_clock:
                continue
            close_line = node.lineno
            bound = [ln for ln in starts.get(start_name, [])
                     if ln < close_line]
            if not bound:
                continue
            start_line = max(bound)
            timed_dispatch = any(start_line < ln <= close_line
                                 for ln in dispatch_lines)
            synced = any(start_line <= ln <= close_line
                         for ln in sync_lines)
            if timed_dispatch and not synced:
                out.append(Finding(
                    file.path, close_line, node.col_offset, "GL115",
                    f"wall-clock close over `{start_name}` times a "
                    "dispatch-only jitted call with no "
                    "block_until_ready/device sync inside the timed "
                    "region — async dispatch makes this latency a "
                    "lie (sync the result before stopping the "
                    "clock, as bench.py's readback does)"))


def _check_signal_discard(file: _File, out: List[Finding]):
    """GL114 — ``signal.signal(sig, handler)`` installing a FRESH
    handler (a lambda, or a name resolving to a def in this file)
    from a scope with no ``signal.getsignal`` call: the previous
    handler is discarded, so whoever registered it (the trainer's
    preemption checkpointing, the serving drain hook, an external
    supervisor) silently stops seeing the signal. The clean shape —
    capture with ``getsignal``, chain in the new handler, restore on
    teardown — is what ``trainer._install_preemption_handler`` and
    ``heal.install_drain_handler`` do. Restores are exempt: passing a
    non-def value (a saved previous handler, ``signal.SIG_DFL``, a
    conditional of the two) is putting a handler BACK, not displacing
    one."""
    def scope_nodes(owner):
        if owner is not None:
            return _iter_own(owner.node)
        # module scope: top-level statements, minus def/class bodies
        return _iter_own(file.tree)

    for node in ast.walk(file.tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func, file) != "signal.signal":
            continue
        if len(node.args) < 2:
            continue
        handler = node.args[1]
        owner = file.owner.get(id(node))
        fresh = isinstance(handler, ast.Lambda)
        if isinstance(handler, ast.Name):
            fresh = _resolve_local(file, handler.id, owner) is not None
        if not fresh:
            continue  # restore / passthrough of a saved handler
        captured = any(
            isinstance(n, ast.Call)
            and _dotted(n.func, file) == "signal.getsignal"
            for n in scope_nodes(owner))
        if captured:
            continue
        out.append(Finding(
            file.path, node.lineno, node.col_offset, "GL114",
            "signal.signal installs a fresh handler but the previous "
            "one is never captured (no signal.getsignal in this "
            "scope) — it is DISCARDED, and whoever registered it "
            "(preemption checkpoint, drain hook, supervisor) silently "
            "stops firing; capture it and chain (see "
            "trainer._install_preemption_handler)"))


_BLOCKING_SOCKET_ATTRS = {"recv", "recv_into", "recvfrom", "accept",
                          "makefile"}
_TIMEOUT_SETTERS = {"settimeout", "setdefaulttimeout"}


def _check_blocking_socket(file: _File, out: List[Finding]):
    """GL117 — blocking socket operations with no timeout/deadline
    IN SCOPE: the distributed-hang class graftwire must never
    reintroduce. A ``.recv``/``.recv_into``/``.recvfrom``/
    ``.accept``/``.makefile`` call (any receiver — pipes and socket
    wrappers block the same way), a ``*sock*.connect(...)``, or a
    ``socket.create_connection`` WITHOUT a timeout argument is flagged
    unless deadline evidence exists in the call's scope chain:

    - the enclosing function (any enclosing def) contains a
      ``settimeout``/``setdefaulttimeout`` call, a
      ``create_connection(..., timeout)`` or a ``run_with_timeout``/
      ``*ensure_timeout`` call (the repo's canonical guard helper);
    - or the enclosing CLASS does, anywhere in its body — the
      configure-in-``__init__``, read-in-a-method shape;
    - or the module's top level does.

    Evidence in an UNRELATED sibling function does not count: a
    timeout someone set on a different socket in a different scope is
    exactly the false comfort that leaves the accept loop unbounded.
    """
    evidence_fns: Set[int] = set()
    evidence_cls: Set[int] = set()
    module_evidence = [False]
    # (call node, enclosing-fn id chain, enclosing-class id, label)
    blocking: List[Tuple[ast.Call, Tuple[int, ...], Optional[int],
                         str]] = []

    def _has_timeout_arg(call: ast.Call) -> bool:
        # timeout=None is an EXPLICIT request for an unbounded
        # blocking connect — the exact hang this rule targets — so
        # only a non-None timeout counts as a deadline
        for kw in call.keywords:
            if kw.arg == "timeout":
                return not (isinstance(kw.value, ast.Constant)
                            and kw.value.value is None)
        if len(call.args) >= 2:  # create_connection(addr, timeout)
            arg = call.args[1]
            return not (isinstance(arg, ast.Constant)
                        and arg.value is None)
        return False

    def _recv_name(expr: ast.AST) -> str:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return ""

    def _classify(call: ast.Call, fns: Tuple[int, ...],
                  cls: Optional[int]) -> None:
        func = call.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        d = _dotted(func, file) or ""
        last = d.split(".")[-1] if d else (
            func.id if isinstance(func, ast.Name) else (attr or ""))
        evidence = (attr in _TIMEOUT_SETTERS
                    or last in _TIMEOUT_SETTERS
                    or last == "run_with_timeout"
                    or last.endswith("ensure_timeout"))
        if last == "create_connection":
            if _has_timeout_arg(call):
                evidence = True
            else:
                blocking.append((call, fns, cls,
                                 "socket.create_connection without a "
                                 "timeout argument"))
        if evidence:
            evidence_fns.update(fns)
            if cls is not None:
                evidence_cls.add(cls)
            if not fns and cls is None:
                module_evidence[0] = True
            return
        if attr in _BLOCKING_SOCKET_ATTRS:
            blocking.append((call, fns, cls, f".{attr}()"))
        elif (attr == "connect"
              and "sock" in _recv_name(func.value).lower()):
            blocking.append((call, fns, cls, ".connect() on a socket"))

    def _visit(node: ast.AST, fns: Tuple[int, ...],
               cls: Optional[int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns = fns + (id(node),)
        elif isinstance(node, ast.ClassDef):
            cls = id(node)
        if isinstance(node, ast.Call):
            _classify(node, fns, cls)
        for child in ast.iter_child_nodes(node):
            _visit(child, fns, cls)

    _visit(file.tree, (), None)
    for call, fns, cls, label in blocking:
        if any(f in evidence_fns for f in fns):
            continue
        if cls is not None and cls in evidence_cls:
            continue
        if module_evidence[0]:
            continue
        out.append(Finding(
            file.path, call.lineno, call.col_offset, "GL117",
            f"blocking socket op ({label}) with no timeout/deadline "
            "in scope — a silent peer hangs this call forever with "
            "no named error; settimeout/create_connection(timeout=)/"
            "run_with_timeout bound it (the graftwire discipline: "
            "every socket op has a deadline)"))


_REAP_ATTRS = {"wait", "join", "kill", "terminate", "communicate"}


def _check_spawn_reap(file: _File, out: List[Finding]):
    """GL118 — child-process spawn with no reaping evidence IN SCOPE:
    the orphan-child class graftscale must never reintroduce. A
    ``subprocess.Popen(...)`` or ``multiprocessing.Process(...)``
    call is flagged unless reaping evidence exists in the call's
    scope chain:

    - the enclosing function (any enclosing def) contains a
      ``.wait``/``.join``/``.kill``/``.terminate``/``.communicate``
      attribute call;
    - or the enclosing CLASS does, anywhere in its body — the
      spawn-in-``spawn``, reap-in-``release`` shape
      (ProcessReplicaSpawner's discipline);
    - or, for a spawn at MODULE scope only, the module's top level
      does (a script's spawn-then-join main block).

    ``subprocess.run``/``check_call``/``check_output`` self-reap and
    are never flagged. Evidence in an UNRELATED sibling function does
    not count, and module-level evidence never excuses a spawn inside
    a function or class: a ``wait`` on a different child in a
    different scope is exactly the false comfort that leaks the
    zombie.
    """
    evidence_fns: Set[int] = set()
    evidence_cls: Set[int] = set()
    module_evidence = [False]
    spawns: List[Tuple[ast.Call, Tuple[int, ...], Optional[int],
                       str]] = []

    def _classify(call: ast.Call, fns: Tuple[int, ...],
                  cls: Optional[int]) -> None:
        func = call.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        if attr in _REAP_ATTRS:
            evidence_fns.update(fns)
            if cls is not None:
                evidence_cls.add(cls)
            if not fns and cls is None:
                module_evidence[0] = True
            return
        d = _dotted(func, file) or ""
        if d == "subprocess.Popen" or d.endswith(".subprocess.Popen"):
            spawns.append((call, fns, cls, "subprocess.Popen"))
        elif d in ("multiprocessing.Process",
                   "torch.multiprocessing.Process") \
                or d.endswith(".multiprocessing.Process"):
            spawns.append((call, fns, cls, "multiprocessing.Process"))

    def _visit(node: ast.AST, fns: Tuple[int, ...],
               cls: Optional[int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns = fns + (id(node),)
        elif isinstance(node, ast.ClassDef):
            cls = id(node)
        if isinstance(node, ast.Call):
            _classify(node, fns, cls)
        for child in ast.iter_child_nodes(node):
            _visit(child, fns, cls)

    _visit(file.tree, (), None)
    for call, fns, cls, label in spawns:
        if any(f in evidence_fns for f in fns):
            continue
        if cls is not None and cls in evidence_cls:
            continue
        # module-level evidence only excuses module-scope spawns: a
        # top-level join() must not grant file-wide amnesty to spawns
        # buried in unrelated functions
        if module_evidence[0] and not fns and cls is None:
            continue
        out.append(Finding(
            file.path, call.lineno, call.col_offset, "GL118",
            f"child-process spawn ({label}) with no reaping evidence "
            "in scope — nothing here ever wait/join/kill/terminates "
            "the child: every crash path leaks a zombie that "
            "outlives the run holding ports and file locks; reap it "
            "in the same scope (the graftscale spawner discipline: "
            "wait with a deadline, then kill LOUDLY), or use "
            "subprocess.run, which self-reaps"))


_SEND_ATTRS = {"sendall", "sendmsg"}


def _check_copy_on_send(file: _File, out: List[Finding]):
    """GL122 — copy-on-send in wire paths: the throughput class
    graftlink exists to kill. Inside any scope (function chain or
    module top level) that also calls ``.sendall``/``.sendmsg``, an
    assembly copy of the outgoing payload is flagged:

    - ``arr.tobytes()`` — a full copy of an array that could ride as
      a zero-copy ``memoryview`` segment of a scatter-gather send;
    - ``b"".join(...)`` (any bytes-literal ``.join``) — frame
      assembly by concatenation;
    - ``bytes(buf)`` with a non-constant argument — materializing a
      buffer that ``sendmsg`` would take as-is.

    A scope with no send call is never flagged: builders like
    ``pack_frame`` legitimately assemble (tests, faults, fallbacks
    consume the assembled representation); the copy only costs when
    it sits on the send path itself.
    """
    send_fns: Set[int] = set()
    module_send = [False]
    copies: List[Tuple[ast.Call, Tuple[int, ...], str]] = []

    def _classify(call: ast.Call, fns: Tuple[int, ...]) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr in _SEND_ATTRS:
                send_fns.update(fns)
                if not fns:
                    module_send[0] = True
                return
            if func.attr == "tobytes" and not call.args:
                copies.append((call, fns,
                               ".tobytes() copies the whole array"))
                return
            if (func.attr == "join"
                    and isinstance(func.value, ast.Constant)
                    and isinstance(func.value.value,
                                   (bytes, bytearray))):
                copies.append((call, fns,
                               "b''.join assembles the frame by "
                               "concatenation"))
                return
        elif (isinstance(func, ast.Name) and func.id == "bytes"
                and len(call.args) == 1 and not call.keywords
                and not isinstance(call.args[0], ast.Constant)):
            copies.append((call, fns,
                           "bytes(...) materializes the buffer"))

    def _visit(node: ast.AST, fns: Tuple[int, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns = fns + (id(node),)
        if isinstance(node, ast.Call):
            _classify(node, fns)
        for child in ast.iter_child_nodes(node):
            _visit(child, fns)

    _visit(file.tree, ())
    for call, fns, label in copies:
        on_send_path = (any(f in send_fns for f in fns)
                        or (not fns and module_send[0]))
        if not on_send_path:
            continue
        out.append(Finding(
            file.path, call.lineno, call.col_offset, "GL122",
            f"copy-on-send in a wire path ({label}) in a scope that "
            "also sends — the payload is duplicated in Python right "
            "before the kernel takes it, a second multi-MB copy per "
            "RPC at KV-block size; hand the header prefix plus raw "
            "memoryview segments to a scatter-gather sendmsg "
            "instead (the graftlink discipline: nothing on the send "
            "path is assembled)"))


def _check_jit_in_loop(file: _File, out: List[Finding]):
    """GL105: jax.jit(...) lexically inside a for/while body."""
    loops: List[ast.AST] = [n for n in ast.walk(file.tree)
                            if isinstance(n, (ast.For, ast.AsyncFor,
                                              ast.While))]
    for loop in loops:
        stack = [n for part in ("body", "orelse")
                 for n in getattr(loop, part, [])]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # a def in a loop body runs on call, not per iter
            if isinstance(node, ast.Call):
                d = _dotted(node.func, file)
                if _is_jit(d) or (
                        d == "functools.partial" and node.args
                        and _is_jit(_dotted(node.args[0], file))):
                    out.append(Finding(
                        file.path, node.lineno, node.col_offset, "GL105",
                        "jax.jit constructed inside a loop body — each "
                        "iteration builds a fresh wrapper with an empty "
                        "trace cache (recompiles every pass); hoist the "
                        "jit out of the loop"))
            stack.extend(ast.iter_child_nodes(node))


def _collect_axes(files: Sequence[_File]) -> Set[str]:
    axes: Set[str] = set()
    for file in files:
        for node in ast.walk(file.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name)
                            and t.id.upper().endswith("_AXIS")
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        axes.add(node.value.value)
            elif isinstance(node, ast.Call):
                d = _dotted(node.func, file)
                if d and d.split(".")[-1] == "Mesh" and len(node.args) >= 2:
                    names = _const_str_seq(node.args[1])
                    if names:
                        axes.update(names)
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        names = _const_str_seq(kw.value)
                        if names:
                            axes.update(names)
                    elif (kw.arg in _AXIS_KWARGS
                          and isinstance(kw.value, ast.Constant)
                          and isinstance(kw.value.value, str)):
                        axes.add(kw.value.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                pos = a.posonlyargs + a.args
                for arg, dflt in zip(pos[len(pos) - len(a.defaults):],
                                     a.defaults):
                    if (arg.arg in _AXIS_KWARGS
                            and isinstance(dflt, ast.Constant)
                            and isinstance(dflt.value, str)):
                        axes.add(dflt.value)
                for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
                    if (dflt is not None and arg.arg in _AXIS_KWARGS
                            and isinstance(dflt, ast.Constant)
                            and isinstance(dflt.value, str)):
                        axes.add(dflt.value)
    return axes


def _check_pspec_axes(file: _File, axes: Set[str], out: List[Finding]):
    """GL109: string axis in a PartitionSpec literal must be declared."""
    if not axes:
        return
    for node in ast.walk(file.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func, file)
        if not d or d.split(".")[-1] != "PartitionSpec":
            continue
        for arg in node.args:
            for el in ([arg] if not isinstance(arg, (ast.Tuple, ast.List))
                       else arg.elts):
                if (isinstance(el, ast.Constant)
                        and isinstance(el.value, str)
                        and el.value not in axes):
                    out.append(Finding(
                        file.path, node.lineno, node.col_offset, "GL109",
                        f"PartitionSpec axis {el.value!r} is not an axis "
                        f"of any mesh declared in the linted files "
                        f"(known: {sorted(axes)}) — typo'd axes fail "
                        "far away, at sharding time"))


# ------------------------------------------------------------ top level

def analyze_files(paths: Sequence[str],
                  package_parent: Optional[str] = None) -> List[Finding]:
    """Lint ``paths`` (Python files) as one closed world: jit scopes
    propagate across files through intra-package imports resolved
    relative to ``package_parent`` (the directory CONTAINING the
    package). Returns findings sorted by (path, line)."""
    files: List[_File] = []
    findings: List[Finding] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            f = _collect_file(path, src, _modkey_for(path, package_parent))
        except SyntaxError as e:
            findings.append(Finding(path, e.lineno or 0, 0, "GL000",
                                    f"does not parse: {e.msg}"))
            continue
        _fill_owners(f)
        files.append(f)

    index: Dict[Tuple[Tuple[str, ...], str], _Func] = {}
    for f in files:
        for name, fn in f.by_name.items():
            index.setdefault((f.modkey, name), fn)

    seeds = _scan_roots(files, index)
    _propagate(files, index, seeds)

    axes = _collect_axes(files)
    for f in files:
        _check_jit_in_loop(f, findings)
        _check_pspec_axes(f, axes, findings)
        _check_swallowed_except(f, findings)
        _check_unpaired_trace(f, findings)
        _check_signal_discard(f, findings)
        _check_blocking_socket(f, findings)
        _check_spawn_reap(f, findings)
        _check_copy_on_send(f, findings)
        _check_unsynced_timing(f, findings)
        for fn in f.funcs:
            if fn.jit_scoped:
                _check_jit_scoped_body(fn, findings)
                _check_traced_branches(fn, findings)
                _check_traced_bool_coercion(fn, findings)
                _check_static_defaults(fn, findings)
                _check_missing_donate(fn, findings)
                _check_ctrl_body_scalars(fn, findings)
    # graftrace: the GL119/GL120/GL121 concurrency pass shares this
    # file set and index (imported here to avoid a module cycle)
    from .concurrency import check_concurrency
    check_concurrency(files, index, findings)
    # graftlife: the GL123/GL124/GL125 resource-lifecycle pass —
    # same file set and index, same late import
    from .lifecycle import check_lifecycle
    check_lifecycle(files, index, findings)

    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings
